"""Elastic membership: roster arithmetic as pure units, handoff
idempotency, barrier renegotiation — and the in-process
kill-a-server / join-a-worker integration flows.

The pure tests need NO sockets: stripe-plan derivation, wire layouts
and state restriping are deterministic functions of the roster
(mxnet_tpu/membership.py), and the server-side handoff/barrier
machinery is driven through ``KVStoreServer._handle`` directly.  The
integration tests run real in-process servers and assert the
acceptance property: kill a server mid-job and the surviving roster
finishes with EXACTLY the uninterrupted values (SGD arithmetic is
order-independent for the integer/power-of-two values used here)."""
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faultinject, membership, profiler
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore_server import KVStoreServer

SHAPE = (3, 4)


# ---------------------------------------------------------------------------
# pure roster arithmetic (no sockets)
# ---------------------------------------------------------------------------
def test_stripe_plan_deterministic_across_generations():
    """The plan is a pure function of (key, shape, n, bound): two
    generations with the same server count derive identical plans, and
    every worker derives the same plan with no coordination."""
    for n in (1, 2, 3, 5):
        a = membership.stripe_plan("w", (10, 4), n, 16)
        b = membership.stripe_plan("w", (10, 4), n, 16)
        assert a == b
    assert membership.stripe_plan("w", (10, 4), 1, 16) is None
    assert membership.stripe_plan("w", (10, 4), 2, 1000) is None  # small
    plan = membership.stripe_plan("w", (10, 4), 2, 16)
    assert plan == [0, 5, 10]
    plan3 = membership.stripe_plan("w", (10, 4), 3, 16)
    assert plan3[0] == 0 and plan3[-1] == 10 and len(plan3) == 4
    # more servers than rows: parts cap at the row count
    tall = membership.stripe_plan("w", (2, 1000), 5, 16)
    assert tall == [0, 1, 2]


def test_stripe_plan_matches_worker_derivation(monkeypatch):
    """kvstore's _stripe_plan delegates to membership.stripe_plan — the
    two can never diverge (handoff planning depends on it)."""
    srv = KVStoreServer(server_id=0, num_workers=1)
    srv.start_background()
    try:
        monkeypatch.setenv("MXT_SERVER_URIS", f"127.0.0.1:{srv.port}")
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        monkeypatch.setenv("DMLC_WORKER_ID", "0")
        monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "16")
        kv = mx.kv.create("dist_async")
        assert kv._stripe_plan("w", (10, 4)) == membership.stripe_plan(
            "w", (10, 4), 1, 16)
        kv.close(stop_servers=True)
    finally:
        srv.stop()


def test_wire_layout_owner_stability_under_eviction():
    """Removal preserves the survivors' relative order; a key whose
    every wire key keeps its URI and row span is NOT moved by the
    bump.  (The reverse — crc routing moving keys between survivors —
    is expected and is exactly what plan_handoff detects.)"""
    servers2 = ["hostA:1", "hostB:2"]
    servers1 = ["hostA:1"]
    lay2 = membership.wire_layout("w", (10, 4), servers2, 16)
    assert set(lay2) == {"w@s0", "w@s1"}
    spans = sorted((lo, hi) for _u, lo, hi in lay2.values())
    assert spans == [(0, 5), (5, 10)]
    lay1 = membership.wire_layout("w", (10, 4), servers1, 16)
    assert lay1 == {"w": ("hostA:1", 0, 10)}  # unstriped on one server
    # a small key: moved only if its crc owner changed
    small = membership.wire_layout("k", (2, 2), servers2, 1000)
    (uri, lo, hi), = small.values()
    assert (lo, hi) == (0, 2) and uri in servers2


def test_sparse_route_rebases_and_skips_untouched_stripes():
    """sparse_route is pure arithmetic over (plan, indices): local ids
    are rebased to the stripe's row 0, positions index the caller's
    row block, and stripes the batch never touched simply don't appear
    — that silence IS the sparse wire win."""
    plan = membership.stripe_plan("emb", (100, 4), 2, 8)
    assert plan == [0, 50, 100]
    idx = np.array([3, 49, 50, 99], dtype=np.int64)
    routed = membership.sparse_route(plan, idx)
    assert [(i, list(loc), list(pos)) for i, loc, pos in routed] == [
        (0, [3, 49], [0, 1]), (1, [0, 49], [2, 3])]
    # a batch confined to one stripe names only that stripe
    routed = membership.sparse_route(plan, np.array([60, 70], np.int64))
    assert [i for i, _l, _p in routed] == [1]
    # empty batch routes nowhere; determinism across calls
    assert membership.sparse_route(plan, np.zeros(0, np.int64)) == []
    again = membership.sparse_route(plan, idx)
    for (i, l, p), (j, l2, p2) in zip(routed, routed):
        assert i == j and list(l) == list(l2) and list(p) == list(p2)
    del again


def test_moved_row_spans_names_exactly_the_moved_rows():
    """moved_row_spans is the arithmetic behind per-row residual
    invalidation: a roster bump must name exactly the half-open row
    spans whose owning server changed — merged and sorted — and an
    identical roster names none."""
    two = ["hostA:1", "hostB:2"]
    one = ["hostA:1"]
    spans = membership.moved_row_spans("emb", (100, 4), two, one, 8)
    lay2 = membership.wire_layout("emb", (100, 4), two, 8)
    # rows hostA already owned stay put; hostB's rows all move
    kept = [(lo, hi) for uri, lo, hi in lay2.values() if uri == "hostA:1"]
    lost = sorted((lo, hi) for uri, lo, hi in lay2.values()
                  if uri == "hostB:2")
    assert spans == lost
    for lo, hi in kept:
        assert all(hi <= s_lo or lo >= s_hi for s_lo, s_hi in spans)
    # identical roster: nothing moved
    assert membership.moved_row_spans("emb", (100, 4), two, two, 8) == []
    # spans are merged, sorted, half-open, in range
    spans3 = membership.moved_row_spans("emb", (100, 4), two,
                                        ["hostC:3"], 8)
    assert spans3 == [(0, 100)]  # every owner changed -> one merged span
    for lo, hi in spans3:
        assert 0 <= lo < hi <= 100


def test_plan_handoff_flags_only_moved_keys():
    servers2 = ["hostA:1", "hostB:2"]
    servers1 = ["hostA:1"]
    shapes = {"big": (10, 4), "smallA": (2, 2), "smallB": (2, 2)}
    # find one small key on each server under the 2-server layout
    owners = {k: next(iter(membership.wire_layout(
        k, shapes[k], servers2, 16).values()))[0]
        for k in ("smallA", "smallB")}
    moved = set(membership.plan_handoff(shapes, servers2, servers1, 16))
    assert "big" in moved          # re-striped 2 -> 1
    for k, owner in owners.items():
        if owner == "hostA:1":
            assert k not in moved  # survivor kept it: nothing to do
        else:
            assert k in moved      # dead server owned it
    # identical roster: nothing moves
    assert membership.plan_handoff(shapes, servers2, servers2, 16) == []


def test_restripe_value_slices_follow_new_layout():
    val = np.arange(40, dtype=np.float32).reshape(10, 4)
    parts = membership.restripe_value("w", val, ["a:1", "b:2"], 16)
    assert {wk for wk, _u, _v in parts} == {"w@s0", "w@s1"}
    got = np.concatenate([v for _wk, _u, v in sorted(parts)], axis=0)
    np.testing.assert_array_equal(got, val)
    whole = membership.restripe_value("w", val, ["a:1"], 16)
    assert len(whole) == 1 and whole[0][0] == "w"
    np.testing.assert_array_equal(whole[0][2], val)


def test_restripe_states_exact_merge():
    """Elementwise (momentum-shaped) state restripes EXACTLY: merge the
    old stripes along axis 0, re-slice along the new plan."""
    mom = np.arange(40, dtype=np.float32).reshape(10, 4)
    old_plan = [0, 5, 10]
    per_wire = {"w@s0": (mom[0:5],), "w@s1": (mom[5:10],)}
    # 2 stripes -> whole key
    out = membership.restripe_states("w", per_wire, old_plan, None)
    np.testing.assert_array_equal(out["w"][0], mom)
    # 2 stripes -> 3 stripes
    new_plan = membership.stripe_plan("w", (10, 4), 3, 16)
    out3 = membership.restripe_states("w", per_wire, old_plan, new_plan)
    got = np.concatenate(
        [out3[f"w@s{i}"][0] for i in range(len(new_plan) - 1)], axis=0)
    np.testing.assert_array_equal(got, mom)
    # bare-array state works too
    outb = membership.restripe_states(
        "w", {"w@s0": mom[0:5], "w@s1": mom[5:10]}, old_plan, None)
    np.testing.assert_array_equal(outb["w"], mom)
    # stateless () states stay empty, never invent arrays
    oute = membership.restripe_states(
        "w", {"w@s0": (), "w@s1": ()}, old_plan, None)
    assert oute["w"] == ()
    # a PARTIAL snapshot cannot be restriped soundly: {} (fresh state)
    assert membership.restripe_states(
        "w", {"w@s0": (mom[0:5],)}, old_plan, None) == {}
    # non-row-decomposable state degrades to None per new stripe
    outn = membership.restripe_states(
        "w", {"w@s0": 3.5, "w@s1": 4.5}, old_plan, None)
    assert outn == {"w": None}


def test_coordinator_uri_and_successor_deterministic():
    """Succession is pure arithmetic over the ordered roster: every
    observer of the same (roster, dead set) elects the SAME successor,
    across generations, with no votes — and the successor IS the
    coordinator of the post-eviction roster (removal preserves
    order)."""
    servers = ["a:1", "b:2", "c:3"]
    assert membership.coordinator_uri(servers) == "a:1"
    assert membership.coordinator_uri([]) is None
    assert membership.coordinator_uri(None) is None
    for _ in range(3):     # deterministic: same answer every evaluation
        assert membership.elect_successor(servers, {"a:1"}) == "b:2"
    assert membership.elect_successor(servers, {"a:1", "b:2"}) == "c:3"
    assert membership.elect_successor(servers, set(servers)) is None
    assert membership.elect_successor(servers, set()) == "a:1"
    assert membership.elect_successor(None, set()) is None
    # composition: evicting the coordinator from the ledger yields a
    # roster whose slot 0 is exactly the elected successor, so every
    # observer converges on one leader with no coordination
    m = membership.MembershipCoordinator(servers, [0])
    m.report_dead_server("a:1")
    assert membership.coordinator_uri(m.roster().servers) \
        == membership.elect_successor(servers, {"a:1"})


def test_rebuild_ledger_merge_rules():
    """The failover rebuild is a pure merge: generation resumes at
    max(reported)+1, duplicate reports are idempotent, reports never
    add servers the successor's roster view lacks, and the snapshot
    bank never invents missing state."""
    mom = np.arange(40, dtype=np.float32).reshape(10, 4)
    reports = [
        {"uri": "b:2", "generation": 3, "beat_seq": 7, "keys": ["w@s1"]},
        {"uri": "c:3", "generation": 5, "beat_seq": 2, "keys": []},
    ]
    snaps = {"a:1": (4, {"store": {}, "states": {"w@s0": (mom[0:5],)}})}
    m = membership.rebuild_ledger(["b:2", "c:3"], [0, 1], reports, snaps)
    assert m.generation == 6           # max(reported) + 1
    assert m.failovers == 1
    assert m.roster().servers == ("b:2", "c:3")
    assert m.roster().workers == (0, 1)
    # duplicate reports (every survivor races to report) are idempotent
    m2 = membership.rebuild_ledger(["b:2", "c:3"], [0, 1],
                                   reports + reports, snaps)
    assert m2.generation == 6
    # an unknown reporter contributes its generation only — it re-joins
    # through the ordinary path, never grandfathered into slot math
    m3 = membership.rebuild_ledger(["b:2"], [0], reports, None)
    assert m3.generation == 6 and m3.roster().servers == ("b:2",)
    # malformed reports are skipped, not fatal (a half-written reply
    # from a dying peer must not block the succession)
    assert membership.rebuild_ledger(
        ["b:2"], [0], [{"generation": "x"}, None, {}], None
    ).generation == 1
    # missing snapshot REFUSAL: the bank answers only what was banked...
    assert m.snapshot_of("a:1") == snaps["a:1"][1]
    assert m.snapshot_of("never-banked:9") is None
    # ...so a restripe over an unbanked dead stripe refuses ({} = the
    # optimizer re-creates fresh state) instead of inventing momentum
    per_wire = dict(m.snapshot_of("a:1")["states"])   # w@s0 only
    assert membership.restripe_states("w", per_wire, [0, 5, 10],
                                      None) == {}


def test_coordinator_idempotent_mutations():
    m = membership.MembershipCoordinator(["a:1", "b:2"], [0, 1])
    assert m.generation == 0
    g1 = m.report_dead_server("b:2")
    assert g1 == 1 and m.evictions == 1
    # duplicate reports (every worker races to report) do NOT re-bump
    assert m.report_dead_server("b:2") == 1 and m.evictions == 1
    assert m.roster().servers == ("a:1",)
    # the LAST server (the coordinator itself) can never be removed
    with pytest.raises(RuntimeError, match="last server"):
        m.report_dead_server("a:1")
    # joins bump once, re-joins don't
    assert m.join_server("c:3") == 2
    assert m.join_server("c:3") == 2
    assert m.roster().servers == ("a:1", "c:3")   # order preserved
    assert m.join_worker(2) == 3
    assert m.join_worker(2) == 3
    assert m.evict_worker(1) == 4
    assert m.evict_worker(1) == 4 and m.evictions == 2
    assert m.roster().workers == (0, 2)


def test_coordinator_snapshots_outlive_eviction():
    m = membership.MembershipCoordinator(["a:1", "b:2"], [0])
    m.note_server_beat("b:2", seq=1, snapshot={"store": {"k": 1}})
    m.note_server_beat("b:2", seq=3, snapshot={"store": {"k": 3}})
    m.note_server_beat("b:2", seq=2, snapshot={"store": {"k": 2}})  # stale
    m.report_dead_server("b:2")
    snap = m.snapshot_of("b:2")
    assert snap == {"store": {"k": 3}}   # newest seq wins, survives death
    assert m.snapshot_of("nope:0") is None


def test_coordinator_silent_server_detection():
    m = membership.MembershipCoordinator(["a:1", "b:2", "c:3"], [0])
    # never heard from = never declared dead (may still be starting)
    assert m.silent_servers(0.01) == []
    m.note_server_beat("b:2")
    time.sleep(0.05)
    assert m.silent_servers(0.01) == ["b:2"]
    assert m.silent_servers(0) == []     # timeout 0 disables


# ---------------------------------------------------------------------------
# server-side handoff + barrier machinery, driven with NO sockets
# ---------------------------------------------------------------------------
def _mk_server(**kw):
    kw.setdefault("num_workers", 1)
    srv = KVStoreServer(server_id=0, **kw)
    srv._listener.close()    # never serving: pure _handle driving
    return srv


def test_handoff_idempotent_under_duplicate_delivery():
    """Quorum re-push: every worker sends the same handoff; the FIRST
    per (wire key, generation) applies, duplicates ack as no-ops, a
    stale generation never regresses the key, a newer one re-applies."""
    srv = _mk_server(elastic=True)
    v1 = np.full(SHAPE, 7.0, np.float32)
    assert srv._handle(("handoff", 3, "w", v1, "w")) is True
    assert srv._handle(("handoff", 3, "w",
                        np.full(SHAPE, 9.0, np.float32), "w")) is False
    np.testing.assert_array_equal(srv._store["w"].asnumpy(), 7.0)
    # stale generation: ignored
    assert srv._handle(("handoff", 2, "w",
                        np.full(SHAPE, 1.0, np.float32), "w")) is False
    np.testing.assert_array_equal(srv._store["w"].asnumpy(), 7.0)
    # newer generation: re-applies
    assert srv._handle(("handoff", 4, "w",
                        np.full(SHAPE, 2.0, np.float32), "w")) is True
    np.testing.assert_array_equal(srv._store["w"].asnumpy(), 2.0)


def test_handoff_purges_stale_wire_forms():
    """The first handoff of a logical key in a generation deletes the
    key's OLD wire forms (stripe keys from the previous layout) and
    their optimizer state, so a re-striped layout leaves no orphans."""
    from mxnet_tpu import optimizer as opt
    srv = _mk_server(elastic=True)
    srv._updater = opt.get_updater(opt.SGD(learning_rate=0.5,
                                           momentum=0.9))
    srv._handle(("init", "w@s1", np.zeros((5, 4), np.float32)))
    srv._handle(("push", "w@s1", np.ones((5, 4), np.float32)))
    assert "w@s1" in srv._store and srv._updater.states
    srv._handle(("handoff", 1, "w",
                 np.zeros((10, 4), np.float32), "w"))
    assert "w@s1" not in srv._store and "w" in srv._store
    assert "w@s1" not in srv._updater.states
    # an in-flight OLD-layout push arriving post-purge fails loudly (the
    # pusher's own repair re-applies it from its push log)
    with pytest.raises(Exception, match="uninitialized"):
        srv._apply_push("w@s1", np.ones((5, 4), np.float32))


def test_handoff_state_idempotent_and_installed():
    from mxnet_tpu import optimizer as opt
    srv = _mk_server(elastic=True)
    srv._updater = opt.get_updater(opt.SGD(learning_rate=0.5,
                                           momentum=0.9))
    mom = np.full((10, 4), 0.25, np.float32)
    assert srv._handle(("handoff_state", 1, "w", (mom,), "w")) is True
    assert srv._handle(("handoff_state", 1, "w", (mom * 9,), "w")) is False
    st = srv._updater.states["w"]
    np.testing.assert_array_equal(np.asarray(st[0].asnumpy()), mom)
    # None clears the slot (the optimizer re-creates fresh state)
    assert srv._handle(("handoff_state", 2, "w", None, "w")) is True
    assert "w" not in srv._updater.states


def test_stale_coordinator_envelopes_rejected():
    """After a failover the successor's ledger resumes at
    max(reported)+1, so envelopes stamped by the dead coordinator's
    epoch — handoffs a worker still converged on the stale roster keeps
    re-sending — are refused by the EXISTING per-generation staleness
    checks; no new wire validation was needed.  Socket-free."""
    srv = _mk_server(elastic=True)
    srv._membership = membership.rebuild_ledger(
        [srv.uri], [0], [{"uri": "dead:1", "generation": 4,
                          "beat_seq": 9, "keys": ["w"]}], None)
    srv._promoted = True
    gen = srv._membership.generation
    assert gen == 5
    # the post-failover roster (and generation) is what roster ops serve
    assert srv._handle(("roster_get",)) == (5, [srv.uri], [0])
    # barrier replies carry the resumed generation, so workers discover
    # the succession at their next sync point for free
    assert srv._handle(("barrier",), rank=0) == 5
    # a post-failover handoff at the rebuilt generation lands...
    v_new = np.full(SHAPE, 7.0, np.float32)
    assert srv._handle(("handoff", gen, "w", v_new, "w")) is True
    # ...and every stale-epoch envelope is rejected, values untouched
    for stale in (gen - 1, gen - 3):
        assert srv._handle(("handoff", stale, "w",
                            np.zeros(SHAPE, np.float32), "w")) is False
    np.testing.assert_array_equal(srv._store["w"].asnumpy(), 7.0)
    srv._stop.set()


def test_ledger_report_names_generation_and_keys():
    """Every elastic server answers ledger_report — the rebuild sweep's
    input: last-known generation, beat seq and the live key set."""
    srv = _mk_server(elastic=True)
    srv._handle(("init", "w", np.zeros(SHAPE, np.float32)))
    srv._known_gen = 3
    srv._beat_seq = 11
    r = srv._handle(("ledger_report",))
    assert r["uri"] == srv.uri and r["keys"] == ["w"]
    assert r["beat_seq"] == 11 and r["generation"] == 3
    # a coordinator reports its LEDGER generation, not the passive view
    srv._get_membership().join_server("x:9")
    assert srv._handle(("ledger_report",))["generation"] \
        == srv._get_membership().generation
    srv._stop.set()


def test_join_reply_carries_cohort_barrier_floor():
    """A joining worker's reply carries the cohort's barrier release
    floor (computed over ARRIVED ranks only, so two simultaneous
    joiners both align to the real cohort, not to each other's zero):
    the joiner seeds its raw sequence there, keeping client sequences
    globally aligned — the invariant that lets a failover successor
    pair arrivals with EMPTY barrier state."""
    srv = _mk_server(num_workers=1, elastic=True)
    assert srv._handle(("barrier", 1), rank=0) == 0   # cohort runs...
    assert srv._handle(("barrier", 2), rank=0) == 0
    reply = srv._handle(("roster_join", "worker", 1))
    assert len(reply) == 4 and reply[3] == 2          # floor = done(0)
    # a second concurrent joiner sees the SAME floor (rank 1 has not
    # arrived yet and must not drag it to zero)
    assert srv._handle(("roster_join", "worker", 2))[3] == 2
    # the seeded joiner's first arrival (floor+1) parks until the
    # cohort reaches the same rendezvous
    done = []

    def joiner():
        try:
            done.append(srv._handle(("barrier", 3), rank=1))
        except Exception as exc:  # noqa: BLE001 — surfaced via assert
            done.append(exc)

    t = threading.Thread(target=joiner, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not done
    srv._handle(("roster_leave", "worker", 2))        # 2 never arrives
    srv._handle(("barrier", 3), rank=0)
    t.join(timeout=5)
    assert not t.is_alive() and isinstance(done[0], int)
    srv._stop.set()


def test_rejoin_realignment_is_one_shot_client_adopted():
    """A (re-)joined rank arriving with a drifted sequence is realigned
    to the cohort's pending rendezvous ONE-SHOT: the offset rides the
    barrier reply so the client adopts the effective sequence — there
    is deliberately NO server-side offset state, which is why a
    failover successor can start with an empty barrier map and still
    pair every arrival."""
    srv = _mk_server(num_workers=1, elastic=True)
    assert srv._handle(("barrier", 1), rank=0) == 0
    assert srv._handle(("barrier", 2), rank=0) == 0
    srv._handle(("roster_join", "worker", 1))
    done = []

    def drifted():
        try:
            done.append(srv._handle(("barrier", 1), rank=1))
        except Exception as exc:  # noqa: BLE001 — surfaced via assert
            done.append(exc)

    t = threading.Thread(target=drifted, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not done                  # realigned to rendezvous 3: parks
    srv._handle(("barrier", 3), rank=0)
    t.join(timeout=5)
    assert not t.is_alive()
    payload = done[0]
    assert isinstance(payload, tuple) and payload[1] == 2, payload
    # the adopted sequence keeps pairing exactly: raw 1+2+1 = 4 next
    assert srv._barrier_high[1] == 3
    srv._stop.set()


def test_fresh_client_generation_resets_barrier_sequence():
    """A trainer RESUMED against live servers barriers under the same
    rank ids but a new client nonce and a sequence restarting at 1: the
    dead predecessors' release marks must not turn the first rendezvous
    into instant no-ops (the resumed set_optimizer barrier must really
    rendezvous)."""
    srv = _mk_server(num_workers=2, elastic=False)
    srv._note_ping(0)
    srv._note_ping(1)
    results = []

    def arrive(rank, bseq, client):
        try:
            results.append(srv._handle(("barrier", bseq), rank=rank,
                                       client=client))
        except Exception as exc:  # noqa: BLE001 — surfaced via assert
            results.append(exc)

    # first client generation completes rendezvous 1
    t0 = threading.Thread(target=arrive, args=(0, 1, (0, "A")),
                          daemon=True)
    t0.start()
    time.sleep(0.1)
    arrive(1, 1, (1, "A"))
    t0.join(timeout=5)
    assert len(results) == 2 and not any(
        isinstance(r, Exception) for r in results)
    # the job restarts: NEW nonces, sequences back at 1 — rank 0 must
    # PARK (no instant release off the stale done marks)...
    results.clear()
    t2 = threading.Thread(target=arrive, args=(0, 1, (0, "B")),
                          daemon=True)
    t2.start()
    time.sleep(0.2)
    assert not results, "fresh client released without a rendezvous"
    # ...until the other resumed rank arrives
    arrive(1, 1, (1, "B"))
    t2.join(timeout=5)
    assert len(results) == 2 and not any(
        isinstance(r, Exception) for r in results)
    srv._stop.set()


def test_dead_report_naming_live_coordinator_refused():
    """A false-positive roster_dead (the reporter's heartbeat blipped)
    that reaches the very coordinator it names is REFUSED — answering
    the report IS proof of life, and a live coordinator must never
    evict itself into a split-brain roster."""
    srv = _mk_server(elastic=True)
    m = srv._get_membership()
    m.join_server("b:2")
    with pytest.raises(Exception, match="alive"):
        srv._handle(("roster_dead", "server", srv.uri))
    assert srv.uri in m.roster().servers
    # reports naming OTHER servers keep working
    assert srv._handle(("roster_dead", "server", "b:2"))[1] == [srv.uri]
    srv._stop.set()


def test_barrier_renegotiates_with_evicted_rank(monkeypatch):
    """Elastic coordinator: a 2-worker barrier whose rank 1 was alive
    and went silent does NOT fail — rank 1 is evicted (generation
    bump), the target re-reads the live roster and rank 0 is released.
    Pure threads, no sockets."""
    srv = _mk_server(num_workers=2, elastic=True, hb_timeout=0.2)
    srv._note_ping(0)
    srv._note_ping(1)
    with srv._barrier_cv:
        srv._hb_seen[1] = time.monotonic() - 99.0   # long silent
    t0 = time.monotonic()
    gen = srv._handle(("barrier",), rank=0)
    assert time.monotonic() - t0 < 5.0
    assert gen == srv._get_membership().generation >= 1
    assert srv._get_membership().roster().workers == (0,)
    assert profiler.channel_counts().get("kvstore.worker_eviction", 0) >= 1
    # the evicted rank was merely slow: arriving at the next barrier
    # RE-ADMITS it (join, another bump) instead of corrupting the count.
    # Stretch the silence budget so phase 2 tests re-admission alone,
    # not another round of evictions racing the parked waiters.
    srv._hb_timeout = 60.0
    done = []

    def late_rank1():
        try:
            done.append(srv._handle(("barrier",), rank=1))
        except Exception as exc:  # noqa: BLE001 — surfaced via assert
            done.append(exc)

    t = threading.Thread(target=late_rank1, daemon=True)
    t.start()
    time.sleep(0.3)          # rank 1 parks: roster is {0, 1} again
    srv._note_ping(1)
    srv._handle(("barrier",), rank=0)
    t.join(timeout=5)
    assert not t.is_alive() and isinstance(done[0], int)
    assert srv._get_membership().roster().workers == (0, 1)
    srv._stop.set()


def test_static_barrier_error_names_heartbeat_age():
    """Satellite: the non-elastic barrier failure carries per-rank
    last-heartbeat AGE — evidence, not just rank ids."""
    srv = _mk_server(num_workers=2, elastic=False, hb_timeout=0.2)
    srv._note_ping(0)
    srv._note_ping(1)
    with srv._barrier_cv:
        srv._hb_seen[1] = time.monotonic() - 42.0
    with pytest.raises(RuntimeError) as ei:
        srv._handle(("barrier",), rank=0)
    msg = str(ei.value)
    assert "[1]" in msg and "arrived rank(s): [0]" in msg
    assert "rank 1: last heartbeat" in msg and "ago" in msg
    srv._stop.set()


# ---------------------------------------------------------------------------
# faultinject: the process-level kill point
# ---------------------------------------------------------------------------
def test_kill_process_after_acks_fires_at_exact_count(monkeypatch):
    """SIGKILL after exactly n enveloped replies (the trigger is
    monkeypatched so the test process survives); heartbeat pings never
    advance the count."""
    fired = []
    monkeypatch.setattr(faultinject, "_sigkill_self",
                        lambda: fired.append(True))
    faultinject.reset()
    srv = KVStoreServer(server_id=0, num_workers=1)
    srv.start_background()
    try:
        monkeypatch.setenv("MXT_SERVER_URIS", f"127.0.0.1:{srv.port}")
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        monkeypatch.setenv("DMLC_WORKER_ID", "0")
        monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_INTERVAL", "0.05")
        with faultinject.kill_process_after_acks(3):
            kv = mx.kv.create("dist_async")
            kv.init("a", mx.nd.ones(SHAPE))        # ack 1
            out = mx.nd.zeros(SHAPE)
            kv.pull("a", out=out)                  # ack 2
            time.sleep(0.3)                        # heartbeats flow...
            assert not fired                       # ...and don't count
            kv.pull("a", out=out)                  # ack 3 -> kill
            deadline = time.time() + 5
            while not fired and time.time() < deadline:
                time.sleep(0.01)
            assert fired and faultinject.stats()["kills_fired"] == 1
        kv.close(stop_servers=True)
    finally:
        faultinject.reset()
        srv.stop()


def test_kill_process_env_arming(monkeypatch):
    """MXNET_FI_KILL_PROCESS_AFTER / MXNET_FI_ONLY_SERVER arm the plan
    from the environment (the launcher-spawned-process path), and the
    server-id filter keeps the plan off other shards."""
    faultinject.reset()
    try:
        faultinject.configure(kill_process_after=2, only_server=1)
        monkeypatch.setenv("DMLC_SERVER_ID", "0")
        faultinject.server_replied()
        faultinject.server_replied()
        faultinject.server_replied()
        assert faultinject.stats()["kills_fired"] == 0   # wrong server id
        monkeypatch.setattr(faultinject, "_sigkill_self", lambda: None)
        monkeypatch.setenv("DMLC_SERVER_ID", "1")
        faultinject.server_replied()
        faultinject.server_replied()
        assert faultinject.stats()["kills_fired"] == 1
    finally:
        faultinject.reset()


def test_kill_on_beat_seq_fires_at_exact_beat(monkeypatch):
    """The beat-boundary SIGKILL point: fires exactly when the armed
    beat number is reached, once (the deterministic way to kill a
    COORDINATOR, whose enveloped-ack ordering is timing-dependent)."""
    fired = []
    monkeypatch.setattr(faultinject, "_sigkill_self",
                        lambda: fired.append(True))
    faultinject.reset()
    try:
        faultinject.configure(kill_on_beat_seq=3)
        faultinject.server_beat(1)
        faultinject.server_beat(2)
        assert not fired
        faultinject.server_beat(3)
        assert fired and faultinject.stats()["kills_fired"] == 1
        faultinject.server_beat(4)          # fired once, stays disarmed
        assert len(fired) == 1
    finally:
        faultinject.reset()


def test_only_coordinator_filter_composes(monkeypatch):
    """MXNET_FI_ONLY_COORDINATOR gates the process-kill points on the
    CURRENT coordinator role — kept fresh across failovers via
    note_coordinator — composing with the ack-count and beat-seq
    points (and with MXNET_FI_ONLY_SERVER)."""
    fired = []
    monkeypatch.setattr(faultinject, "_sigkill_self",
                        lambda: fired.append(True))
    faultinject.reset()
    try:
        faultinject.configure(kill_process_after=1, only_coordinator=True)
        faultinject.note_coordinator(False)
        faultinject.server_replied()
        assert not fired          # not the coordinator: count frozen
        faultinject.note_coordinator(True)   # a failover promotes us
        faultinject.server_replied()
        assert len(fired) == 1
        # env arming covers the new knobs too
        faultinject.reset()
        monkeypatch.setenv("MXNET_FI_KILL_ON_BEAT_SEQ", "2")
        monkeypatch.setenv("MXNET_FI_ONLY_COORDINATOR", "1")
        faultinject._arm_from_env()
        faultinject.note_coordinator(False)
        faultinject.server_beat(2)
        assert len(fired) == 1    # filtered: not the coordinator
        faultinject.note_coordinator(True)
        faultinject.server_beat(3)
        assert len(fired) == 2
    finally:
        faultinject.note_coordinator(False)
        faultinject.reset()


# ---------------------------------------------------------------------------
# integration: real in-process servers, sockets, kill / join / leave
# ---------------------------------------------------------------------------
def _elastic_pair(monkeypatch, num_workers=1, snapshot_s=0.0):
    """Two elastic in-process servers sharing a roster, env wired."""
    monkeypatch.setenv("MXNET_KVSTORE_ELASTIC", "1")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX", "2")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_INITIAL_MS", "10")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX_MS", "50")
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_INTERVAL", "0.1")
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_TIMEOUT", "0.5")
    monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "16")
    monkeypatch.setenv("MXNET_KVSTORE_SNAPSHOT_S", str(snapshot_s))
    monkeypatch.setenv("DMLC_NUM_WORKER", str(num_workers))
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    srv0 = KVStoreServer(server_id=0, num_workers=num_workers,
                         elastic=True)
    srv1 = KVStoreServer(server_id=1, num_workers=num_workers,
                         elastic=True)
    uris = f"127.0.0.1:{srv0.port},127.0.0.1:{srv1.port}"
    monkeypatch.setenv("MXT_SERVER_URIS", uris)
    srv0._roster_servers = uris.split(",")
    srv1._roster_servers = uris.split(",")
    srv0._snapshot_s = snapshot_s
    srv1._snapshot_s = snapshot_s
    srv0.start_background()
    srv1.start_background()
    return srv0, srv1


def test_elastic_server_death_recovers_exact(monkeypatch):
    """THE acceptance flow, in-process: kill server 1 mid-job; the
    worker reports it, re-derives striping against the survivor, hands
    the state off from its pull cache and re-pushes the updates the
    dead server took with it — final weights EXACTLY equal the
    uninterrupted run (integer grads, power-of-two lr: the arithmetic
    is order-independent and exact in fp32)."""
    srv0, srv1 = _elastic_pair(monkeypatch)
    try:
        kv = mx.kv.create("dist_async")
        big = np.arange(40, dtype=np.float32).reshape(10, 4)
        kv.init("big", mx.nd.NDArray(big))
        kv.init("small", mx.nd.ones((2, 2)))
        kv.set_optimizer(mx.optimizer.SGD(
            learning_rate=0.125, momentum=0.0, wd=0.0, rescale_grad=1.0))
        kv.push("big", mx.nd.ones((10, 4)))
        kv.push("small", mx.nd.ones((2, 2)))
        out_b, out_s = mx.nd.zeros((10, 4)), mx.nd.zeros((2, 2))
        kv.pull("big", out=out_b)        # sync point: cache = server state
        kv.pull("small", out=out_s)
        # both servers hold live stripes before the kill
        assert len(srv0._store) >= 1 and len(srv1._store) >= 1
        gen0 = kv._roster_gen
        srv1.stop()                      # SIGKILL-equivalent: state LOST
        # next round rides the repair path end to end
        kv.push("big", mx.nd.ones((10, 4)) * 2)
        kv.push("small", mx.nd.ones((2, 2)) * 2)
        kv.barrier()
        kv.pull("big", out=out_b)
        kv.pull("small", out=out_s)
        np.testing.assert_array_equal(out_b.asnumpy(), big - 0.125 * 3)
        np.testing.assert_array_equal(out_s.asnumpy(), 1.0 - 0.125 * 3)
        assert kv._roster_gen > gen0
        assert kv._roster_servers == [f"127.0.0.1:{srv0.port}"]
        counts = profiler.channel_counts()
        assert counts.get("kvstore.roster_bump", 0) >= 1
        assert counts.get("kvstore.handoff_applied", 0) >= 1
        assert counts.get("kvstore.orphan_repush", 0) >= 1
        assert counts.get("kvstore.roster_generation", 0) >= 1
        assert profiler.channel_bytes().get("handoff", 0) > 0
        from mxnet_tpu import distributed
        assert distributed.roster_generation() >= 1
        # striping must have re-derived: the survivor now owns ALL keys
        assert "big" in srv0._store and "small" in srv0._store
        kv.close(stop_servers=True)
    finally:
        srv0.stop()
        srv1.stop()


def test_elastic_momentum_state_hands_off_via_snapshot(monkeypatch):
    """Optimizer state survives a SIGKILL through the coordinator's
    banked snapshot: a momentum-SGD run that loses server 1 and repairs
    at a QUIESCENT sync point (barrier, no pushes in flight) finishes
    EXACTLY like an uninterrupted single-server run of the same push
    sequence — momentum restriping is elementwise-exact.  (A repair
    with pushes still in flight keeps VALUES exact but may capture
    survivor-stripe momentum one update ahead — the same staleness
    async SGD already tolerates; docs/ROBUSTNESS.md.)"""
    srv0, srv1 = _elastic_pair(monkeypatch, snapshot_s=0.05)
    try:
        kv = mx.kv.create("dist_async")
        big = np.arange(40, dtype=np.float32).reshape(10, 4)
        kv.init("big", mx.nd.NDArray(big))
        opt = mx.optimizer.SGD(learning_rate=0.125, momentum=0.5,
                               wd=0.0, rescale_grad=1.0)
        kv.set_optimizer(opt)
        kv.push("big", mx.nd.ones((10, 4)))      # momentum builds
        out = mx.nd.zeros((10, 4))
        kv.pull("big", out=out)                  # sync point
        # which of big's stripes lives on (doomed) server 1
        uris = os.environ["MXT_SERVER_URIS"].split(",")
        doomed_wk = [wk for wk, (uri, _lo, _hi) in membership.wire_layout(
            "big", (10, 4), uris, 16).items() if uri == uris[1]][0]

        def banked_momentum():
            m = srv0._get_membership()
            snap = m.snapshot_of(uris[1]) if m else None
            return snap is not None and snap.get("states", {}).get(
                doomed_wk) not in (None, ())

        deadline = time.time() + 5
        while not banked_momentum() and time.time() < deadline:
            time.sleep(0.02)                 # wait for a POST-push beat
        assert banked_momentum(), "no momentum-bearing snapshot banked"
        srv1.stop()
        kv.barrier()         # quiescent repair: handoff at the sync point
        kv.push("big", mx.nd.ones((10, 4)))      # momentum compounds on
        kv.barrier()
        kv.pull("big", out=out)
        # golden: the same sequence against one never-interrupted server
        mom = np.zeros((10, 4), np.float32)
        w = big.copy()
        for _ in range(2):
            mom = 0.5 * mom - 0.125 * np.ones((10, 4), np.float32)
            w = w + mom
        np.testing.assert_array_equal(out.asnumpy(), w)
        counts = profiler.channel_counts()
        assert counts.get("kvstore.handoff_state_applied", 0) >= 1
        kv.close(stop_servers=True)
    finally:
        srv0.stop()
        srv1.stop()


def test_elastic_repair_with_compression_residuals(monkeypatch):
    """2-bit wire compression composes with a roster bump: the
    error-feedback residuals are keyed by WIRE key and shaped like the
    OLD stripe spans — a re-stripe must drop the moved keys' residuals
    (bounded pending-quantum loss, same class as compression itself)
    instead of broadcast-adding stale rows into the new layout or
    crashing on the shape mismatch."""
    srv0, srv1 = _elastic_pair(monkeypatch)
    monkeypatch.setenv("MXNET_KVSTORE_COMPRESSION", "2bit")
    monkeypatch.setenv("MXNET_KVSTORE_COMPRESSION_THRESHOLD", "0.5")
    try:
        kv = mx.kv.create("dist_async")
        big = np.zeros((10, 4), np.float32)
        kv.init("big", mx.nd.NDArray(big))
        kv.set_optimizer(mx.optimizer.SGD(
            learning_rate=1.0, momentum=0.0, wd=0.0, rescale_grad=1.0))
        # fractional grads leave nonzero residuals behind, one per
        # OLD-layout stripe key
        kv.push("big", mx.nd.ones((10, 4)) * 0.3)
        out = mx.nd.zeros((10, 4))
        kv.pull("big", out=out)
        assert any("@s" in wk for wk in kv._gc_residual)
        srv1.stop()
        kv.push("big", mx.nd.ones((10, 4)) * 0.3)   # repairs mid-flight
        kv.barrier()
        kv.pull("big", out=out)                     # completes, no crash
        # stale striped residuals are gone; the re-grown one matches the
        # new (whole-key) layout
        assert not any("@s" in wk for wk in kv._gc_residual), \
            kv._gc_residual.keys()
        if "big" in kv._gc_residual:
            assert kv._gc_residual["big"].shape == (10, 4)
        kv.close(stop_servers=True)
    finally:
        srv0.stop()
        srv1.stop()


def test_elastic_worker_join_and_graceful_leave(monkeypatch):
    """Add a worker at step N: a second worker joins mid-job (roster
    bump), barriers re-target the grown roster, and a graceful close
    deregisters it so the survivor's barriers shrink back without
    waiting out a heartbeat timeout."""
    srv0, srv1 = _elastic_pair(monkeypatch, num_workers=1)
    try:
        kv1 = mx.kv.create("dist_async")
        kv1.init("w", mx.nd.zeros(SHAPE))
        kv1.barrier()                      # 1-worker barrier: immediate
        assert kv1.num_workers == 1
        monkeypatch.setenv("DMLC_WORKER_ID", "1")
        kv2 = mx.kv.create("dist_async")   # joins: generation bump
        assert kv2.num_workers == 2
        done = []

        def w2_barrier():
            try:
                kv2.barrier()
                done.append("ok")
            except Exception as exc:  # noqa: BLE001 — surfaced below
                done.append(exc)

        t = threading.Thread(target=w2_barrier, daemon=True)
        t.start()
        time.sleep(0.2)
        assert not done                    # rank 1 is parked, waiting
        kv1.barrier()                      # rank 0 arrives -> released
        t.join(timeout=10)
        assert done == ["ok"]
        assert kv1.num_workers == 2        # barrier reply refreshed kv1
        kv2.close()                        # graceful roster_leave
        kv1.barrier()                      # 1-worker again: immediate
        assert kv1.num_workers == 1
        kv1.close(stop_servers=True)
    finally:
        srv0.stop()
        srv1.stop()


def test_elastic_graceful_server_leave(monkeypatch):
    """A departing server ships its final snapshot and deregisters; the
    worker converges at its next op and the values survive exactly."""
    srv0, srv1 = _elastic_pair(monkeypatch, snapshot_s=3600.0)
    try:
        kv = mx.kv.create("dist_async")
        big = np.arange(40, dtype=np.float32).reshape(10, 4)
        kv.init("big", mx.nd.NDArray(big))
        kv.push("big", mx.nd.NDArray(big * 3))   # assign semantics
        out = mx.nd.zeros((10, 4))
        kv.pull("big", out=out)
        srv1.leave()                       # snapshot + roster_leave + stop
        kv.push("big", mx.nd.NDArray(big * 5))
        kv.barrier()
        kv.pull("big", out=out)
        np.testing.assert_array_equal(out.asnumpy(), big * 5)
        assert len(kv._conns) == 1
        kv.close(stop_servers=True)
    finally:
        srv0.stop()
        srv1.stop()


def test_stripe_plan_staleness_is_hard_error(monkeypatch):
    """Satellite: mutating the server set WITHOUT the elastic path must
    fail loudly — a stale cached plan silently routes rows to the wrong
    servers.  _reset_stripe_plans() is the sanctioned clear."""
    srvs = [KVStoreServer(server_id=i, num_workers=1) for i in range(2)]
    for s in srvs:
        s.start_background()
    try:
        monkeypatch.setenv("MXT_SERVER_URIS", ",".join(
            f"127.0.0.1:{s.port}" for s in srvs))
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        monkeypatch.setenv("DMLC_WORKER_ID", "0")
        monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "16")
        kv = mx.kv.create("dist_async")
        big = np.arange(40, dtype=np.float32).reshape(10, 4)
        kv.init("big", mx.nd.NDArray(big))
        dropped = kv._conns.pop()          # the old test-only mutation
        with pytest.raises(MXNetError, match="server count changed"):
            kv._stripe_plan("big", big.shape)
        kv._reset_stripe_plans()
        assert kv._stripe_plan("big", big.shape) is None  # 1 server now
        kv._conns.append(dropped)
        kv._reset_stripe_plans()
        kv.close(stop_servers=True)
    finally:
        for s in srvs:
            s.stop()


def test_elastic_coordinator_death_fails_over_exact(monkeypatch):
    """THE tentpole flow, in-process: kill server 0 — the COORDINATOR.
    The worker elects the deterministic successor (server 1), reports
    the death there; the successor verifies it with its own probe,
    rebuilds the ledger at max(reported)+1 and answers with the
    post-succession roster; the ordinary three-phase handoff then
    reconstructs server 0's stripes — final weights EXACTLY equal the
    uninterrupted run (integer grads, power-of-two lr).  No restart,
    no votes, no extra protocol."""
    srv0, srv1 = _elastic_pair(monkeypatch)
    try:
        kv = mx.kv.create("dist_async")
        big = np.arange(40, dtype=np.float32).reshape(10, 4)
        kv.init("big", mx.nd.NDArray(big))
        kv.init("small", mx.nd.ones((2, 2)))
        kv.set_optimizer(mx.optimizer.SGD(
            learning_rate=0.125, momentum=0.0, wd=0.0, rescale_grad=1.0))
        kv.push("big", mx.nd.ones((10, 4)))
        kv.push("small", mx.nd.ones((2, 2)))
        out_b, out_s = mx.nd.zeros((10, 4)), mx.nd.zeros((2, 2))
        kv.pull("big", out=out_b)        # sync point: cache = state
        kv.pull("small", out=out_s)
        gen0 = kv._roster_gen
        srv0.stop()                      # the COORDINATOR dies
        # the next round rides succession + repair end to end
        kv.push("big", mx.nd.ones((10, 4)) * 2)
        kv.push("small", mx.nd.ones((2, 2)) * 2)
        kv.barrier()                     # retried against the successor
        kv.pull("big", out=out_b)
        kv.pull("small", out=out_s)
        np.testing.assert_array_equal(out_b.asnumpy(), big - 0.125 * 3)
        np.testing.assert_array_equal(out_s.asnumpy(), 1.0 - 0.125 * 3)
        uris = os.environ["MXT_SERVER_URIS"].split(",")
        assert kv._roster_servers == [uris[1]]
        assert kv._roster_gen > gen0
        assert kv._failovers == 1
        assert srv1._promoted
        assert srv1._get_membership().roster().servers == (uris[1],)
        counts = profiler.channel_counts()
        assert counts.get("kvstore.coordinator_failover", 0) >= 1
        assert counts.get("kvstore.coordinator_failover_observed",
                          0) >= 1
        assert counts.get("kvstore.coordinator_slot", None) == 1
        assert counts.get("kvstore.failover_rebuild_s", None) is not None
        from mxnet_tpu import distributed
        assert distributed.coordinator_failovers() >= 1
        # the survivor (now coordinator) owns every key
        assert "big" in srv1._store and "small" in srv1._store
        kv.close(stop_servers=True)
    finally:
        srv0.stop()
        srv1.stop()


def test_elastic_coordinator_death_momentum_via_peer_bank(monkeypatch):
    """The snapshot bank OUTLIVES server 0: the coordinator's beat
    fan-out ships its state snapshots to every peer, each peer banks
    them, and a promotion preloads the local bank into the rebuilt
    ledger — so momentum on the dead COORDINATOR's stripes restripes
    elementwise-exactly, same contract as the non-coordinator kill."""
    srv0, srv1 = _elastic_pair(monkeypatch, snapshot_s=0.05)
    try:
        kv = mx.kv.create("dist_async")
        big = np.arange(40, dtype=np.float32).reshape(10, 4)
        kv.init("big", mx.nd.NDArray(big))
        kv.set_optimizer(mx.optimizer.SGD(
            learning_rate=0.125, momentum=0.5, wd=0.0, rescale_grad=1.0))
        kv.push("big", mx.nd.ones((10, 4)))      # momentum builds
        out = mx.nd.zeros((10, 4))
        kv.pull("big", out=out)                  # sync point
        uris = os.environ["MXT_SERVER_URIS"].split(",")
        doomed_wk = [wk for wk, (uri, _lo, _hi) in membership.wire_layout(
            "big", (10, 4), uris, 16).items() if uri == uris[0]][0]

        def banked_on_peer():
            have = srv1._peer_snapshots.get(uris[0])
            return have is not None and have[1].get("states", {}).get(
                doomed_wk) not in (None, ())

        deadline = time.time() + 5
        while not banked_on_peer() and time.time() < deadline:
            time.sleep(0.02)             # wait for a POST-push beat
        assert banked_on_peer(), \
            "no momentum-bearing coordinator snapshot banked on the peer"
        srv0.stop()                      # the COORDINATOR dies
        kv.barrier()     # quiescent repair: succession at the sync point
        kv.push("big", mx.nd.ones((10, 4)))      # momentum compounds on
        kv.barrier()
        kv.pull("big", out=out)
        # golden: the same sequence against one never-interrupted server
        mom = np.zeros((10, 4), np.float32)
        w = big.copy()
        for _ in range(2):
            mom = 0.5 * mom - 0.125 * np.ones((10, 4), np.float32)
            w = w + mom
        np.testing.assert_array_equal(out.asnumpy(), w)
        assert profiler.channel_counts().get(
            "kvstore.handoff_state_applied", 0) >= 1
        kv.close(stop_servers=True)
    finally:
        srv0.stop()
        srv1.stop()


def test_elastic_double_death_walks_to_true_survivor(monkeypatch):
    """Coordinator AND the next roster slot die together: the worker's
    repair walks the election past the dead successor (its channel's
    hard failure is the evidence), and the true survivor's probe-walk
    excludes BOTH corpses from the rebuilt roster — values stay exact
    on the last server standing."""
    monkeypatch.setenv("MXNET_KVSTORE_ELASTIC", "1")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX", "2")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_INITIAL_MS", "10")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX_MS", "50")
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_INTERVAL", "0.1")
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_TIMEOUT", "0.5")
    monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "16")
    monkeypatch.setenv("MXNET_KVSTORE_SNAPSHOT_S", "0")
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    srvs = [KVStoreServer(server_id=i, num_workers=1, elastic=True)
            for i in range(3)]
    uris = ",".join(f"127.0.0.1:{s.port}" for s in srvs)
    monkeypatch.setenv("MXT_SERVER_URIS", uris)
    for s in srvs:
        s._roster_servers = uris.split(",")
        s.start_background()
    try:
        kv = mx.kv.create("dist_async")
        big = np.arange(40, dtype=np.float32).reshape(10, 4)
        kv.init("big", mx.nd.NDArray(big))
        kv.set_optimizer(mx.optimizer.SGD(
            learning_rate=0.125, momentum=0.0, wd=0.0, rescale_grad=1.0))
        kv.push("big", mx.nd.ones((10, 4)))
        out = mx.nd.zeros((10, 4))
        kv.pull("big", out=out)
        srvs[0].stop()               # the coordinator...
        srvs[1].stop()               # ...AND its deterministic successor
        kv.push("big", mx.nd.ones((10, 4)) * 2)
        kv.barrier()
        kv.pull("big", out=out)
        np.testing.assert_array_equal(out.asnumpy(), big - 0.125 * 3)
        assert kv._roster_servers == [uris.split(",")[2]]
        assert srvs[2]._promoted and kv._failovers >= 1
        assert kv._coordinator_slot == 2
        m = srvs[2]._get_membership()
        assert m.roster().servers == (uris.split(",")[2],)
        kv.close(stop_servers=True)
    finally:
        for s in srvs:
            s.stop()


def test_beat_loop_self_promotes_on_coordinator_silence(monkeypatch):
    """No worker needed: the survivors' own beat loops detect the
    coordinator's death (refused dial = decisive evidence), every one
    elects the same successor, and the elected one promotes itself —
    so a workerless window (e.g. between epochs) still converges."""
    srv0, srv1 = _elastic_pair(monkeypatch)
    try:
        deadline = time.time() + 5
        while srv1._coord_last_ok is None and time.time() < deadline:
            time.sleep(0.02)             # beats flowing to server 0
        assert srv1._coord_last_ok is not None
        srv0.stop()
        deadline = time.time() + 10
        while not srv1._promoted and time.time() < deadline:
            time.sleep(0.05)
        assert srv1._promoted
        uris = os.environ["MXT_SERVER_URIS"].split(",")
        m = srv1._get_membership()
        assert m is not None and m.roster().servers == (uris[1],)
        assert m.failovers == 1
        assert profiler.channel_counts().get(
            "kvstore.coordinator_failover", 0) >= 1
    finally:
        srv0.stop()
        srv1.stop()


def test_serving_replica_tolerates_roster_bump(monkeypatch):
    """The serving tier's weight-refresh client follows the roster: a
    parameter server dying between version pulls repairs transparently
    (roster_member=False — the replica never joins the roster), and a
    version bump published AFTER the churn still refreshes served
    weights with zero replica restarts."""
    from mxnet_tpu import serving
    from mxnet_tpu.serving.replica import VERSION_KEY
    srv0, srv1 = _elastic_pair(monkeypatch)
    try:
        kv = mx.kv.create("dist_async")
        # layer name chosen so 'fca_weight' crc-routes to server 1 (the
        # one we kill) — the refresh MUST cross the repair path
        assert membership.server_index("fca_weight", 2) == 1
        w = np.full((2, 4), 2.0, np.float32)   # FC weight: (hidden, in)
        kv.init("fca_weight", mx.nd.NDArray(w))
        serving.publish_version(kv, 1)
        # build a replica over the SAME roster
        import mxnet_tpu.symbol as sym
        data = sym.Variable("data")
        net = sym.FullyConnected(data, num_hidden=2, no_bias=True,
                                 name="fca")
        replica = serving.ServingReplica(
            net, {"data": (1, 4)},
            {"fca_weight": mx.nd.NDArray(w)}, {},
            param_servers=os.environ["MXT_SERVER_URIS"].split(","),
            refresh_interval=0.0, port=0)
        r1 = replica._refresh_once()
        gen_before = getattr(replica._ps, "_roster_gen", 0)
        # kill whichever server does NOT host the coordinator
        srv1.stop()
        # trainer-side: repair + handoff re-homes every key (incl. the
        # version register), then publish a NEW version
        kv.push("fca_weight", mx.nd.NDArray(np.full((2, 4), 5.0,
                                                    np.float32)))
        kv.barrier()
        serving.publish_version(kv, 2)
        r2 = replica._refresh_once()       # repairs mid-pull if needed
        assert r2["version"] == 2 and r2["refreshed"]
        assert getattr(replica._ps, "_roster_gen", 0) > gen_before
        stats = replica._op_stats(("serving_stats",), None)
        assert stats["roster_generation"] >= 1
        # the failover observables surface through serving_stats too
        assert "coordinator_slot" in stats
        assert stats["coordinator_failovers"] == 0   # srv1 was not coord
        replica.stop()
        kv.close(stop_servers=True)
    finally:
        srv0.stop()
        srv1.stop()


# -- roster_diff (the fleet's roster-observation primitive) -------------------
def test_roster_diff_pure_arithmetic():
    added, removed = membership.roster_diff(
        ["a:1", "b:2", "c:3"], ["b:2", "d:4", "c:3"])
    assert added == ["d:4"] and removed == ["a:1"]
    # order of the NEW roster is preserved for added; old for removed
    added, removed = membership.roster_diff([], ["x:1", "y:2"])
    assert added == ["x:1", "y:2"] and removed == []
    added, removed = membership.roster_diff(["x:1", "y:2"], [])
    assert added == [] and removed == ["x:1", "y:2"]
    # identical rosters are a no-op; empties/Nones are ignored
    assert membership.roster_diff(["a:1"], ["a:1"]) == ([], [])
    assert membership.roster_diff(["a:1", ""], ["a:1", None]) == ([], [])
    assert membership.roster_diff(None, None) == ([], [])
