"""Optimizer tests (reference: tests/python/unittest/test_optimizer.py) —
each optimizer's update is checked against a numpy reference implementation.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt


def _run_steps(optimizer, w0, grads, nsteps=3):
    w = mx.nd.array(w0.copy())
    state = optimizer.create_state(0, w)
    for i in range(nsteps):
        g = mx.nd.array(grads[i])
        optimizer.update(0, w, g, state)
    return w.asnumpy()


RNG = np.random.RandomState(42)
W0 = RNG.randn(4, 3).astype('float32')
GRADS = [RNG.randn(4, 3).astype('float32') for _ in range(3)]


def test_sgd_matches_numpy():
    o = opt.create('sgd', learning_rate=0.1, momentum=0.9, wd=0.01)
    got = _run_steps(o, W0, GRADS)
    w = W0.copy()
    mom = np.zeros_like(w)
    for g in GRADS:
        mom = 0.9 * mom - 0.1 * (g + 0.01 * w)
        w = w + mom
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_sgd_clip_gradient():
    o = opt.create('sgd', learning_rate=1.0, clip_gradient=0.1)
    got = _run_steps(o, W0, GRADS, nsteps=1)
    w = W0 - 1.0 * np.clip(GRADS[0], -0.1, 0.1)
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_adam_matches_numpy():
    o = opt.create('adam', learning_rate=0.01)
    got = _run_steps(o, W0, GRADS)
    w = W0.copy()
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    b1, b2, eps = 0.9, 0.999, 1e-8
    for t, g in enumerate(GRADS, 1):
        lr = 0.01 * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        w = w - lr * m / (np.sqrt(v) + eps)
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_rmsprop_matches_numpy():
    o = opt.create('rmsprop', learning_rate=0.01, gamma1=0.9)
    got = _run_steps(o, W0, GRADS)
    w = W0.copy()
    n = np.zeros_like(w)
    for g in GRADS:
        n = 0.9 * n + 0.1 * g * g
        w = w - 0.01 * g / np.sqrt(n + 1e-8)
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_adagrad_matches_numpy():
    o = opt.create('adagrad', learning_rate=0.05)
    got = _run_steps(o, W0, GRADS)
    w = W0.copy()
    h = np.zeros_like(w)
    for g in GRADS:
        h += g * g
        w = w - 0.05 * g / np.sqrt(h + 1e-7)
    np.testing.assert_allclose(got, w, rtol=1e-5)


@pytest.mark.parametrize("name", ['sgd', 'nag', 'adam', 'adagrad', 'rmsprop',
                                  'adadelta', 'ftrl', 'adamax', 'nadam',
                                  'signum', 'sgld', 'dcasgd', 'lars', 'lamb'])
def test_all_optimizers_step(name):
    """Every registered optimizer must take a step without error and move
    the weights."""
    o = opt.create(name, learning_rate=0.01)
    got = _run_steps(o, W0, GRADS, nsteps=2)
    assert got.shape == W0.shape
    assert np.isfinite(got).all()
    assert np.abs(got - W0).sum() > 0


def test_lr_mult_wd_mult():
    o = opt.create('sgd', learning_rate=0.1, wd=0.1,
                   param_idx2name={0: 'w_weight', 1: 'b_bias'})
    o.set_lr_mult({'w_weight': 0.5})
    assert o._get_lr(0) == pytest.approx(0.05)
    # bias gets wd_mult 0 automatically (reference behavior)
    assert o._get_wd(1) == pytest.approx(0.0)
    assert o._get_wd(0) == pytest.approx(0.1)


def test_updater_and_states_roundtrip(tmp_path):
    o = opt.create('sgd', learning_rate=0.1, momentum=0.9)
    u = opt.get_updater(o)
    w = mx.nd.array(W0.copy())
    u(0, mx.nd.array(GRADS[0]), w)
    blob = u.get_states()
    u2 = opt.get_updater(opt.create('sgd', learning_rate=0.1, momentum=0.9))
    u2.set_states(blob)
    assert 0 in u2.states


def test_lr_scheduler_factor():
    s = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5)
    s.base_lr = 1.0
    assert s(5) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25


def test_lr_scheduler_multifactor():
    s = mx.lr_scheduler.MultiFactorScheduler(step=[5, 9], factor=0.1)
    s.base_lr = 1.0
    assert s(1) == 1.0
    assert s(6) == pytest.approx(0.1)
    assert s(10) == pytest.approx(0.01)


def test_lr_scheduler_poly():
    s = mx.lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0, pwr=2)
    assert s(0) == 1.0
    assert s(100) == 0.0
    assert 0 < s(50) < 1.0


def test_adam_preserves_dtype():
    """Adam's bias-correction scalars must not promote f32 weights to f64
    under the global x64 mode (regression: jnp.asarray(beta) was f64)."""
    import jax.numpy as jnp
    import mxnet_tpu as mx
    opt = mx.optimizer.Adam(learning_rate=0.01)
    w = jnp.ones((4,), jnp.float32)
    g = jnp.ones((4,), jnp.float32)
    st = (jnp.zeros((4,), jnp.float32), jnp.zeros((4,), jnp.float32))
    nw, nst = opt._update_impl(w, g, st, np.float32(0.01), np.float32(0.0),
                               t=jnp.asarray(1, jnp.int32))
    assert nw.dtype == jnp.float32
    assert all(s.dtype == jnp.float32 for s in nst)


def test_lars_matches_numpy():
    o = opt.create('lars', learning_rate=0.1, momentum=0.9, wd=0.01,
                   eta=0.001)
    got = _run_steps(o, W0, GRADS)
    w = W0.copy()
    mom = np.zeros_like(w)
    for g in GRADS:
        w_norm = np.sqrt((w.astype('float64') ** 2).sum())
        g_norm = np.sqrt((g.astype('float64') ** 2).sum())
        trust = 0.001 * w_norm / (g_norm + 0.01 * w_norm + 1e-9)
        mom = 0.9 * mom - 0.1 * trust * (g + 0.01 * w)
        w = w + mom
    np.testing.assert_allclose(got, w, rtol=2e-5)


def test_lars_bias_skips_trust_ratio():
    # 1-D params (bias/BN) take the plain momentum-SGD path, no wd
    o = opt.create('lars', learning_rate=0.1, momentum=0.9, wd=0.01)
    b0 = RNG.randn(5).astype('float32')
    gb = [RNG.randn(5).astype('float32') for _ in range(2)]
    got = _run_steps(o, b0, gb, nsteps=2)
    b = b0.copy()
    mom = np.zeros_like(b)
    for g in gb:
        mom = 0.9 * mom - 0.1 * g
        b = b + mom
    np.testing.assert_allclose(got, b, rtol=1e-5)


def test_lamb_matches_numpy():
    o = opt.create('lamb', learning_rate=0.01, wd=0.01)
    got = _run_steps(o, W0, GRADS)
    w = W0.copy()
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    b1, b2, eps = 0.9, 0.999, 1e-6
    for t, g in enumerate(GRADS, 1):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** t)
        v_hat = v / (1 - b2 ** t)
        r = m_hat / (np.sqrt(v_hat) + eps) + 0.01 * w
        w_norm = np.sqrt((w.astype('float64') ** 2).sum())
        r_norm = np.sqrt((r.astype('float64') ** 2).sum())
        ratio = w_norm / r_norm if w_norm > 0 and r_norm > 0 else 1.0
        w = w - 0.01 * ratio * r
    np.testing.assert_allclose(got, w, rtol=2e-5)


def test_lars_lamb_in_fused_module_step():
    """Large-batch optimizers must trace inside the fused Module step
    (pure_update) and train a tiny net without NaNs."""
    from mxnet_tpu import models
    for name in ('lars', 'lamb'):
        sym = models.mlp(num_classes=4, hidden=[8])
        mod = mx.mod.Module(sym)
        x = np.random.RandomState(1).uniform(size=(8, 6)).astype('float32')
        y = (np.arange(8) % 4).astype('float32')
        it = mx.io.NDArrayIter(x, y, batch_size=8)
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(optimizer=name,
                           optimizer_params={'learning_rate': 0.05})
        for _ in range(3):
            it.reset()
            for b in it:
                mod.forward(b, is_train=True)
                mod.update()
        w = next(iter(mod.get_params()[0].values())).asnumpy()
        assert np.isfinite(w).all(), name


def test_lamb_late_state_starts_at_t1():
    """A param whose LAMB state is created after other params have taken
    N steps must bias-correct from t=1, not t=N (per-index update count
    through the base update path)."""
    o = opt.create('lamb', learning_rate=0.01)
    w0 = mx.nd.array(W0.copy())
    s0 = o.create_state(0, w0)
    for g in GRADS:
        o.update(0, w0, mx.nd.array(g), s0)
    # param 7 starts fresh after param 0 took 3 steps
    w7 = mx.nd.array(W0.copy())
    s7 = o.create_state(7, w7)
    o.update(7, w7, mx.nd.array(GRADS[0]), s7)
    # reference: single LAMB step from zeroed moments at t=1
    o2 = opt.create('lamb', learning_rate=0.01)
    wref = mx.nd.array(W0.copy())
    sref = o2.create_state(0, wref)
    o2.update(0, wref, mx.nd.array(GRADS[0]), sref)
    np.testing.assert_allclose(w7.asnumpy(), wref.asnumpy(), rtol=1e-6)
