"""Flash/ring attention tests (new TPU-native capability, SURVEY.md §5.7).

Pallas kernel runs in interpret mode on the CPU mesh — same code path as
TPU (SURVEY.md §4 consistency strategy)."""
import os
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.ops.attention import flash_attention, _attn_reference


def _rand_qkv(B=2, H=2, S=96, D=32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D).astype('float32'))
    return mk(), mk(), mk()


@pytest.mark.parametrize('causal', [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _rand_qkv()
    out = flash_attention(q, k, v, causal, None)
    ref = _attn_reference(q, k, v, causal, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_unaligned_seq():
    """Sequence not a multiple of the block size exercises the padding
    masks."""
    q, k, v = _rand_qkv(S=100)
    out = flash_attention(q, k, v, True, None)
    ref = _attn_reference(q, k, v, True, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_cross_attention():
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 48, 16).astype('float32'))
    k = jnp.asarray(rng.randn(1, 2, 80, 16).astype('float32'))
    v = jnp.asarray(rng.randn(1, 2, 80, 16).astype('float32'))
    out = flash_attention(q, k, v, False, None)
    ref = _attn_reference(q, k, v, False, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_gradients():
    q, k, v = _rand_qkv(S=64)
    f = lambda *xs: jnp.sum(flash_attention(*xs, True, None) ** 2)
    fr = lambda *xs: jnp.sum(_attn_reference(*xs, True, None) ** 2)
    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_via_op_registry():
    """The op is reachable from the nd/sym frontends."""
    q, k, v = _rand_qkv(S=32, D=16)
    out = mx.nd.flash_attention(mx.nd.NDArray(q), mx.nd.NDArray(k),
                                mx.nd.NDArray(v), causal=True)
    ref = _attn_reference(q, k, v, True, None)
    np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_ring_attention_exact(causal):
    mesh = par.make_mesh(dp=1, sp=8)
    q, k, v = _rand_qkv(S=64)
    qs, ks, vs = (par.shard_seq(x, mesh) for x in (q, k, v))
    out = par.ring_attention(qs, ks, vs, mesh, causal=causal)
    ref = _attn_reference(q, k, v, causal, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_grad():
    mesh = par.make_mesh(dp=1, sp=8)
    q, k, v = _rand_qkv(S=64)
    qs, ks, vs = (par.shard_seq(x, mesh) for x in (q, k, v))
    f = lambda a, b, c: jnp.sum(
        par.ring_attention(a, b, c, mesh, causal=True) ** 2)
    fr = lambda a, b, c: jnp.sum(_attn_reference(a, b, c, True, None) ** 2)
    g = jax.grad(f, argnums=(0, 1, 2))(qs, ks, vs)
    gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_dp_sp():
    """dp and sp compose: batch over dp, sequence over the sp ring."""
    mesh = par.make_mesh(dp=2, sp=4)
    q, k, v = _rand_qkv(B=4, S=32)
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, P('dp', None, 'sp', None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = par.ring_attention(qs, ks, vs, mesh, causal=True)
    ref = _attn_reference(q, k, v, True, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_flash_backward_kernel_matches_reference(causal):
    """The Pallas dq/dk/dv kernels must match jax.vjp of plain-XLA
    attention (FA2 backward correctness)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import attention as A
    rng = np.random.RandomState(0)
    B, H, Sq, D = 2, 3, 80, 16   # non-multiple of block sizes
    q = jnp.asarray(rng.randn(B, H, Sq, D).astype('f'))
    k = jnp.asarray(rng.randn(B, H, Sq, D).astype('f'))
    v = jnp.asarray(rng.randn(B, H, Sq, D).astype('f'))
    g = jnp.asarray(rng.randn(B, H, Sq, D).astype('f'))

    out, vjp = jax.vjp(
        lambda q_, k_, v_: A._attn_reference(q_, k_, v_, causal, None),
        q, k, v)
    dq_ref, dk_ref, dv_ref = vjp(g)

    dq, dk, dv = jax.vjp(
        lambda q_, k_, v_: A.flash_attention(q_, k_, v_, causal, None),
        q, k, v)[1](g)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq_ref),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk_ref),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv_ref),
                               rtol=2e-3, atol=2e-3)


def test_flash_backward_small_blocks():
    """Multi-block path (several q and k blocks) with causal masking."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import attention as A
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 2, 96, 8).astype('f'))
    k = jnp.asarray(rng.randn(1, 2, 96, 8).astype('f'))
    v = jnp.asarray(rng.randn(1, 2, 96, 8).astype('f'))
    g = jnp.asarray(rng.randn(1, 2, 96, 8).astype('f'))
    ref = jax.vjp(lambda a, b, c: A._attn_reference(a, b, c, True, None),
                  q, k, v)[1](g)
    got = A._flash_bwd(q, k, v,
                       *A._flash_fwd(q, k, v, causal=True,
                                     return_lse=True),
                       g, causal=True, block_q=32, block_k=32)
    for x, y in zip(got, ref):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-3, atol=2e-3)


# --- Ulysses all-to-all sequence parallelism (parallel/ulysses.py) ---------

def _full_attn(q, k, v, causal=False):
    # same oracle as every other test in this file
    return np.asarray(_attn_reference(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), causal, None))


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full_attention(causal):
    import jax
    from mxnet_tpu import parallel as par
    from mxnet_tpu.parallel.ulysses import ulysses_attention
    n = 4
    mesh = par.make_mesh(dp=1, sp=n, devices=jax.devices()[:n])
    rs = np.random.RandomState(0)
    B, H, S, D = 2, 8, 32, 16
    q, k, v = (rs.randn(B, H, S, D).astype('float32') for _ in range(3))
    qs, ks, vs = (par.shard_seq(np.asarray(x), mesh) for x in (q, k, v))
    out = np.asarray(ulysses_attention(qs, ks, vs, mesh, causal=causal))
    ref = _full_attn(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ulysses_grad_and_ring_agreement():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import parallel as par
    from mxnet_tpu.parallel.ulysses import ulysses_attention
    n = 4
    mesh = par.make_mesh(dp=1, sp=n, devices=jax.devices()[:n])
    rs = np.random.RandomState(1)
    B, H, S, D = 1, 4, 16, 8
    q, k, v = (rs.randn(B, H, S, D).astype('float32') for _ in range(3))
    qs, ks, vs = (par.shard_seq(np.asarray(x), mesh) for x in (q, k, v))

    def loss_u(a, b, c):
        return jnp.sum(ulysses_attention(a, b, c, mesh, causal=True) ** 2)

    def loss_r(a, b, c):
        return jnp.sum(par.ring_attention(a, b, c, mesh, causal=True) ** 2)

    gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(qs, ks, vs)
    gr = jax.jit(jax.grad(loss_r, argnums=(0, 1, 2)))(qs, ks, vs)
    for a, b in zip(gu, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


def test_ulysses_head_divisibility_error():
    import jax
    from mxnet_tpu import parallel as par
    from mxnet_tpu.parallel.ulysses import ulysses_attention
    mesh = par.make_mesh(dp=1, sp=4, devices=jax.devices()[:4])
    q = np.zeros((1, 2, 16, 8), 'float32')  # 2 heads < sp=4
    with pytest.raises(Exception):
        ulysses_attention(q, q, q, mesh)


# --- grouped-query / multi-query attention (GQA) ---------------------------

@pytest.mark.parametrize("hk,causal", [(2, False), (2, True), (1, True)])
def test_flash_gqa_matches_repeated_kv(hk, causal):
    """flash_attention with Hk kv heads == full attention with the kv
    heads explicitly repeated per group (Hk=1 is MQA)."""
    rs = np.random.RandomState(0)
    B, H, S, D = 2, 4, 48, 16
    q = rs.randn(B, H, S, D).astype('float32')
    k = rs.randn(B, hk, S, D).astype('float32')
    v = rs.randn(B, hk, S, D).astype('float32')
    out = np.asarray(flash_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), causal, None))
    g = H // hk
    ref = np.asarray(_attn_reference(
        jnp.asarray(q), jnp.asarray(np.repeat(k, g, axis=1)),
        jnp.asarray(np.repeat(v, g, axis=1)), causal, None))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_flash_gqa_gradients():
    """GQA backward: dq/dk/dv match autodiff through the repeated-KV
    reference (dk/dv sum over the group's query heads)."""
    rs = np.random.RandomState(1)
    B, H, Hk, S, D = 1, 4, 2, 32, 8
    q = jnp.asarray(rs.randn(B, H, S, D).astype('float32'))
    k = jnp.asarray(rs.randn(B, Hk, S, D).astype('float32'))
    v = jnp.asarray(rs.randn(B, Hk, S, D).astype('float32'))

    def loss_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, True, None) ** 2)

    def loss_ref(q_, k_, v_):
        g = H // Hk
        return jnp.sum(_attn_reference(
            q_, jnp.repeat(k_, g, axis=1), jnp.repeat(v_, g, axis=1),
            True, None) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_flash_gqa_bad_heads_raises():
    q = jnp.zeros((1, 4, 16, 8))
    k = jnp.zeros((1, 3, 16, 8))
    with pytest.raises(ValueError):
        flash_attention(q, k, k, False, None)


def test_ring_and_ulysses_accept_gqa_inputs():
    import jax
    from mxnet_tpu import parallel as par
    from mxnet_tpu.parallel.ulysses import ulysses_attention
    n = 4
    mesh = par.make_mesh(dp=1, sp=n, devices=jax.devices()[:n])
    rs = np.random.RandomState(5)
    B, H, Hk, S, D = 1, 8, 2, 32, 16
    q = rs.randn(B, H, S, D).astype('float32')
    k = rs.randn(B, Hk, S, D).astype('float32')
    v = rs.randn(B, Hk, S, D).astype('float32')
    ref = _full_attn(q, np.repeat(k, H // Hk, 1), np.repeat(v, H // Hk, 1),
                     causal=True)
    qs = par.shard_seq(np.asarray(q), mesh)
    ks = par.shard_seq(np.asarray(k), mesh)
    vs = par.shard_seq(np.asarray(v), mesh)
    out_r = np.asarray(par.ring_attention(qs, ks, vs, mesh, causal=True))
    out_u = np.asarray(ulysses_attention(qs, ks, vs, mesh, causal=True))
    np.testing.assert_allclose(out_r, ref, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(out_u, ref, rtol=2e-4, atol=2e-5)


def test_ulysses_gqa_compact_path_and_ring_dp_fold():
    """Hk divisible by the group size: Ulysses moves the COMPACT kv form
    through the all-to-all; ring's query-group fold works under dp
    sharding (local batch differs from global)."""
    import jax
    from mxnet_tpu import parallel as par
    from mxnet_tpu.parallel.ulysses import ulysses_attention
    from jax.sharding import NamedSharding, PartitionSpec as P
    rs = np.random.RandomState(6)
    B, H, Hk, S, D = 4, 8, 4, 32, 16
    q = rs.randn(B, H, S, D).astype('float32')
    k = rs.randn(B, Hk, S, D).astype('float32')
    v = rs.randn(B, Hk, S, D).astype('float32')
    ref = _full_attn(q, np.repeat(k, H // Hk, 1),
                     np.repeat(v, H // Hk, 1), causal=True)

    mesh = par.make_mesh(dp=2, sp=4)
    sh = NamedSharding(mesh, P('dp', None, 'sp', None))
    qs, ks, vs = (jax.device_put(np.asarray(x), sh) for x in (q, k, v))
    out_u = np.asarray(ulysses_attention(qs, ks, vs, mesh, causal=True))
    np.testing.assert_allclose(out_u, ref, rtol=2e-4, atol=2e-5)
    out_r = np.asarray(par.ring_attention(qs, ks, vs, mesh, causal=True))
    np.testing.assert_allclose(out_r, ref, rtol=2e-4, atol=2e-5)


def test_attention_impl_dispatch(monkeypatch, tmp_path):
    """Per-shape winner dispatch (VERDICT r3 item 5): env override, the
    measured table, and both impls agreeing numerically."""
    import json
    from mxnet_tpu.ops import attention as att

    q, k, v = _rand_qkv(S=32, D=16)
    # both impls produce the same math, so dispatch is free to choose
    out_flash = att.flash_attention(q, k, v, True, None)
    out_xla = att._attn_reference(q, k, v, True, None)
    np.testing.assert_allclose(np.asarray(out_flash),
                               np.asarray(out_xla), rtol=1e-5, atol=1e-5)

    # env override wins over everything
    monkeypatch.setenv("MXNET_ATTENTION_IMPL", "xla")
    assert att.pick_attention_impl(4096, False) == "xla"
    monkeypatch.setenv("MXNET_ATTENTION_IMPL", "flash")
    assert att.pick_attention_impl(64, True) == "flash"

    # auto consults the measured table; default (no table) is flash
    monkeypatch.setenv("MXNET_ATTENTION_IMPL", "auto")
    table = {"rows": [
        {"min_seq": 0, "max_seq": 512, "gqa": False, "winner": "xla"},
        {"min_seq": 513, "max_seq": 1 << 62, "gqa": False,
         "winner": "flash"},
    ]}
    path = tmp_path / "attention_dispatch.json"
    path.write_text(json.dumps(table))
    monkeypatch.setattr(att, "_DISPATCH_PATH", str(path))
    monkeypatch.setattr(att, "_dispatch_cache", None)  # drop cache
    assert att.pick_attention_impl(256, False) == "xla"
    assert att.pick_attention_impl(4096, False) == "flash"
    assert att.pick_attention_impl(256, True) == "flash"  # no gqa row

    # registry op respects the table (xla branch, numerics identical)
    out = mx.nd.flash_attention(mx.nd.NDArray(q), mx.nd.NDArray(k),
                                mx.nd.NDArray(v), causal=True)
    np.testing.assert_allclose(out.asnumpy(), np.asarray(out_xla),
                               rtol=1e-5, atol=1e-5)

    # a table REWRITTEN in the same process is observed (mtime cache) —
    # the bench-then-use flow must not require a restart.  The stat is
    # throttled (~2s) for eager-op dispatch cost; expire the throttle
    # instead of sleeping through it.
    table["rows"][0]["winner"] = "flash"
    table["rows"][0]["blocks"] = "256x128"
    path.write_text(json.dumps(table))
    os.utime(path, (time.time() + 5, time.time() + 5))
    monkeypatch.setattr(att, "_dispatch_stat_t", 0.0)
    assert att.pick_attention_config(256, False) == ("flash", 256, 128)
    # a forced impl still runs the shape's MEASURED tile config
    monkeypatch.setenv("MXNET_ATTENTION_IMPL", "xla")
    assert att.pick_attention_config(256, False) == ("xla", 256, 128)
    monkeypatch.setenv("MXNET_ATTENTION_IMPL", "auto")
    monkeypatch.setattr(att, "_dispatch_cache", None)
