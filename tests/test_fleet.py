"""mxnet_tpu.serving.fleet — the health-routed replica-set client.

Covers the ISSUE 17 tentpole on CPU, in tier-1, with ZERO real sleeps
on the retry paths (clock, sleep and RNG are injected):

* weighted-least-loaded routing that excludes CRITICAL / dead /
  quarantined / draining replicas and penalizes DEGRADED ones;
* cross-replica retry of BUSY / connection failure / reply timeout,
  with the backoff schedule pinned EXACTLY under an injected clock;
* budget and deadline exhaustion surfacing the LAST error while naming
  every attempted replica;
* scoreboard staleness: an OK verdict older than the staleness horizon
  is discounted to DEGRADED (a silent replica's last OK is not live);
* operator drain / undrain over the wire, roster-departure drain via
  membership.roster_diff;
* canary rollout bookkeeping: p99 and error-rate SLO regressions
  auto-roll back (cohort drained, flight-recorder event), promotion
  dissolves the cohorts;
* the gray-failure path end to end: a BLACKHOLED replica (accepts,
  never replies) is caught by the reply timeout, quarantined and
  routed around.

The 3-process kill + blackhole storm and the forced-canary-regression
rollback run as CI gates (tests/dist/dist_fleet_chaos.py,
tests/dist/dist_fleet_canary.py).
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faultinject, health, profiler
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import (BusyError, FleetClient, FleetError,
                               PredictTimeout, ServingReplica)

FEAT = 4
HIDDEN = 3


@pytest.fixture(autouse=True)
def _clean_slate():
    """Every test starts with a clean flight recorder and no armed
    fault plan: earlier suite tests legitimately leave channel poison /
    trips behind, and a replica's self-reported verdict is the
    process-global roll-up — leaked poison would read CRITICAL here."""
    health.reset()
    faultinject.reset()
    profiler.reset_channel_counts()
    yield
    faultinject.reset()


def _softmax_symbol():
    data = mx.sym.Variable('data')
    fc = mx.sym.FullyConnected(data, num_hidden=HIDDEN, name='fc')
    return mx.sym.SoftmaxOutput(fc, name='softmax')


def _params(seed=0):
    rs = np.random.RandomState(seed)
    return {
        'fc_weight': mx.nd.NDArray(
            rs.randn(HIDDEN, FEAT).astype(np.float32)),
        'fc_bias': mx.nd.NDArray(
            rs.randn(HIDDEN).astype(np.float32)),
    }


def _ref_softmax(x, params):
    w = np.asarray(params['fc_weight'].asnumpy())
    b = np.asarray(params['fc_bias'].asnumpy())
    logits = x @ w.T + b
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def _replica(**kw):
    kw.setdefault('buckets', [2, 4])
    kw.setdefault('warmup', False)
    rep = ServingReplica(_softmax_symbol(), {'data': (FEAT,)},
                         _params(), **kw)
    rep.start_background()
    return rep


# -- deterministic harness ----------------------------------------------------
class _FakeTime:
    """Injected monotonic clock + sleep recorder — the retry tests'
    whole point is that NO real time passes."""

    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def clock(self):
        return self.t

    def sleep(self, d):
        self.sleeps.append(round(d, 9))
        self.t += d


class _StubFuture:
    def __init__(self, fn, timeout_seen):
        self._fn = fn
        self._timeout_seen = timeout_seen

    def get(self, timeout=None):
        self._timeout_seen.append(timeout)
        return self._fn()


class _StubClient:
    """Stands in for ServingClient on a scoreboard entry: ``behavior``
    runs at ``get()`` time and either returns outputs or raises."""

    def __init__(self, behavior, stats=None):
        self.behavior = behavior
        self.calls = 0
        self.canary_calls = 0
        self.timeouts_seen = []
        self._stats = stats

    def predict_async(self, data, name="data", canary=False):
        self.calls += 1
        if canary:
            self.canary_calls += 1
        return _StubFuture(self.behavior, self.timeouts_seen)

    def stats(self, timeout=None):
        if self._stats is None:
            raise MXNetError("stub has no stats")
        return dict(self._stats)

    def refresh(self, timeout=None):
        return {"version": 99, "refreshed": True}

    def drain(self, enable=True, timeout=None):
        return {"draining": bool(enable)}

    def is_dead(self):
        return False

    def close(self):
        pass

    def abort(self):
        pass


def _stub_fleet(behaviors, ft=None, **kw):
    """FleetClient over stub clients (no sockets, no background poll).
    ``behaviors`` maps uri -> callable for that replica's get()."""
    ft = ft or _FakeTime()
    kw.setdefault("jitter", 0.0)
    kw.setdefault("backoff_ms", 10.0)
    kw.setdefault("backoff_max_ms", 40.0)
    kw.setdefault("deadline_s", 1000.0)
    kw.setdefault("attempt_s", 5.0)
    fl = FleetClient(list(behaviors), stats_interval=0,
                     clock=ft.clock, sleep=ft.sleep, **kw)
    stubs = {}
    for uri, beh in behaviors.items():
        st = beh if isinstance(beh, _StubClient) else _StubClient(beh)
        fl._entries[uri].client = st
        stubs[uri] = st
    return fl, stubs, ft


_OK = lambda: [np.zeros((1, HIDDEN), np.float32)]  # noqa: E731


def _busy(tag):
    def beh():
        raise BusyError("shed busy-%s" % tag)
    return beh


# -- routing ------------------------------------------------------------------
def test_routing_excludes_sick_states():
    """CRITICAL, draining, quarantined and dead replicas never see a
    request — every route lands on the one healthy survivor."""
    fl, stubs, _ = _stub_fleet({u: _OK for u in "abcd"}, retries=0)
    fl._entries["b"].verdict = "CRITICAL"
    fl._entries["c"].draining = True
    fl._entries["d"].quarantined = True
    for _ in range(5):
        fl.predict(np.zeros((1, FEAT), np.float32))
    assert stubs["a"].calls == 5
    assert all(stubs[u].calls == 0 for u in "bcd")
    sb = fl.scoreboard()
    assert sb["b"]["state"] == "CRITICAL"
    assert sb["c"]["state"] == "DRAINING"
    assert sb["d"]["state"] == "DEAD"


def test_degraded_penalty_steers_traffic():
    """A DEGRADED replica still serves, but only once the healthy one
    is loaded past the penalty multiplier — at idle it gets nothing."""
    fl, stubs, _ = _stub_fleet({"deg": _OK, "ok": _OK}, retries=0,
                               degraded_penalty=4.0)
    fl._entries["deg"].verdict = "DEGRADED"
    for _ in range(6):
        fl.predict(np.zeros((1, FEAT), np.float32))
    assert stubs["ok"].calls == 6 and stubs["deg"].calls == 0
    # queue pressure on the healthy one flips the comparison:
    # (0+4+1)*1 = 5 > (0+0+1)*4 = 4
    fl._entries["ok"].queue_depth = 4
    fl.predict(np.zeros((1, FEAT), np.float32))
    assert stubs["deg"].calls == 1


def test_least_loaded_ties_round_robin():
    fl, stubs, _ = _stub_fleet({"a": _OK, "b": _OK}, retries=0)
    for _ in range(8):
        fl.predict(np.zeros((1, FEAT), np.float32))
    assert stubs["a"].calls == 4 and stubs["b"].calls == 4


# -- retries ------------------------------------------------------------------
def test_busy_retries_on_a_different_replica():
    profiler.reset_channel_counts()
    fl, stubs, ft = _stub_fleet({"busy": _busy("x"), "good": _OK})
    for _ in range(6):
        outs = fl.predict(np.zeros((1, FEAT), np.float32))
        assert outs[0].shape == (1, HIDDEN)
    sb = fl.scoreboard()
    # every attempt that hit the busy replica was shed and re-routed;
    # none of the 6 requests failed
    assert sb["busy"]["busy"] == stubs["busy"].calls
    assert stubs["good"].calls == 6 + 0  # every request ended here
    counts = profiler.channel_counts()
    assert counts.get("fleet.busy", 0) == stubs["busy"].calls
    if stubs["busy"].calls:
        assert counts["fleet.retry"] >= stubs["busy"].calls
    # BUSY does NOT quarantine — the replica is healthy, just full
    assert sb["busy"]["state"] == "OK"


def test_backoff_schedule_pinned_exactly():
    """jitter=0 + injected clock/sleep: the retry backoff is EXACTLY
    base * 2^k capped — and not one real millisecond passes."""
    fl, _, ft = _stub_fleet(
        {u: _busy(u) for u in ("r0", "r1", "r2")},
        retries=4, backoff_ms=10.0, backoff_max_ms=40.0)
    wall0 = time.monotonic()
    with pytest.raises(FleetError) as ei:
        fl.predict(np.zeros((1, FEAT), np.float32))
    assert time.monotonic() - wall0 < 2.0      # no real sleeps
    assert ft.sleeps == [0.01, 0.02, 0.04, 0.04]
    msg = str(ei.value)
    for uri in ("r0", "r1", "r2"):
        assert uri in msg, msg
    assert "retry budget" in msg


def test_jittered_backoff_stays_in_band():
    import random
    ft = _FakeTime()
    fl, _, _ = _stub_fleet(
        {u: _busy(u) for u in ("a", "b")}, ft=ft,
        retries=3, jitter=0.5, backoff_ms=100.0, backoff_max_ms=400.0)
    fl._rng = random.Random(7)
    with pytest.raises(FleetError):
        fl.predict(np.zeros((1, FEAT), np.float32))
    bases = [0.1, 0.2, 0.4]
    assert len(ft.sleeps) == 3
    for got, base in zip(ft.sleeps, bases):
        assert base * 0.5 <= got <= base * 1.5, (got, base)
        assert got != base                      # jitter actually moved it


def test_exhaustion_names_every_replica_and_surfaces_last_error():
    fl, _, _ = _stub_fleet(
        {u: _busy(u) for u in ("s1", "s2", "s3")}, retries=2)
    with pytest.raises(FleetError) as ei:
        fl.predict(np.zeros((1, FEAT), np.float32))
    msg = str(ei.value)
    for uri in ("s1", "s2", "s3"):
        assert uri in msg, msg
    # the LAST error rides along: named inline AND chained as __cause__
    assert "last error from" in msg and "BusyError" in msg
    last_uri = msg.split("last error from ")[1].split(":")[0]
    assert ("busy-%s" % last_uri) in msg
    assert isinstance(ei.value.__cause__, BusyError)


def test_deadline_exhaustion_is_typed_and_named():
    ft = _FakeTime()

    def slow_busy():
        ft.t += 10.0                 # each attempt burns 10 fake seconds
        raise BusyError("still full")

    fl, _, _ = _stub_fleet({"a": slow_busy, "b": slow_busy}, ft=ft,
                           retries=100, deadline_s=25.0, attempt_s=50.0)
    with pytest.raises(FleetError, match="deadline"):
        fl.predict(np.zeros((1, FEAT), np.float32))
    assert ft.t < 40.0               # stopped at the deadline, not at 100


def test_attempt_timeout_bounded_by_deadline():
    """The per-attempt wait shrinks to the remaining deadline — a
    30s attempt budget never outlives a 2s request deadline."""
    fl, stubs, ft = _stub_fleet({"only": _OK}, retries=0,
                                deadline_s=2.0, attempt_s=30.0)
    fl.predict(np.zeros((1, FEAT), np.float32))
    assert stubs["only"].timeouts_seen == [2.0]


def test_timeout_quarantines_and_poll_reinstates():
    """A reply timeout is the gray-failure verdict: quarantine NOW,
    route around, and only a successful scoreboard probe re-earns
    eligibility."""
    profiler.reset_channel_counts()

    def hang():
        raise PredictTimeout("no reply within 0.1s")

    good_stats = {"health": {"status": "OK", "ts": time.time()},
                  "queue_depth": 0, "queue_limit": 8, "version": 1}
    hung = _StubClient(hang, stats=good_stats)
    fl, stubs, _ = _stub_fleet({"hung": hung, "good": _OK})
    for _ in range(4):
        fl.predict(np.zeros((1, FEAT), np.float32))
    sb = fl.scoreboard()
    assert sb["good"]["routes"] == 4          # every request ended here
    assert sb["hung"]["routes"] == sb["hung"]["timeouts"]
    if sb["hung"]["timeouts"]:
        assert sb["hung"]["state"] == "DEAD"    # quarantined
        hung_calls = stubs["hung"].calls
        for _ in range(4):                      # no more traffic there
            fl.predict(np.zeros((1, FEAT), np.float32))
        assert stubs["hung"].calls == hung_calls
        assert profiler.channel_counts()["fleet.timeout"] \
            == sb["hung"]["timeouts"]
        # quarantine REPLACED the suspect conn (FIFO acks are
        # misaligned after a missed reply; see ServingClient.abort)
        assert fl._entries["hung"].client is None
        # the probe re-dials and clears the quarantine (stats OK)
        fl._entries["hung"].client = hung
        states = fl.poll_once()
        assert states["hung"] == "OK"


def test_conn_error_quarantines_and_flight_records():
    def refuse():
        raise ConnectionRefusedError("nope")

    fl, _, _ = _stub_fleet({"down": refuse, "up": _OK})
    health.reconfigure()
    outs = fl.predict(np.zeros((1, FEAT), np.float32))
    assert outs[0].shape == (1, HIDDEN)
    sb = fl.scoreboard()
    if sb["down"]["conn_errors"]:
        assert sb["down"]["state"] == "DEAD"
        kinds = [e["kind"] for e in health.events()]
        assert "fleet_quarantine" in kinds


# -- scoreboard staleness -----------------------------------------------------
def test_stale_ok_verdict_discounted_to_degraded():
    """An OK stamped 100s ago is NOT a live OK: the router discounts it
    past MXNET_HEALTH_STALE_S and steers to the freshly-OK replica."""
    stale = _StubClient(_OK, stats={
        "health": {"status": "OK", "ts": time.time() - 100.0},
        "queue_depth": 0, "queue_limit": 8, "version": 1})
    fresh = _StubClient(_OK, stats={
        "health": {"status": "OK", "ts": time.time()},
        "queue_depth": 0, "queue_limit": 8, "version": 1})
    fl, stubs, _ = _stub_fleet({"stale": stale, "fresh": fresh},
                               retries=0, stale_s=30.0)
    states = fl.poll_once()
    assert states == {"stale": "DEGRADED", "fresh": "OK"}
    sb = fl.scoreboard()
    assert sb["stale"]["verdict_age_s"] >= 99.0
    for _ in range(4):
        fl.predict(np.zeros((1, FEAT), np.float32))
    assert stubs["fresh"].calls == 4 and stubs["stale"].calls == 0


def test_poll_quarantines_unreachable_replica():
    dead = _StubClient(_OK)          # stats raises (stub has none)
    live = _StubClient(_OK, stats={
        "health": {"status": "OK", "ts": time.time()},
        "queue_depth": 2, "queue_limit": 8, "version": 7})
    fl, _, _ = _stub_fleet({"dead": dead, "live": live}, retries=0)
    states = fl.poll_once()
    assert states["dead"] == "DEAD" and states["live"] == "OK"
    sb = fl.scoreboard()
    assert sb["live"]["queue_depth"] == 2 and sb["live"]["version"] == 7


# -- drain / roster -----------------------------------------------------------
def test_observe_roster_drains_departed_and_adds_joined():
    fl, _, _ = _stub_fleet({"a": _OK, "b": _OK}, retries=0)
    diff = fl.observe_roster(["b", "c"])
    assert diff == {"added": ["c"], "removed": ["a"]}
    sb = fl.scoreboard()
    assert sb["a"]["state"] == "DRAINING"
    assert "c" in sb and sb["c"]["state"] == "OK"
    # reconciliation is idempotent
    assert fl.observe_roster(["b", "c"]) == {"added": [], "removed": []}


def test_drain_is_sticky_server_side():
    """Operator drain travels over the wire: the replica flags itself
    in serving_stats, so a SECOND fleet (different process in prod)
    observes the drain on its next poll."""
    rep = _replica(max_wait_s=0.0)
    uri = f"127.0.0.1:{rep.port}"
    fl1 = FleetClient([uri], stats_interval=0, connect_timeout=10.0)
    fl2 = FleetClient([uri], stats_interval=0, connect_timeout=10.0)
    try:
        fl1.predict(np.zeros((1, FEAT), np.float32))
        fl1.drain(uri)
        with pytest.raises(FleetError, match="no eligible"):
            fl1.predict(np.zeros((1, FEAT), np.float32))
        assert fl2.poll_once()[uri] == "DRAINING"
        fl1.undrain(uri)
        outs = fl1.predict(np.zeros((1, FEAT), np.float32))
        assert outs[0].shape == (1, HIDDEN)
        assert fl2.poll_once()[uri] != "DRAINING"
    finally:
        fl1.close()
        fl2.close()
        rep.stop()


# -- canary -------------------------------------------------------------------
def _armed_canary(p99_regression):
    """Stub fleet with an active canary on 'can'; cohort windows filled
    to the min sample count, regression injected on the last canary
    sample."""
    fl, stubs, ft = _stub_fleet({"base": _OK, "can": _OK},
                                canary_min_n=8, canary_fraction=0.5)
    fl.start_canary(["can"], refresh=False)
    for _ in range(8):
        fl._note_sample("baseline", 0.010, ok=True)
    for i in range(8):
        if p99_regression:
            fl._note_sample("canary", 0.100, ok=True)   # 10x the p99
        else:
            fl._note_sample("canary", 0.010, ok=(i < 4))  # 50% errors
    return fl, stubs


def test_canary_p99_regression_rolls_back():
    health.reconfigure()
    profiler.reset_channel_counts()
    fl, _ = _armed_canary(p99_regression=True)
    assert not fl.canary_active
    assert fl.last_rollback["reasons"] == ["p99"]
    assert fl.last_rollback["canary_p99_ms"] == 100.0
    sb = fl.scoreboard()
    assert sb["can"]["state"] == "DRAINING" and not sb["can"]["canary"]
    assert profiler.channel_counts()["fleet.rollback"] == 1
    ev = [e for e in health.events() if e["kind"] == "canary_rollback"]
    assert ev and ev[-1]["uris"] == ["can"]
    # post-rollback traffic goes ONLY to the baseline
    for _ in range(4):
        fl.predict(np.zeros((1, FEAT), np.float32))
    assert fl.scoreboard()["base"]["routes"] >= 4


def test_canary_error_rate_regression_rolls_back():
    fl, _ = _armed_canary(p99_regression=False)
    assert not fl.canary_active
    assert "error_rate" in fl.last_rollback["reasons"]
    rep = fl.canary_report()
    assert rep["canary"]["err"] == 4 and rep["baseline"]["err"] == 0


def test_canary_needs_both_cohorts_before_judging():
    """No verdict before BOTH cohorts hit the minimum sample count —
    8 slow canary samples alone must not trigger anything."""
    fl, _, _ = _stub_fleet({"base": _OK, "can": _OK}, canary_min_n=8)
    fl.start_canary(["can"], refresh=False)
    for _ in range(8):
        fl._note_sample("canary", 0.100, ok=True)
    assert fl.canary_active and fl.last_rollback is None


def test_canary_routes_fraction_with_tagged_op():
    import random
    fl, stubs, _ = _stub_fleet({"base": _OK, "can": _OK},
                               canary_fraction=0.5, canary_min_n=10 ** 6)
    fl._rng = random.Random(3)
    fl.start_canary(["can"], refresh=False)
    for _ in range(40):
        fl.predict(np.zeros((1, FEAT), np.float32))
    # the canary cohort got real traffic, all of it canary-TAGGED ops
    assert 5 <= stubs["can"].calls <= 35
    assert stubs["can"].canary_calls == stubs["can"].calls
    assert stubs["base"].canary_calls == 0
    assert stubs["base"].calls + stubs["can"].calls == 40


def test_canary_promote_dissolves_cohorts():
    fl, stubs, _ = _stub_fleet({"base": _OK, "can": _OK},
                               canary_min_n=10 ** 6)
    replies = fl.start_canary(["can"], refresh=True)
    assert replies["can"]["refreshed"] is True
    promoted = fl.promote_canary()
    assert set(promoted) == {"base"}
    assert not fl.canary_active
    sb = fl.scoreboard()
    assert not sb["can"]["canary"] and not sb["can"]["draining"]
    with pytest.raises(MXNetError, match="promote"):
        fl.promote_canary()


# -- live-replica integration -------------------------------------------------
def test_fleet_over_two_replicas_end_to_end():
    """Two real replicas, one fleet: correct outputs, traffic on both,
    per-replica routing counters visible in the profiler."""
    profiler.reset_channel_counts()
    reps = [_replica(max_wait_s=0.0) for _ in range(2)]
    uris = [f"127.0.0.1:{r.port}" for r in reps]
    fl = FleetClient(uris, stats_interval=0, connect_timeout=10.0)
    try:
        assert set(fl.poll_once().values()) == {"OK"}
        x = np.random.RandomState(5).randn(3, FEAT).astype(np.float32)
        want = _ref_softmax(x, _params())
        for _ in range(8):
            outs = fl.predict({'data': x})
            np.testing.assert_allclose(outs[0], want,
                                       rtol=1e-5, atol=1e-6)
        routed = profiler.fleet_route_counts()
        assert set(routed) == set(uris)
        assert all(v > 0 for v in routed.values())
        assert sum(routed.values()) == 8
    finally:
        fl.close()
        for r in reps:
            r.stop()


def test_fleet_routes_around_blackholed_replica():
    """The acceptance gray failure, in-process: the replica keeps
    accepting and heartbeating but never replies.  Liveness says OK;
    only the fleet's reply timeout catches it — the attempt times out,
    the replica is quarantined, and the caller sees a typed error that
    NAMES the silent replica."""
    faultinject.reset()
    rep = _replica(max_wait_s=0.0)
    uri = f"127.0.0.1:{rep.port}"
    fl = FleetClient([uri], stats_interval=0, connect_timeout=10.0,
                     retries=1, attempt_s=0.5, deadline_s=5.0,
                     backoff_ms=1.0, backoff_max_ms=1.0, jitter=0.0)
    try:
        fl.predict(np.zeros((1, FEAT), np.float32))   # warm, replies on
        with faultinject.blackhole_after_replies(0):
            with pytest.raises(FleetError) as ei:
                fl.predict(np.zeros((1, FEAT), np.float32))
            assert uri in str(ei.value)
            assert isinstance(ei.value.__cause__, PredictTimeout)
            assert faultinject.stats()["replies_blackholed"] >= 1
        sb = fl.scoreboard()
        assert sb[uri]["state"] == "DEAD" and sb[uri]["timeouts"] >= 1
    finally:
        faultinject.reset()
        fl.close()
        rep.stop()


def test_fleet_storm_with_one_busy_replica_zero_failures():
    """16 concurrent callers against a healthy replica plus one that
    sheds EVERYTHING: every request succeeds (retried onto the healthy
    one), nothing leaks to callers."""
    healthy = _replica(max_wait_s=0.0, queue_depth=256)
    shedding = _replica(max_wait_s=0.0, queue_depth=0)
    uris = [f"127.0.0.1:{healthy.port}", f"127.0.0.1:{shedding.port}"]
    fl = FleetClient(uris, stats_interval=0, connect_timeout=10.0,
                     retries=3, backoff_ms=1.0, backoff_max_ms=5.0)
    x = np.random.RandomState(6).randn(2, FEAT).astype(np.float32)
    want = _ref_softmax(x, _params())
    errors = []

    def storm():
        try:
            outs = fl.predict({'data': x})
            np.testing.assert_allclose(outs[0], want,
                                       rtol=1e-5, atol=1e-6)
        except Exception as exc:  # noqa: BLE001 — the assertion IS zero
            errors.append(exc)

    try:
        threads = [threading.Thread(target=storm) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors, errors
        sb = fl.scoreboard()
        assert sb[uris[0]]["routes"] >= 16     # everyone ended here
    finally:
        fl.close()
        healthy.stop()
        shedding.stop()
