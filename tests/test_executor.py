"""Executor fwd/bwd tests (reference: tests/python/unittest/test_executor.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def test_bind_simple_fwd_bwd():
    a = sym.Variable('a')
    b = sym.Variable('b')
    c = a * b
    ex = c.simple_bind(a=(4,), b=(4,), grad_req='write')
    av = np.array([1., 2., 3., 4.], np.float32)
    bv = np.array([5., 6., 7., 8.], np.float32)
    ex.arg_dict['a']._set_data(av)
    ex.arg_dict['b']._set_data(bv)
    ex.forward(is_train=True)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), av * bv)
    ex.backward(out_grads=mx.nd.array(np.ones(4, np.float32)))
    np.testing.assert_allclose(ex.grad_dict['a'].asnumpy(), bv)
    np.testing.assert_allclose(ex.grad_dict['b'].asnumpy(), av)


def test_grad_req_add():
    a = sym.Variable('a')
    c = a * a
    ex = c.simple_bind(a=(3,), grad_req='add')
    ex.arg_dict['a']._set_data(np.array([1., 2., 3.], np.float32))
    for _ in range(2):
        ex.forward(is_train=True)
        ex.backward(out_grads=mx.nd.array(np.ones(3, np.float32)))
    np.testing.assert_allclose(ex.grad_dict['a'].asnumpy(),
                               2 * 2 * np.array([1., 2., 3.]))


def test_grad_req_null():
    a = sym.Variable('a')
    b = sym.Variable('b')
    c = a * b
    ex = c.simple_bind(a=(2,), b=(2,), grad_req={'a': 'write', 'b': 'null'})
    ex.arg_dict['a']._set_data(np.ones(2, np.float32))
    ex.arg_dict['b']._set_data(np.full(2, 3., np.float32))
    ex.forward(is_train=True)
    ex.backward(out_grads=mx.nd.array(np.ones(2, np.float32)))
    np.testing.assert_allclose(ex.grad_dict['a'].asnumpy(), [3., 3.])
    assert ex.grad_dict['b'] is None


def test_forward_kwargs_update():
    a = sym.Variable('a')
    c = a * 2.0
    ex = c.simple_bind(a=(2,))
    ex.forward(a=mx.nd.array(np.array([1., 2.], np.float32)))
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), [2., 4.])
    ex.forward(a=mx.nd.array(np.array([3., 4.], np.float32)))
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), [6., 8.])


def test_dropout_train_vs_eval():
    d = sym.Variable('d')
    out = sym.Dropout(d, p=0.5, name='drop')
    ex = out.simple_bind(d=(100, 100))
    ex.arg_dict['d']._set_data(np.ones((100, 100), np.float32))
    ex.forward(is_train=False)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(),
                               np.ones((100, 100)))
    ex.forward(is_train=True)
    v = ex.outputs[0].asnumpy()
    assert 0.3 < (v == 0).mean() < 0.7  # roughly half dropped


def test_batchnorm_aux_update():
    d = sym.Variable('d')
    bn = sym.BatchNorm(d, name='bn', momentum=0.5)
    ex = bn.simple_bind(d=(8, 4))
    rng = np.random.RandomState(0)
    ex.arg_dict['d']._set_data(rng.randn(8, 4).astype(np.float32) + 3.0)
    ex.arg_dict['bn_gamma']._set_data(np.ones(4, np.float32))
    ex.aux_dict['bn_moving_var']._set_data(np.ones(4, np.float32))
    ex.forward(is_train=True)
    ex.outputs[0].asnumpy()
    mm = ex.aux_dict['bn_moving_mean'].asnumpy()
    assert np.all(mm > 0.5)  # moved toward batch mean (~3)
    # eval mode must use (not update) the stats
    ex.forward(is_train=False)
    ex.outputs[0].asnumpy()
    np.testing.assert_allclose(ex.aux_dict['bn_moving_mean'].asnumpy(), mm)


def test_softmax_output_implicit_loss_grad():
    data = sym.Variable('data')
    out = sym.SoftmaxOutput(data, name='sm')
    ex = out.simple_bind(data=(2, 3), sm_label=(2,), grad_req='write')
    logits = np.array([[1., 2., 3.], [0., 0., 0.]], np.float32)
    labels = np.array([2., 0.], np.float32)
    ex.arg_dict['data']._set_data(logits)
    ex.arg_dict['sm_label']._set_data(labels)
    ex.forward(is_train=True)
    ex.backward()
    p = ex.outputs[0].asnumpy()
    expect = p.copy()
    expect[0, 2] -= 1.0
    expect[1, 0] -= 1.0
    np.testing.assert_allclose(ex.grad_dict['data'].asnumpy(), expect,
                               rtol=1e-5)


def test_fused_lazy_forward_backward():
    """forward + backward must produce outputs AND grads consistently."""
    a = sym.Variable('a')
    loss = sym.sum(a * a)
    ex = loss.simple_bind(a=(5,), grad_req='write')
    ex.arg_dict['a']._set_data(np.arange(5, dtype=np.float32))
    ex.forward(is_train=True)
    ex.backward()  # ones head grad
    np.testing.assert_allclose(ex.grad_dict['a'].asnumpy(),
                               2 * np.arange(5))
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), 30.0)


def test_copy_params_from():
    a = sym.Variable('a')
    c = a * 1.0
    ex = c.simple_bind(a=(2,))
    ex.copy_params_from({'a': mx.nd.array(np.array([7., 8.], np.float32))})
    ex.forward()
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), [7., 8.])


def test_reshape():
    a = sym.Variable('a')
    c = a * 2.0
    ex = c.simple_bind(a=(2, 3))
    ex2 = ex.reshape(a=(4, 3))
    ex2.arg_dict['a']._set_data(np.ones((4, 3), np.float32))
    ex2.forward()
    assert ex2.outputs[0].shape == (4, 3)


def test_monitor_callback():
    a = sym.Variable('a')
    b = sym.sqrt(a, name='sq')
    ex = b.simple_bind(a=(2,))
    seen = []
    ex.set_monitor_callback(lambda name, arr: seen.append(name))
    ex.arg_dict['a']._set_data(np.ones(2, np.float32))
    ex.forward()
    assert any('sq' in s for s in seen)
