"""Checkpoint/resume hardening (VERDICT r1 item 8; reference:
model.py:340 save_checkpoint, src/ndarray/ndarray.cc:826 NDArray::Save,
legacy_ndarray.v0 / save_000800.json format-stability fixtures).

Covers: sharded save/load roundtrip on the 8-device mesh, kill-and-resume
producing the identical training trajectory (params + optimizer state),
and format goldens pinning the serialization bytes.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, nd
from mxnet_tpu import symbol as sym

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          'golden')


def _mlp():
    data = sym.Variable('data')
    net = sym.FullyConnected(data, num_hidden=8, name='fc1')
    net = sym.Activation(net, act_type='relu')
    net = sym.FullyConnected(net, num_hidden=2, name='fc2')
    return sym.SoftmaxOutput(net, name='softmax')


def _data(n=120, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 4).astype('f')
    Y = (X[:, 0] * X[:, 1] > 0).astype('f')
    return X, Y


# ---------------------------------------------------------------------------
# sharded checkpoint
# ---------------------------------------------------------------------------

def test_sharded_roundtrip_replicated(tmp_path):
    params = {'w': nd.array(np.arange(12, dtype='f').reshape(3, 4)),
              'b': nd.array(np.array([1.5, -2.0], 'f'))}
    checkpoint.save_params_sharded(str(tmp_path / 'p'), params)
    loaded = checkpoint.load_params_sharded(str(tmp_path / 'p'))
    for k in params:
        np.testing.assert_array_equal(loaded[k].asnumpy(),
                                      params[k].asnumpy())


def test_sharded_roundtrip_mesh_sharded(tmp_path):
    """Params sharded over the 8-device mesh save shard-wise and
    reassemble exactly."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ('a', 'b'))
    big = np.arange(64 * 16, dtype='f').reshape(64, 16)
    arr = jax.device_put(big, NamedSharding(mesh, P('a', 'b')))
    params = {'sharded_w': nd.NDArray(arr),
              'repl': nd.array(np.ones((3,), 'f'))}
    checkpoint.save_params_sharded(str(tmp_path / 's'), params)
    loaded = checkpoint.load_params_sharded(str(tmp_path / 's'))
    np.testing.assert_array_equal(loaded['sharded_w'].asnumpy(), big)


def test_sharded_roundtrip_bf16(tmp_path):
    import jax.numpy as jnp
    a = nd.array(np.linspace(-2, 2, 32).astype('f')).astype(jnp.bfloat16)
    checkpoint.save_params_sharded(str(tmp_path / 'b'), {'w': a})
    loaded = checkpoint.load_params_sharded(str(tmp_path / 'b'))
    assert loaded['w'].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(loaded['w'].asnumpy(), np.float32),
        np.asarray(a.asnumpy(), np.float32))


def test_sharded_checkpoint_with_symbol(tmp_path):
    net = _mlp()
    prefix = str(tmp_path / 'model')
    args = {'fc1_weight': nd.array(np.ones((8, 4), 'f'))}
    aux = {'stat': nd.array(np.zeros((2,), 'f'))}
    checkpoint.save_checkpoint_sharded(prefix, 3, net, args, aux)
    s2, a2, x2 = checkpoint.load_checkpoint_sharded(prefix, 3)
    assert s2.list_arguments() == net.list_arguments()
    np.testing.assert_array_equal(a2['fc1_weight'].asnumpy(),
                                  np.ones((8, 4)))
    np.testing.assert_array_equal(x2['stat'].asnumpy(), np.zeros((2,)))


# ---------------------------------------------------------------------------
# kill-and-resume: identical trajectory
# ---------------------------------------------------------------------------

def test_kill_and_resume_identical_trajectory(tmp_path):
    X, Y = _data()
    prefix = str(tmp_path / 'ck')

    # uninterrupted run: 6 epochs
    mx.random.seed(11)
    np.random.seed(11)
    it = mx.io.NDArrayIter(X, Y, batch_size=30)
    mod_full = mx.mod.Module(_mlp(), context=mx.cpu())
    mod_full.fit(it, num_epoch=6, optimizer='sgd',
                 optimizer_params={'learning_rate': 0.1, 'momentum': 0.9},
                 initializer=mx.initializer.Xavier())
    full_params = {k: v.asnumpy()
                   for k, v in mod_full.get_params()[0].items()}

    # interrupted run: 3 epochs with checkpointing + optimizer state
    mx.random.seed(11)
    np.random.seed(11)
    it = mx.io.NDArrayIter(X, Y, batch_size=30)
    mod_a = mx.mod.Module(_mlp(), context=mx.cpu())
    mod_a.fit(it, num_epoch=3, optimizer='sgd',
              optimizer_params={'learning_rate': 0.1, 'momentum': 0.9},
              initializer=mx.initializer.Xavier())
    mod_a.save_checkpoint(prefix, 3, save_optimizer_states=True)
    del mod_a  # "kill"

    # resume in a fresh module from the checkpoint (params + opt state)
    it = mx.io.NDArrayIter(X, Y, batch_size=30)
    mod_b = mx.mod.Module.load(prefix, 3, load_optimizer_states=True,
                               context=mx.cpu())
    mod_b.fit(it, num_epoch=6, begin_epoch=3, optimizer='sgd',
              optimizer_params={'learning_rate': 0.1, 'momentum': 0.9},
              arg_params=None, aux_params=None)
    resumed_params = {k: v.asnumpy()
                      for k, v in mod_b.get_params()[0].items()}

    for k in full_params:
        np.testing.assert_allclose(resumed_params[k], full_params[k],
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f'param {k} diverged after '
                                           f'resume')


# ---------------------------------------------------------------------------
# format goldens (reference: legacy_ndarray.v0 fixtures)
# ---------------------------------------------------------------------------

def test_params_format_golden():
    """The NDArray file format must stay loadable: a golden file written
    by the current format generation is committed and re-read here."""
    path = os.path.join(GOLDEN_DIR, 'golden_params.bin')
    golden = {
        'w': np.arange(6, dtype=np.float32).reshape(2, 3),
        'b': np.array([-1.5, 2.25], np.float32),
        'i': np.array([[1, 2], [3, 4]], np.int32),
    }
    if not os.path.exists(path):  # first generation: write it
        nd.save(path, {k: nd.array(v) for k, v in golden.items()})
    loaded = nd.load(path)
    assert set(loaded) == set(golden)
    for k, v in golden.items():
        np.testing.assert_array_equal(loaded[k].asnumpy(), v)


def test_symbol_json_golden():
    """Symbol JSON format stability: the committed golden graph must
    load and keep its structure."""
    path = os.path.join(GOLDEN_DIR, 'golden_symbol.json')
    if not os.path.exists(path):
        _mlp().save(path)
    s = sym.load(path)
    assert s.list_arguments() == _mlp().list_arguments()
    assert s.list_outputs() == _mlp().list_outputs()
    # loaded graph is executable
    arg_shapes, out_shapes, _ = s.infer_shape(data=(4, 4))
    assert out_shapes[0] == (4, 2)


def test_sharded_format_golden():
    path_prefix = os.path.join(GOLDEN_DIR, 'golden_sharded.params')
    golden = np.arange(24, dtype=np.float32).reshape(4, 6)
    if not os.path.exists(path_prefix + '.index'):
        checkpoint.save_params_sharded(path_prefix,
                                       {'w': nd.array(golden)})
    loaded = checkpoint.load_params_sharded(path_prefix)
    np.testing.assert_array_equal(loaded['w'].asnumpy(), golden)


# ---------------------------------------------------------------------------
# Reference binary .params compatibility (compat_serialization.py)
# ---------------------------------------------------------------------------

def test_load_reference_legacy_v0_fixture():
    """tests/golden/legacy_ndarray.v0 is REAL bytes the original
    implementation wrote (mirrored from the reference's own test
    fixture, tests/python/unittest/test_ndarray.py:272-278 expects six
    arange(128) arrays) — mx.nd.load reads it transparently."""
    import mxnet_tpu as mx
    path = os.path.join(GOLDEN_DIR, 'legacy_ndarray.v0')
    got = mx.nd.load(path)
    assert len(got) == 6
    for a in got:
        np.testing.assert_array_equal(a.asnumpy(),
                                      np.arange(128, dtype=np.float32))


def test_reference_v2_roundtrip(tmp_path):
    """save_reference_params writes the V2 container; our reader loads
    it back bit-exactly (both directions of migration)."""
    from mxnet_tpu import compat_serialization as compat
    import mxnet_tpu as mx
    rs = np.random.RandomState(0)
    data = {
        'w': mx.nd.array(rs.randn(4, 5).astype('f')),
        'b64': mx.nd.array(np.arange(7, dtype=np.int64)),
        'u8': mx.nd.array(rs.randint(0, 255, (3, 2)).astype(np.uint8)),
    }
    path = str(tmp_path / 'ref.params')
    compat.save_reference_params(path, data)
    assert compat.is_reference_format(path)
    back = mx.nd.load(path)    # auto-detected
    assert set(back) == set(data)
    for k in data:
        a, b = data[k].asnumpy(), back[k].asnumpy()
        assert a.dtype == b.dtype, k
        np.testing.assert_array_equal(a, b)


def test_reference_format_positional_list(tmp_path):
    from mxnet_tpu import compat_serialization as compat
    import mxnet_tpu as mx
    arrs = [mx.nd.array(np.full((2, 2), i, np.float32)) for i in range(3)]
    path = str(tmp_path / 'ref_list.params')
    compat.save_reference_params(path, arrs)
    back = mx.nd.load(path)
    assert isinstance(back, list) and len(back) == 3
    np.testing.assert_array_equal(back[2].asnumpy(),
                                  np.full((2, 2), 2, np.float32))


def test_reference_bf16_upcasts_on_save(tmp_path):
    from mxnet_tpu import compat_serialization as compat
    import jax.numpy as jnp
    import mxnet_tpu as mx
    a = mx.nd.array(np.arange(4, dtype=np.float32))
    a._set_data(a._data.astype(jnp.bfloat16))
    path = str(tmp_path / 'bf16.params')
    compat.save_reference_params(path, {'x': a})
    back = mx.nd.load(path)
    assert back['x'].asnumpy().dtype == np.float32
    np.testing.assert_array_equal(back['x'].asnumpy(),
                                  np.arange(4, dtype=np.float32))


def test_full_reference_checkpoint_migration(tmp_path):
    """End-to-end migration: a checkpoint whose SYMBOL is the
    reference's own v0.8 JSON fixture and whose PARAMS are written in
    the reference binary container loads through the standard
    mx.model.load_checkpoint entry and trains/infers."""
    import shutil
    import mxnet_tpu as mx
    from mxnet_tpu import compat_serialization as compat
    from mxnet_tpu.io import DataBatch

    prefix = str(tmp_path / 'legacy')
    shutil.copy(os.path.join(GOLDEN_DIR, 'reference_save_000800.json'),
                prefix + '-symbol.json')

    # params in the REFERENCE binary format, arg:/aux: prefixed exactly
    # as the reference's save_checkpoint wrote them (model.py:340)
    sym = mx.sym.load(prefix + '-symbol.json')
    mod = mx.mod.Module(sym, label_names=('softmax_label',))
    mod.bind(data_shapes=[('data', (4, 10))],
             label_shapes=[('softmax_label', (4,))])
    mx.random.seed(3)
    mod.init_params(mx.initializer.Xavier())
    arg, aux = mod.get_params()
    blob = {('arg:%s' % k): v for k, v in arg.items()}
    blob.update({('aux:%s' % k): v for k, v in aux.items()})
    compat.save_reference_params(prefix + '-0007.params', blob)

    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 7)
    assert sym2 is not None
    assert set(arg2) == set(arg) and set(aux2) == set(aux)

    mod2 = mx.mod.Module(sym2, label_names=('softmax_label',))
    mod2.bind(data_shapes=[('data', (4, 10))],
              label_shapes=[('softmax_label', (4,))])
    mod2.set_params(arg2, aux2)
    x = np.random.RandomState(1).rand(4, 10).astype('f')
    batch = DataBatch([mx.nd.array(x)], [mx.nd.zeros((4,))])
    mod.forward(batch, is_train=False)
    mod2.forward(batch, is_train=False)
    np.testing.assert_allclose(mod2.get_outputs()[0].asnumpy(),
                               mod.get_outputs()[0].asnumpy(),
                               rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# async checkpointing (device-side snapshot + background write)
# ---------------------------------------------------------------------------
def test_async_checkpoint_snapshot_survives_donation(tmp_path):
    """The async save snapshots params BEFORE returning; later fused
    update steps (which DONATE the live param buffers) must not corrupt
    the bytes being written in the background."""
    X, Y = _data()
    it = mx.io.NDArrayIter(X, Y, batch_size=30)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1,
                                         'momentum': 0.9})
    b = next(iter(it))
    mod.forward(b, is_train=True)
    mod.update()
    at_save = {k: v.asnumpy().copy()
               for k, v in mod.get_params()[0].items()}

    ck = checkpoint.AsyncCheckpointer()
    args, aux = mod._exec.arg_dict, mod._exec.aux_dict
    upd = {n: args[n] for n in mod._update_names()}
    ck.save_params(str(tmp_path / "async.params"), upd)
    for _ in range(5):  # donated steps overwrite the live buffers
        mod.forward(b, is_train=True)
        mod.update()
    ck.wait()
    loaded = checkpoint.load_params_sharded(str(tmp_path / "async.params"))
    for k, v in loaded.items():
        np.testing.assert_array_equal(v.asnumpy(), at_save[k], err_msg=k)
    # and the training really moved on past the snapshot
    moved = mod.get_params()[0]
    assert any(not np.array_equal(moved[k].asnumpy(), at_save[k])
               for k in at_save)


def test_async_checkpoint_serializes_saves_and_reports_errors(tmp_path):
    ck = checkpoint.AsyncCheckpointer()
    p1 = {"w": nd.array(np.arange(6, dtype='f').reshape(2, 3))}
    ck.save_params(str(tmp_path / "a.params"), p1)
    p2 = {"w": nd.array(np.ones((2, 3), 'f'))}
    ck.save_params(str(tmp_path / "b.params"), p2)  # waits for a first
    ck.wait()
    a = checkpoint.load_params_sharded(str(tmp_path / "a.params"))
    b = checkpoint.load_params_sharded(str(tmp_path / "b.params"))
    np.testing.assert_array_equal(a["w"].asnumpy(),
                                  p1["w"].asnumpy())
    np.testing.assert_array_equal(b["w"].asnumpy(), 1.0)
    # a background failure surfaces at wait()
    ck.save_params(str(tmp_path / "nodir" / "sub" / "x.params"),
                   p1)
    with pytest.raises(Exception):
        ck.wait()
    # the checkpointer stays usable after the failure
    ck.save_params(str(tmp_path / "c.params"), p2)
    ck.wait()


def test_async_checkpoint_epoch_api(tmp_path):
    ck = checkpoint.AsyncCheckpointer()
    net = _mlp()
    args = {"fc1_weight": nd.array(np.ones((8, 4), 'f'))}
    aux = {"bn_mean": nd.array(np.zeros((8,), 'f'))}
    ck.save_checkpoint(str(tmp_path / "ck"), 3, net, args, aux)
    ck.wait()
    s, a, x = checkpoint.load_checkpoint_sharded(str(tmp_path / "ck"), 3)
    assert s is not None
    np.testing.assert_array_equal(a["fc1_weight"].asnumpy(), 1.0)
    np.testing.assert_array_equal(x["bn_mean"].asnumpy(), 0.0)


def test_do_checkpoint_sharded_async_through_fit(tmp_path):
    """The fit() epoch callback path with sharded_async: epochs only pay
    the snapshot; shards land in the background; the final epoch loads
    back bit-exact after wait()."""
    X, Y = _data()
    prefix = str(tmp_path / "ack")
    it = mx.io.NDArrayIter(X, Y, batch_size=30)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    cb = mx.callback.do_checkpoint(prefix, sharded_async=True)
    mod.fit(it, num_epoch=3, optimizer='sgd',
            optimizer_params={'learning_rate': 0.1},
            initializer=mx.initializer.Xavier(),
            epoch_end_callback=cb)
    cb.checkpointer.wait()
    final = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    s, arg, aux = checkpoint.load_checkpoint_sharded(prefix, 3)
    assert s is not None
    assert set(arg) == set(final)
    for k in final:
        np.testing.assert_array_equal(arg[k].asnumpy(), final[k],
                                      err_msg=k)
