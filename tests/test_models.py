"""Model zoo coverage: every builder constructs, infers shape, and runs one
forward/backward on tiny inputs (mirrors reference symbols/ being exercised
by example configs + test_forward.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models


SMALL = [
    ("mlp", dict(num_classes=10), (2, 1, 28, 28)),
    ("lenet", dict(num_classes=10), (2, 1, 28, 28)),
    ("resnet", dict(num_layers=18, num_classes=10,
                    image_shape="3,32,32"), (2, 3, 32, 32)),
    pytest.param("resnet", dict(num_layers=50, num_classes=10,
                                image_shape="3,64,64"), (1, 3, 64, 64),
                 marks=pytest.mark.slow),  # deep-variant sweep; CI tier
    pytest.param("resnext", dict(num_layers=50, num_classes=10,
                                 image_shape="3,64,64", num_group=4),
                 (1, 3, 64, 64), marks=pytest.mark.slow),
    ("mobilenet", dict(num_classes=10, multiplier=0.25), (1, 3, 64, 64)),
    ("squeezenet", dict(num_classes=10), (1, 3, 64, 64)),
]

LARGE = [
    ("alexnet", dict(num_classes=1000), (1, 3, 224, 224)),
    ("densenet", dict(num_layers=121, num_classes=1000), (1, 3, 224, 224)),
    ("vgg", dict(num_layers=11, num_classes=1000), (1, 3, 224, 224)),
    ("inception-bn", dict(num_classes=1000), (1, 3, 224, 224)),
    ("inception-v3", dict(num_classes=1000), (1, 3, 299, 299)),
]


@pytest.mark.parametrize("net,kwargs,dshape", SMALL)
def test_small_models_forward_backward(net, kwargs, dshape):
    symbol = models.get_symbol(net, **kwargs)
    arg_shapes, out_shapes, _ = symbol.infer_shape(data=dshape)
    assert out_shapes[0] == (dshape[0], kwargs["num_classes"])
    ex = symbol.simple_bind(mx.cpu(), data=dshape,
                            softmax_label=(dshape[0],))
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    assert out.shape == (dshape[0], kwargs["num_classes"])
    assert np.allclose(out.sum(axis=1), 1.0, atol=1e-4)
    ex.backward()
    # every trainable arg got a gradient
    for name, g in ex.grad_dict.items():
        if name in ("data", "softmax_label"):
            continue
        assert np.isfinite(g.asnumpy()).all(), name


@pytest.mark.parametrize("net,kwargs,dshape", LARGE)
def test_large_models_shape_only(net, kwargs, dshape):
    symbol = models.get_symbol(net, **kwargs)
    arg_shapes, out_shapes, _ = symbol.infer_shape(data=dshape)
    assert out_shapes[0] == (dshape[0], kwargs["num_classes"])


def test_resnet50_imagenet_shapes():
    symbol = models.resnet(num_layers=50, num_classes=1000,
                           image_shape="3,224,224")
    args = symbol.list_arguments()
    arg_shapes, out_shapes, _ = symbol.infer_shape(data=(2, 3, 224, 224))
    n_params = sum(int(np.prod(s)) for name, s in zip(args, arg_shapes)
                   if name not in ("data", "softmax_label"))
    # ResNet-50 ~25.5M params (reference zoo resnet-50 checkpoint size)
    assert 24e6 < n_params < 27e6, n_params


def test_unknown_network():
    with pytest.raises(ValueError):
        models.get_symbol("nonexistent")


def test_s2d_stem_equivalent_to_conv7():
    """stem='s2d' (space-to-depth, MLPerf-TPU trick) computes the SAME
    function as the reference 7x7/s2 stem once weights are mapped through
    space_to_depth_stem_weight."""
    from mxnet_tpu.models.resnet import space_to_depth_stem_weight
    rs = np.random.RandomState(3)
    B = 2
    x = rs.uniform(-1, 1, (B, 3, 64, 64)).astype('f')
    kw = dict(num_layers=18, num_classes=10, image_shape="3,64,64")
    ref = models.resnet(stem="conv7", **kw)
    s2d = models.resnet(stem="s2d", **kw)

    ex1 = ref.simple_bind(mx.cpu(), data=x.shape, softmax_label=(B,),
                          grad_req='null')
    for name, arr in ex1.arg_dict.items():
        if name in ('data', 'softmax_label'):
            continue
        arr[:] = rs.uniform(-0.05, 0.05, arr.shape).astype('f')
    ex2 = s2d.simple_bind(mx.cpu(), data=x.shape, softmax_label=(B,),
                          grad_req='null')
    for name, arr in ex2.arg_dict.items():
        if name in ('data', 'softmax_label'):
            continue
        if name == 'conv0_weight':
            arr[:] = space_to_depth_stem_weight(
                ex1.arg_dict['conv0_weight'].asnumpy())
        else:
            arr[:] = ex1.arg_dict[name].asnumpy()

    ex1.arg_dict['data'][:] = x
    ex2.arg_dict['data'][:] = x
    o1 = ex1.forward(is_train=False)[0].asnumpy()
    o2 = ex2.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(o1, o2, rtol=1e-4, atol=1e-5)


def test_vit_trains_and_gqa():
    """ViT builder (models/vit.py): non-causal flash attention blocks,
    patch conv, GAP head — trains a small net above chance on a linearly
    separable toy task; GQA variant builds too."""
    rng = np.random.RandomState(0)
    n, nc = 64, 4
    y = rng.randint(0, nc, (n,)).astype('f')
    # class-dependent mean image: trivially learnable
    x = rng.randn(n, 3, 16, 16).astype('f') * 0.1
    for i in range(n):
        x[i] += int(y[i]) * 0.5

    net = models.vit(nc, image_shape=(3, 16, 16), patch_size=8,
                     num_layers=1, d_model=32, num_heads=4,
                     num_kv_heads=2)
    mod = mx.mod.Module(net)
    it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True)
    mx.random.seed(5)
    mod.fit(it, num_epoch=12, optimizer='adam',
            optimizer_params={'learning_rate': 3e-3},
            initializer=mx.initializer.Xavier(),
            eval_metric='acc')
    it.reset()
    metric = mx.metric.Accuracy()
    mod.score(it, metric)
    acc = dict(metric.get_name_value())['accuracy']
    assert acc > 0.7, acc


def test_nhwc_layout_matches_nchw():
    """layout='NHWC' (channels-last activation path, MLPerf-TPU
    convention) computes the SAME function and gradients as the default
    NCHW graph from identical (layout-agnostic OIHW) weights — both
    stems, forward and backward."""
    rs = np.random.RandomState(7)
    B = 2
    x = rs.uniform(-1, 1, (B, 3, 64, 64)).astype('f')
    y = rs.randint(0, 10, (B,)).astype('f')
    for stem in ("conv7", "s2d"):
        kw = dict(num_layers=18, num_classes=10, image_shape="3,64,64",
                  stem=stem)
        nchw = models.resnet(layout="NCHW", **kw)
        nhwc = models.resnet(layout="NHWC", **kw)
        ex1 = nchw.simple_bind(mx.cpu(), data=x.shape, softmax_label=(B,),
                               grad_req='write')
        for name, arr in ex1.arg_dict.items():
            if name in ('data', 'softmax_label'):
                continue
            arr[:] = rs.uniform(-0.05, 0.05, arr.shape).astype('f')
        ex2 = nhwc.simple_bind(mx.cpu(), data=x.shape, softmax_label=(B,),
                               grad_req='write')
        for name, arr in ex2.arg_dict.items():
            if name in ('data', 'softmax_label'):
                continue
            assert arr.shape == ex1.arg_dict[name].shape, name
            arr[:] = ex1.arg_dict[name].asnumpy()
        for ex in (ex1, ex2):
            ex.arg_dict['data'][:] = x
            ex.arg_dict['softmax_label'][:] = y
        o1 = ex1.forward(is_train=True)[0].asnumpy()
        o2 = ex2.forward(is_train=True)[0].asnumpy()
        np.testing.assert_allclose(o1, o2, rtol=1e-4, atol=1e-5)
        ex1.backward()
        ex2.backward()
        for name in ex1.grad_dict:
            if name in ('data', 'softmax_label'):
                continue
            g1 = ex1.grad_dict[name].asnumpy()
            g2 = ex2.grad_dict[name].asnumpy()
            np.testing.assert_allclose(
                g1, g2, rtol=2e-3, atol=2e-5,
                err_msg=f"{stem} grad mismatch for {name}")
