"""Model zoo coverage: every builder constructs, infers shape, and runs one
forward/backward on tiny inputs (mirrors reference symbols/ being exercised
by example configs + test_forward.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models


SMALL = [
    ("mlp", dict(num_classes=10), (2, 1, 28, 28)),
    ("lenet", dict(num_classes=10), (2, 1, 28, 28)),
    ("resnet", dict(num_layers=18, num_classes=10,
                    image_shape="3,32,32"), (2, 3, 32, 32)),
    ("resnet", dict(num_layers=50, num_classes=10,
                    image_shape="3,64,64"), (1, 3, 64, 64)),
    ("resnext", dict(num_layers=50, num_classes=10,
                     image_shape="3,64,64", num_group=4), (1, 3, 64, 64)),
    ("mobilenet", dict(num_classes=10, multiplier=0.25), (1, 3, 64, 64)),
    ("squeezenet", dict(num_classes=10), (1, 3, 64, 64)),
]

LARGE = [
    ("alexnet", dict(num_classes=1000), (1, 3, 224, 224)),
    ("densenet", dict(num_layers=121, num_classes=1000), (1, 3, 224, 224)),
    ("vgg", dict(num_layers=11, num_classes=1000), (1, 3, 224, 224)),
    ("inception-bn", dict(num_classes=1000), (1, 3, 224, 224)),
    ("inception-v3", dict(num_classes=1000), (1, 3, 299, 299)),
]


@pytest.mark.parametrize("net,kwargs,dshape", SMALL)
def test_small_models_forward_backward(net, kwargs, dshape):
    symbol = models.get_symbol(net, **kwargs)
    arg_shapes, out_shapes, _ = symbol.infer_shape(data=dshape)
    assert out_shapes[0] == (dshape[0], kwargs["num_classes"])
    ex = symbol.simple_bind(mx.cpu(), data=dshape,
                            softmax_label=(dshape[0],))
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    assert out.shape == (dshape[0], kwargs["num_classes"])
    assert np.allclose(out.sum(axis=1), 1.0, atol=1e-4)
    ex.backward()
    # every trainable arg got a gradient
    for name, g in ex.grad_dict.items():
        if name in ("data", "softmax_label"):
            continue
        assert np.isfinite(g.asnumpy()).all(), name


@pytest.mark.parametrize("net,kwargs,dshape", LARGE)
def test_large_models_shape_only(net, kwargs, dshape):
    symbol = models.get_symbol(net, **kwargs)
    arg_shapes, out_shapes, _ = symbol.infer_shape(data=dshape)
    assert out_shapes[0] == (dshape[0], kwargs["num_classes"])


def test_resnet50_imagenet_shapes():
    symbol = models.resnet(num_layers=50, num_classes=1000,
                           image_shape="3,224,224")
    args = symbol.list_arguments()
    arg_shapes, out_shapes, _ = symbol.infer_shape(data=(2, 3, 224, 224))
    n_params = sum(int(np.prod(s)) for name, s in zip(args, arg_shapes)
                   if name not in ("data", "softmax_label"))
    # ResNet-50 ~25.5M params (reference zoo resnet-50 checkpoint size)
    assert 24e6 < n_params < 27e6, n_params


def test_unknown_network():
    with pytest.raises(ValueError):
        models.get_symbol("nonexistent")
