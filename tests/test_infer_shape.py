"""Bidirectional / partial shape inference
(model: tests/python/unittest/test_infer_shape.py — 0 dims are unknowns
resolved by constraints anywhere in the graph)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


def _mlp2():
    data = mx.sym.Variable('data')
    out = mx.sym.FullyConnected(data=data, name='fc1', num_hidden=1000)
    out = mx.sym.Activation(data=out, act_type='relu')
    out = mx.sym.FullyConnected(data=out, name='fc2', num_hidden=10)
    return out


def test_mlp2_infer_shape():
    out = _mlp2()
    arg_shapes, out_shapes, _ = out.infer_shape(data=(100, 100))
    d = dict(zip(out.list_arguments(), arg_shapes))
    assert out_shapes == [(100, 10)]
    assert d['fc1_weight'] == (1000, 100)
    assert d['fc1_bias'] == (1000,)
    assert d['fc2_weight'] == (10, 1000)
    assert d['fc2_bias'] == (10,)


def test_mlp2_infer_error():
    out = _mlp2()
    with pytest.raises(MXNetError):
        out.infer_shape(data=(100, 100), fc1_weight=(1, 100))


def test_backward_infer():
    """Unknown weight pinned through _identity_with_attr_like_rhs + FC
    (reference: test_infer_shape.py:48)."""
    w = mx.sym.Variable("weight")
    wshift = mx.sym.Variable("wshift", shape=(1,))
    data = mx.sym.Variable("data")
    wt = mx.sym.broadcast_add(w, wshift)
    wt = mx.sym._identity_with_attr_like_rhs(wt, w)
    net = mx.sym.FullyConnected(data=data, weight=wt, num_hidden=11,
                                no_bias=True)
    arg_shapes, _, _ = net.infer_shape(data=(7, 100))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d['weight'] == (11, 100)


def test_incomplete_infer_elewise():
    a = mx.sym.Variable('a', shape=(0, 10))
    b = mx.sym.Variable('b', shape=(12, 0))
    c = a + b
    arg_shapes, _, _ = c.infer_shape()
    d = dict(zip(c.list_arguments(), arg_shapes))
    assert d['a'] == (12, 10)
    assert d['b'] == (12, 10)


def test_incomplete_infer_mlp():
    a = mx.sym.Variable('a', shape=(0, 10))
    b = mx.sym.FullyConnected(data=a, num_hidden=21)
    c = mx.sym.Variable('c', shape=(5, 0))
    d = b + c
    arg_shapes, _, _ = d.infer_shape()
    sh = dict(zip(d.list_arguments(), arg_shapes))
    assert sh['a'] == (5, 10)
    assert sh['c'] == (5, 21)


def test_incomplete_infer_slicechannel():
    a = mx.sym.Variable('a', shape=(0, 10))
    b = mx.sym.SliceChannel(data=a, num_outputs=10, axis=1,
                            squeeze_axis=True)
    c = mx.sym.Variable('c', shape=(5,))
    d = b[1] + c
    arg_shapes, _, _ = d.infer_shape()
    sh = dict(zip(d.list_arguments(), arg_shapes))
    assert sh['a'] == (5, 10)

    a = mx.sym.Variable('a', shape=(0, 15, 0))
    b = mx.sym.SliceChannel(data=a, num_outputs=3, squeeze_axis=False)
    c = mx.sym.Variable('c', shape=(3, 5, 2))
    d = b[1] + c
    arg_shapes, _, _ = d.infer_shape()
    sh = dict(zip(d.list_arguments(), arg_shapes))
    assert sh['a'] == (3, 15, 2)


def test_incomplete_infer_convolution():
    a = mx.sym.Variable('a', shape=(0, 10, 0, 0))
    b = mx.sym.Convolution(data=a, num_filter=21, kernel=(3, 3),
                           dilate=(1, 1), pad=(1, 1))
    c = mx.sym.Variable('c', shape=(5, 21, 32, 32))
    d = b + c
    arg_shapes, _, _ = d.infer_shape()
    sh = dict(zip(d.list_arguments(), arg_shapes))
    assert sh['a'] == (5, 10, 32, 32)


def test_incomplete_infer_concat():
    a = mx.sym.Variable('a', shape=(0, 10))
    b = mx.sym.Variable('b', shape=(0, 5))
    c = mx.sym.Concat(a, b, num_args=2, dim=1)
    d = mx.sym.Variable('d', shape=(2, 0))
    d = d + c
    arg_shapes, _, _ = d.infer_shape()
    sh = dict(zip(d.list_arguments(), arg_shapes))
    assert sh['a'] == (2, 10)
    assert sh['b'] == (2, 5)
    assert sh['d'] == (2, 15)


def test_fc_infer_type():
    data = mx.sym.Variable('data', dtype='float16')
    out = mx.sym.FullyConnected(data=data, name='fc1', num_hidden=10)
    arg_types, out_types, _ = out.infer_type()
    d = dict(zip(out.list_arguments(), arg_types))
    assert np.dtype(d['data']) == np.float16
    assert np.dtype(out_types[0]) == np.float16


def test_partial_then_executor():
    """A partially-specified graph resolves and then binds/executes."""
    a = mx.sym.Variable('a', shape=(0, 6))
    b = mx.sym.FullyConnected(data=a, num_hidden=4)
    c = mx.sym.Variable('c', shape=(3, 0))
    d = b + c
    arg_shapes, out_shapes, _ = d.infer_shape()
    assert out_shapes == [(3, 4)]
    ex = mx.Executor.simple_bind(d, shapes={'a': (3, 6), 'c': (3, 4)})
    assert ex.forward()[0].shape == (3, 4)


def test_fc_flatten_false_higher_rank():
    """flatten=False FC keeps leading dims (regression: the prepass
    hard-coded rank-2 output)."""
    data = mx.sym.Variable('data')
    fc = mx.sym.FullyConnected(data, num_hidden=4, flatten=False)
    out = mx.sym.elemwise_add(fc, mx.sym.Variable('c', shape=(2, 3, 4)))
    arg_shapes, out_shapes, _ = out.infer_shape(data=(2, 3, 5))
    assert out_shapes == [(2, 3, 4)]


def test_concat_negative_dim():
    a = mx.sym.Variable('a')
    b = mx.sym.Variable('b')
    c = mx.sym.Concat(a, b, num_args=2, dim=-1)
    _, out_shapes, _ = c.infer_shape(a=(2, 10), b=(2, 5))
    assert out_shapes == [(2, 15)]


def test_slicechannel_negative_axis_squeeze():
    x = mx.sym.Variable('x')
    s = mx.sym.SliceChannel(x, num_outputs=2, axis=-1, squeeze_axis=True)
    d = s[0] + mx.sym.Variable('y', shape=(3, 5))
    arg_shapes, out_shapes, _ = d.infer_shape(x=(3, 5, 2))
    assert out_shapes == [(3, 5)]


def test_concat_inconsistent_dim_raises():
    """Regression: an impossible concat split must error, not produce a
    negative inferred dim."""
    a = mx.sym.Variable('a', shape=(2, 7))
    b = mx.sym.Variable('b')
    c = mx.sym.Concat(a, b, num_args=2, dim=1)
    d = c + mx.sym.Variable('e', shape=(2, 5))
    with pytest.raises(MXNetError):
        d.infer_shape()


def test_int_variable_dtype_does_not_poison_defaults():
    """An int32 index input pins itself but untyped params stay float32."""
    idx = mx.sym.Variable('idx', dtype='int32')
    emb = mx.sym.Embedding(idx, input_dim=10, output_dim=4, name='emb')
    fc = mx.sym.FullyConnected(emb, num_hidden=3, name='fc')
    arg_types, out_types, _ = fc.infer_type()
    d = dict(zip(fc.list_arguments(), arg_types))
    assert np.dtype(d['idx']) == np.int32
    assert np.dtype(d['emb_weight']) == np.float32
    assert np.dtype(d['fc_weight']) == np.float32
