"""mxnet_tpu.autotune — the measure-and-search harness (ISSUE 11).

Tier-1 coverage of the whole loop on CPU via the deterministic stub
backend:

* search-space derivation strictly from the declare_env registry
  (undeclared / tune-less / out-of-range-restricted knobs all refuse);
* searcher + cost-model determinism: same journal + same seed → the
  SAME next proposal;
* the append-only journal: resume tolerates the truncated line a
  killed sweep leaves behind;
* subprocess executor deadline/kill discipline against a deliberately
  hanging stub target;
* per-topology promotion (schema 2) incl. legacy flat-file back-compat
  and topology isolation — and bench.py's resolver loading the entry
  for ITS topology and only its topology;
* the end-to-end acceptance: a CPU sweep proposes, measures, journals,
  resumes after a kill, and promotes the measured best.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from mxnet_tpu.autotune import (CostModel, Journal, MeasureResult,
                                SubprocessExecutor, Trial, get_target,
                                load_defaults, lookup_defaults, promote,
                                space_for, topology_key)
from mxnet_tpu.autotune import stub_target
from mxnet_tpu.autotune.history import import_history
from mxnet_tpu.autotune.search import (GridSearcher, ModelSearcher,
                                       RandomSearcher, make_searcher)
from mxnet_tpu.autotune.space import axis_for, restrict_axis
from mxnet_tpu.autotune.targets import all_target_knobs, repo_root
from mxnet_tpu.base import MXNetError, declare_env, list_env_tunables

W = "MXNET_KVSTORE_WINDOW"
C = "MXNET_KVSTORE_FUSED_CHUNK"


def _stub_trial(num, window, chunk, target="stub"):
    return Trial(num=num, target=target,
                 config={W: window, C: chunk}, status="ok",
                 objective=stub_target.objective(window, chunk))


# -- space derivation ---------------------------------------------------------
def test_space_derives_from_registry():
    space = get_target("stub").space()
    assert list(space.axes) == [W, C]
    axis = space.axes[W]
    assert axis.kind == "choice" and 8 in axis.choices
    # encoding: one-hot per choice axis
    assert space.feature_width() == len(axis.choices) \
        + len(space.axes[C].choices)
    row = space.encode({W: 8, C: 4})
    assert sum(row) == 2.0 and set(row) == {0.0, 1.0}


def test_undeclared_knob_can_never_be_tuned():
    with pytest.raises(MXNetError, match="never be tuned"):
        space_for(["MXNET_NO_SUCH_KNOB_EVER"])


def test_tuneless_knob_refused():
    # declared (engine type) but carries no tune metadata
    with pytest.raises(MXNetError, match="no tune= metadata"):
        space_for(["MXNET_ENGINE_TYPE"])


def test_declare_env_tune_validation():
    with pytest.raises(MXNetError, match="min < max"):
        declare_env("MXNET_AUTOTUNE_BAD_TMP", int, 1, "tmp",
                    tune={"min": 8, "max": 2})
    with pytest.raises(MXNetError, match="choices OR a min/max"):
        declare_env("MXNET_AUTOTUNE_BAD_TMP", int, 1, "tmp",
                    tune={"choices": [1], "min": 1, "max": 2})
    assert "MXNET_AUTOTUNE_BAD_TMP" not in list_env_tunables()


def test_restriction_outside_declared_choices_refused():
    axis = axis_for(W)
    with pytest.raises(MXNetError, match="outside its declared"):
        restrict_axis(axis, [7])           # 7 is not a declared choice
    narrowed = restrict_axis(axis, ["4", "8"])   # strings coerce
    assert narrowed.choices == (4, 8)


def test_range_axis_sampling_and_encoding():
    axis = axis_for("MXNET_KVSTORE_COMPRESSION_THRESHOLD")
    assert axis.kind == "float" and axis.log
    rng = np.random.RandomState(0)
    for _ in range(20):
        v = axis.sample(rng)
        assert axis.lo <= v <= axis.hi
    lo_enc = axis.encode(axis.lo)[0]
    hi_enc = axis.encode(axis.hi)[0]
    assert lo_enc == 0.0 and hi_enc == 1.0


def test_all_builtin_target_knobs_are_declared():
    tunables = list_env_tunables()
    for target, names in all_target_knobs().items():
        for name in names:
            assert name in tunables, (target, name)


def test_tunable_but_undeclared_is_a_lint_finding(monkeypatch):
    """The env-knob rule flags a built-in target axis that names an
    unregistered knob."""
    from pathlib import Path

    from mxnet_tpu.analysis.rules.env_knobs import RULE
    from mxnet_tpu.autotune import targets as targets_mod
    bogus = dict(targets_mod.TARGETS)
    bogus["bad"] = targets_mod.Target(
        name="bad", knobs=("MXNET_NOT_DECLARED_ANYWHERE",),
        objective="value", maximize=True, doc="x", script="bench.py")
    monkeypatch.setattr(targets_mod, "TARGETS", bogus)

    class _P:
        is_package = True
        scratch = {"env-knob-reads": set()}
        files = ()
        root = Path(targets_mod.repo_root()) / "mxnet_tpu"

    found = [f for f in RULE.finalize(_P())
             if "sweeps knob MXNET_NOT_DECLARED_ANYWHERE" in f.message]
    assert found, "tunable-but-undeclared finding missing"


# -- searcher determinism -----------------------------------------------------
def test_same_journal_same_seed_same_proposal(tmp_path):
    space = get_target("stub").space()
    trials = [_stub_trial(1, 1, 1), _stub_trial(2, 8, 2),
              _stub_trial(3, 16, 8)]
    for cls in (RandomSearcher, GridSearcher, ModelSearcher):
        a = cls(space, maximize=True, seed=7).propose(trials)
        b = cls(space, maximize=True, seed=7).propose(trials)
        assert a == b, cls.__name__
    # and through a real journal round trip (json stringification)
    j = Journal(str(tmp_path / "j.jsonl"))
    for t in trials:
        j.append(t)
    s1 = ModelSearcher(space, maximize=True, seed=7)
    assert s1.propose(j.load()) == \
        ModelSearcher(space, maximize=True, seed=7).propose(trials)


def test_proposals_skip_measured_configs():
    space = get_target("stub").space()
    trials = [_stub_trial(i + 1, w, c)
              for i, (w, c) in enumerate(
                  (w, c) for w in (1, 2, 4, 8, 16, 32)
                  for c in (1, 2, 4, 8, 16))]
    # 30 of 36 configs measured: every proposal must be one of the 6 left
    left = {(w, 32) for w in (1, 2, 4, 8, 16, 32)}
    for seed in range(5):
        cand = ModelSearcher(space, maximize=True, seed=seed) \
            .propose(trials)
        assert (cand[W], cand[C]) in left


def test_grid_searcher_walks_the_grid_in_order():
    space = get_target("stub").space()
    s = GridSearcher(space, maximize=True, seed=0)
    trials = []
    seen = []
    for i in range(4):
        cfg = s.propose(trials)
        seen.append((cfg[W], cfg[C]))
        trials.append(_stub_trial(i + 1, cfg[W], cfg[C]))
    grid = [(w, c) for w in (1, 2, 4, 8, 16, 32)
            for c in (1, 2, 4, 8, 16, 32)]
    assert seen == grid[:4]


def test_unknown_strategy_refused():
    with pytest.raises(MXNetError, match="unknown strategy"):
        make_searcher("annealing", get_target("stub").space(), True, 0)


# -- cost model ---------------------------------------------------------------
def test_cost_model_learns_the_stub_bowl():
    space = get_target("stub").space()
    trials = [_stub_trial(i + 1, w, c)
              for i, (w, c) in enumerate(
                  (w, c) for w in (1, 2, 4, 8, 16, 32)
                  for c in (1, 2, 4, 8, 16, 32))]
    m = CostModel(space)
    assert m.fit(trials)
    configs = [t.config for t in trials]
    pred = m.predict(configs)
    best = configs[int(np.argmax(pred))]
    assert (best[W], best[C]) == (8, 4)     # the known optimum


def test_cost_model_needs_two_ok_trials():
    space = get_target("stub").space()
    m = CostModel(space)
    assert not m.fit([_stub_trial(1, 8, 4)])
    assert not m.fit([Trial(num=1, target="stub", config={W: 8, C: 4},
                            status="timeout", objective=None)])


# -- journal ------------------------------------------------------------------
def test_journal_resume_tolerates_truncated_line(tmp_path):
    j = Journal(str(tmp_path / "j.jsonl"))
    j.append(_stub_trial(1, 8, 4))
    j.append(_stub_trial(2, 1, 1))
    with open(j.path, "a") as f:
        f.write('{"num": 3, "target": "stub", "config": {"MXNET')  # killed
    trials = j.load()
    assert [t.num for t in trials] == [1, 2]
    assert j.next_num() == 3
    # appending after the torn line still yields parseable records
    j.append(_stub_trial(3, 2, 2))
    assert len(j.load()) == 3


def test_imported_unknown_config_does_not_shadow_defaults(tmp_path):
    """config={} marks an imported round with unknown settings: the
    searcher's dedup must NOT treat it as the registry-default config."""
    space = get_target("stub").space()
    unknown = Trial(num=1, target="stub", config={}, status="timeout",
                    objective=None)
    s = RandomSearcher(space, maximize=True, seed=0)
    assert s._measured([unknown]) == set()


# -- subprocess executor ------------------------------------------------------
def test_executor_ok_parses_last_json_line():
    target = get_target("stub")
    res = SubprocessExecutor(timeout_s=60).run(
        target.command(), {W: 8, C: 4})
    assert res.status == "ok"
    assert res.payload["value"] == 100.0
    assert target.objective_value(res.payload) == 100.0


def test_executor_kills_hanging_target():
    target = get_target("stub")
    ex = SubprocessExecutor(timeout_s=1.5)
    res = ex.run(target.command(), {W: 8, C: 4,
                                    "MXT_AUTOTUNE_STUB_SLEEP_S": "60"})
    assert res.status == "timeout"
    assert res.duration_s < 20           # killed, not waited out
    assert "SIGKILL" in res.error


def test_executor_records_crash():
    target = get_target("stub")
    res = SubprocessExecutor(timeout_s=60).run(
        target.command(), {"MXT_AUTOTUNE_STUB_CRASH": "1"})
    assert res.status == "crash"
    assert "rc=7" in res.error


# -- promotion (schema 2) -----------------------------------------------------
def test_promote_per_topology_isolation(tmp_path):
    path = str(tmp_path / "d.json")
    tpu = topology_key("TPU v5 lite")
    cpu = topology_key("cpu")
    assert promote(path, tpu, {"batch": 256}, 2332.5)
    assert lookup_defaults(path, tpu)["batch"] == 256
    assert lookup_defaults(path, cpu) == {}          # no leak
    assert lookup_defaults(path, None) == {}
    # a CPU promotion lands NEXT TO the TPU row, clobbering nothing
    assert promote(path, cpu, {"batch": 8}, 4.4)
    assert lookup_defaults(path, tpu)["batch"] == 256
    assert lookup_defaults(path, cpu)["batch"] == 8
    # MULTICHIP (8 hosts) is its own row too
    multi = topology_key("TPU v5 lite", hosts=8)
    assert promote(path, multi, {"batch": 1024}, 9000.0)
    assert lookup_defaults(path, tpu)["batch"] == 256
    assert lookup_defaults(path, multi)["batch"] == 1024


def test_promote_hysteresis_and_direction(tmp_path):
    path = str(tmp_path / "d.json")
    topo = topology_key("TPU v5 lite")
    assert promote(path, topo, {"batch": 256}, 1000.0)
    assert not promote(path, topo, {"batch": 512}, 1010.0)   # < +2%
    assert lookup_defaults(path, topo)["batch"] == 256
    assert promote(path, topo, {"batch": 512}, 1100.0)       # > +2%
    assert lookup_defaults(path, topo)["batch"] == 512
    # minimize direction (latency-style objectives)
    lat = str(tmp_path / "lat.json")
    assert promote(lat, topo, {"env": {W: 8}}, 5.0, maximize=False)
    assert not promote(lat, topo, {"env": {W: 4}}, 4.95, maximize=False)
    assert promote(lat, topo, {"env": {W: 4}}, 4.0, maximize=False)


def test_legacy_flat_defaults_back_compat(tmp_path):
    """The seed repo's flat dict reads as ONE topology — the one its
    provenance names — and no longer applies anywhere else."""
    path = str(tmp_path / "d.json")
    flat = {"batch": 256, "stem": "conv7", "opt": "sgd",
            "dtype": "bfloat16", "remat": "0",
            "promoted_from": {"value": 2332.52, "device": "TPU v5 lite"}}
    with open(path, "w") as f:
        json.dump(flat, f)
    doc = load_defaults(path)
    assert list(doc["topologies"]) == [topology_key("TPU v5 lite")]
    assert lookup_defaults(path, topology_key("TPU v5 lite"))["batch"] \
        == 256
    assert lookup_defaults(path, topology_key("cpu")) == {}
    # promoting over a legacy file keeps it, migrated
    assert promote(path, topology_key("cpu"), {"batch": 8}, 4.4)
    doc = load_defaults(path)
    assert set(doc["topologies"]) == {topology_key("TPU v5 lite"),
                                      topology_key("cpu")}


# -- bench.py resolver --------------------------------------------------------
def _load_bench():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(repo_root(), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_resolves_only_its_topology(tmp_path, monkeypatch):
    path = str(tmp_path / "d.json")
    promote(path, topology_key("cpu-stub"),
            {"batch": 64, "env": {W: 16}}, 100.0)
    monkeypatch.setenv("BENCH_DEFAULTS_PATH", path)
    for name in ("BENCH_BATCH", W):
        monkeypatch.delenv(name, raising=False)
    bench = _load_bench()
    try:
        cfg = bench._resolve_config("cpu-stub")
        assert cfg["batch"] == 64
        assert cfg["applied_env"] == {W: 16}
        assert os.environ[W] == "16"
    finally:
        os.environ.pop(W, None)
    # a DIFFERENT topology sees none of it
    cfg = bench._resolve_config("TPU v5 lite")
    assert cfg["batch"] == 256 and cfg["applied_env"] == {}
    assert W not in os.environ
    # explicit env always beats the promoted entry
    monkeypatch.setenv("BENCH_BATCH", "32")
    monkeypatch.setenv(W, "2")
    cfg = bench._resolve_config("cpu-stub")
    assert cfg["batch"] == 32
    assert cfg["applied_env"] == {} and os.environ[W] == "2"


# -- history import -----------------------------------------------------------
def test_import_history_warm_start(tmp_path):
    j = Journal(str(tmp_path / "hist.jsonl"))
    counts = import_history(j, repo_root())
    assert counts["BENCH_LOG.jsonl"] >= 10
    for n in range(1, 6):
        assert counts["BENCH_r0%d.json" % n] == 1
    trials = j.load()
    ok = [t for t in trials if t.ok]
    assert ok and all(t.config.get("BENCH_BATCH") for t in ok)
    assert max(t.objective for t in ok) > 2000       # the banked v5e rows
    # the tunnel-hang rounds import as failures with unknown config
    hangs = [t for t in trials if t.source == "BENCH_r02.json"]
    assert hangs[0].status == "timeout" and hangs[0].config == {}
    # idempotent: importing again adds nothing
    assert sum(import_history(j, repo_root()).values()) == 0
    assert len(j.load()) == len(trials)
    # the cost model starts warm from history alone: fits and prefers
    # the measured-best batch among the banked configs
    space = get_target("bench").space()
    m = CostModel(space)
    assert m.fit([t for t in trials if t.ok])


def test_imported_history_never_blocks_proposals():
    """Banked rows warm the model but must not veto re-measuring their
    configs (a new device / post-TCP_NODELAY re-baseline measures the
    historical best again on purpose)."""
    space = get_target("stub").space()
    imported = Trial(num=1, target="stub", config={W: 8, C: 4},
                     status="ok", objective=100.0,
                     source="BENCH_LOG.jsonl")
    mine = _stub_trial(2, 8, 4)
    s = RandomSearcher(space, maximize=True, seed=0)
    assert s._measured([imported]) == set()
    assert s._measured([imported, mine]) == {space.canonical(mine.config)}


def test_sweep_topology_scoping_and_effective_config():
    from mxnet_tpu.autotune.__main__ import (_effective_config,
                                             _topology_for)
    # payload-reported topology wins over re-derivation defaults
    t = Trial(num=1, target="bench", config={}, status="ok",
              objective=1.0,
              metrics={"device": "TPU v5 lite", "hosts": 1,
                       "topology": "TPU v5 lite|hosts=1|n=2|s=2"})
    assert _topology_for(t) == "TPU v5 lite|hosts=1|n=2|s=2"
    # OOM-halved batch: the journal records what really ran ...
    target = get_target("bench")
    space = target.space()
    cfg = _effective_config(
        target, space,
        {"BENCH_BATCH": 1024, "BENCH_REMAT": "0"},
        {"batch": 512, "remat": False})
    assert cfg["BENCH_BATCH"] == 512
    # ... but bench's remat=False rendering of choice "0" is NOT a
    # declared value and must not clobber the proposal
    assert cfg["BENCH_REMAT"] == "0"


# -- end-to-end acceptance ----------------------------------------------------
def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.autotune", *args],
        cwd=repo_root(), env=env, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("{")]
    assert len(line) == 1, proc.stdout      # the one-JSON-line contract
    return json.loads(line[0])


def test_end_to_end_sweep_resume_promote(tmp_path):
    """ISSUE 11 acceptance: propose → measure → journal → (killed) →
    resume → converge to the known best → promote per topology →
    bench.py loads it for that topology and only that topology."""
    journal = str(tmp_path / "trials.jsonl")
    defaults = str(tmp_path / "defaults.json")
    restrict = ("--restrict", "%s=4,8,16" % W,
                "--restrict", "%s=2,4" % C)
    # first leg: 2 trials, then the sweep "dies" mid-append
    out = _run_cli("--target", "stub", "--trials", "2", "--seed", "3",
                   "--journal", journal, "--defaults", defaults,
                   "--no-promote", *restrict)
    assert out["trials_run"] == 2
    with open(journal, "a") as f:
        f.write('{"num": 3, "target": "stub", "config"')   # torn line
    # second leg resumes: 4 more trials = exhaustive over the 6 configs
    out = _run_cli("--target", "stub", "--trials", "4", "--seed", "3",
                   "--journal", journal, "--defaults", defaults,
                   *restrict)
    assert out["trials_total"] == 6 and out["ok"] == 6
    # no config measured twice (resume skipped the first leg's work)
    trials = Journal(journal).load()
    keys = {tuple(sorted(t.config.items())) for t in trials}
    assert len(keys) == 6
    # converged to the analytic optimum and promoted it
    assert out["best_config"] == {W: 8, C: 4}
    assert out["best_objective"] == 100.0
    assert out["promoted"] is True
    topo = topology_key("cpu-stub")
    assert out["topology"] == topo
    entry = lookup_defaults(defaults, topo)
    assert entry["env"] == {W: 8, C: 4}
    assert entry["promoted_from"]["value"] == 100.0
    # bench.py picks the winner up for THIS topology only
    bench = _load_bench()
    os.environ.pop(W, None)
    os.environ.pop(C, None)
    os.environ["BENCH_DEFAULTS_PATH"] = defaults
    try:
        cfg = bench._resolve_config("cpu-stub")
        assert cfg["applied_env"] == {W: 8, C: 4}
    finally:
        os.environ.pop(W, None)
        os.environ.pop(C, None)
        os.environ.pop("BENCH_DEFAULTS_PATH", None)
    cfg = bench._resolve_config("TPU v5 lite")
    assert cfg["applied_env"] == {}
    assert W not in os.environ and C not in os.environ


def test_sweep_promotes_its_own_topology_not_imported_history(tmp_path):
    """An imported other-device row with a huge objective must neither
    become 'the winner' nor hysteresis-shadow the topology this sweep
    actually measured."""
    journal = str(tmp_path / "trials.jsonl")
    defaults = str(tmp_path / "defaults.json")
    j = Journal(journal)
    j.append(Trial(num=1, target="stub", config={W: 1, C: 1},
                   status="ok", objective=99999.0,
                   metrics={"device": "TPU v5 lite"},
                   source="BENCH_LOG.jsonl"))
    out = _run_cli("--target", "stub", "--trials", "2", "--seed", "1",
                   "--journal", journal, "--defaults", defaults,
                   "--restrict", "%s=8" % W, "--restrict", "%s=2,4" % C)
    assert out["topology"] == topology_key("cpu-stub")
    assert out["best_objective"] < 99999.0       # not the imported row
    entry = lookup_defaults(defaults, topology_key("cpu-stub"))
    assert entry["promoted_from"]["value"] == out["best_objective"]
    assert lookup_defaults(defaults, topology_key("TPU v5 lite")) == {}


@pytest.mark.slow
def test_serving_probe_measures(tmp_path):
    """The serving target's probe runs one config in a fresh process
    and lands p50/p99/QPS (the sweep's measurement backend)."""
    target = get_target("serving")
    res = SubprocessExecutor(timeout_s=240).run(
        target.command(),
        {"MXNET_SERVING_BUCKETS": "1,4,16,64",
         "MXNET_SERVING_MAX_WAIT_MS": "0.5",
         "MXT_AUTOTUNE_SERVING_REQUESTS": "64",
         "JAX_PLATFORMS": "cpu"})
    assert res.status == "ok", res.error
    assert res.payload["p99_ms"] > 0 and res.payload["qps"] > 0
    assert target.objective_value(res.payload) == res.payload["p99_ms"]


@pytest.mark.slow
def test_failover_probe_measures(tmp_path):
    """The failover target's probe kills the elastic coordinator and
    reports the rebuild-cost gauge."""
    target = get_target("failover")
    res = SubprocessExecutor(timeout_s=240).run(
        target.command(),
        {"MXNET_KVSTORE_SNAPSHOT_S": "0.25",
         "MXT_AUTOTUNE_FAILOVER_ROWS": "512",
         "JAX_PLATFORMS": "cpu"})
    assert res.status == "ok", res.error
    assert res.payload["failovers"] >= 1
    assert res.payload["failover_rebuild_s"] is not None
    assert target.objective_value(res.payload) \
        == res.payload["failover_rebuild_s"]
