"""Precision-layout guard on the COMPILED fused train step.

The round-2/3 MFU work moved BatchNorm onto a bf16 data path with fp32
statistics (docs/PERF_NOTES.md; reference contract:
src/operator/cudnn_batch_norm-inl.h — fp32 stats over a low-precision
data path).  These tests pin that contract at the StableHLO level, on
CPU, so an AMP regression (an op silently upcasting the activation
stream to fp32 between conv fusions) is caught without chip time:

* every convolution in the lowered step consumes bf16 operands;
* every large dot/dot_general does too (the fp32 ops that remain are
  statistics reductions, the softmax/loss head, and the optimizer update
  on fp32 master weights — all small or param-shaped, not
  activation-shaped).
"""
import re

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models


def _lowered_resnet_step_hlo(compute_dtype, stem="conv7",
                             num_layers=8, image_shape=(3, 28, 28)):
    import jax.numpy as jnp
    sym = models.resnet(num_classes=10, num_layers=num_layers,
                        image_shape=image_shape, stem=stem)
    mod = mx.mod.Module(sym, compute_dtype=compute_dtype and
                        jnp.dtype(compute_dtype))
    batch = 2
    it = mx.io.NDArrayIter(
        data=np.random.RandomState(0).uniform(
            -1, 1, (batch,) + tuple(image_shape)).astype(np.float32),
        label=np.zeros((batch,), np.float32), batch_size=batch)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    mod.forward(next(iter(it)), is_train=True)
    hlo = mod.fused_step_hlo()
    mod.update()
    return hlo


# one lowering serves both tests (tracing a ResNet step isn't free)
@pytest.fixture(scope="module")
def bf16_hlo():
    return _lowered_resnet_step_hlo("bfloat16")


def _op_operand_dtypes(hlo, op):
    """dtypes of tensor operands for every `op` application in the text."""
    out = []
    for m in re.finditer(r"stablehlo\.%s[^\n]*:\s*\(([^)]*)\)" % op, hlo):
        dts = re.findall(r"tensor<[^>]*?x?([a-z]+[0-9]+)>", m.group(1))
        out.append(dts)
    return out


def test_bf16_step_has_no_fp32_convolution(bf16_hlo):
    convs = _op_operand_dtypes(bf16_hlo, "convolution")
    assert convs, "no convolutions found in lowered step HLO"
    bad = [dts for dts in convs if "f32" in dts]
    assert not bad, (
        "fp32 convolutions in bf16 fused step (AMP regression): %r"
        % bad[:5])


def test_bf16_step_dots_are_bf16(bf16_hlo):
    dots = _op_operand_dtypes(bf16_hlo, "dot_general")
    assert dots, "no dot_general found in lowered step HLO"
    bad = [dts for dts in dots if "f32" in dts]
    assert not bad, (
        "fp32 dot_general in bf16 fused step (AMP regression): %r"
        % bad[:5])


def test_fp32_mode_keeps_fp32_convolution():
    hlo = _lowered_resnet_step_hlo(None)
    convs = _op_operand_dtypes(hlo, "convolution")
    assert convs and all("f32" in dts for dts in convs)


def _sweep_step_hlo(stem, remat_policy):
    """Lower the fused step in a sweep configuration (s2d stem and/or
    remat) — the exact configs tools/chip_session.sh measures; an fp32
    activation leak in one of them would waste the chip session.

    The stem only exists on the imagenet branch (height > 32,
    models/resnet.py), so this lowers a 64x64 ResNet-18 — 28x28 would
    silently test the cifar stem regardless of `stem`.
    """
    import os
    old = {k: os.environ.pop(k, None)
           for k in ("MXNET_BACKWARD_DO_MIRROR", "MXNET_REMAT_POLICY")}
    try:
        if remat_policy:
            os.environ["MXNET_BACKWARD_DO_MIRROR"] = "1"
            if remat_policy not in ("1", "full"):
                os.environ["MXNET_REMAT_POLICY"] = remat_policy
        return _lowered_resnet_step_hlo("bfloat16", stem=stem,
                                        num_layers=18,
                                        image_shape=(3, 64, 64))
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.mark.parametrize("stem,remat", [
    ("s2d", None),
    ("s2d", "save_matmuls"),
    ("s2d", "1"),       # b512_s2d_remat: the full-remat config the
                        # session actually measures pairs with s2d
])
def test_sweep_configs_keep_bf16_convs(stem, remat):
    hlo = _sweep_step_hlo(stem, remat)
    if stem == "s2d":
        # non-vacuous stem check: the s2d conv0 weight is (64, 12, 4, 4)
        assert "x12x4x4x" in hlo.replace("bf16", "").replace("f32", ""), \
            "s2d stem not present in lowered HLO"
    convs = _op_operand_dtypes(hlo, "convolution")
    assert convs, "no convolutions found in lowered step"
    for dts in convs:
        assert all(d == "bf16" for d in dts), (stem, remat, dts)
