"""Two-tower retrieval scenario (ISSUE 19): the example's towers train
through the gluon fused Trainer over ``dist_async`` — every Embedding
grad is row-sparse, so the one-list-push step rides the sparse wire —
then the live item table serves top-k through a :class:`ServingReplica`
whose weight refresh is a pure data swap (zero extra compiles).

The test imports the example module itself (the test_examples loader
idiom) so the scenario under test IS the shipped scenario, just at toy
sizes.
"""
import importlib.util
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import profiler
from mxnet_tpu.kvstore_server import KVStoreServer
from mxnet_tpu.serving import publish_version

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_two_tower():
    spec = importlib.util.spec_from_file_location(
        "two_tower_example",
        os.path.join(ROOT, "examples", "recommender", "two_tower.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _pool_hits(topk_rows, prefs):
    return [len(set(topk_rows[r]) & set(prefs[r])) > 0
            for r in range(topk_rows.shape[0])]


def test_two_tower_trains_sparse_and_serves_topk_with_live_refresh(
        monkeypatch):
    """Train over dist_async (grads ride the row-sparse wire — the
    kvstore.sparse_rows counter moves), retrieval hits the planted
    pools, a replica serves top-k from the SAME parameter server, and
    after more training a version bump + refresh changes served scores
    without a single additional compile."""
    tt = _load_two_tower()
    profiler.reset_dispatch_counts()
    ps = KVStoreServer(server_id=0, num_workers=1)
    ps.start_background()
    uri = f"127.0.0.1:{ps.port}"
    monkeypatch.setenv("MXT_SERVER_URIS", uri)
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    monkeypatch.setenv("MXNET_KVSTORE_SPARSE", "1")

    users, items, dim = 32, 64, 4
    stream = tt.make_clickstream(users, items, events=1024, pool=8, seed=3)
    ut, it = tt.build_towers(users, items, dim)
    rows0 = profiler.channel_counts().get("kvstore.sparse_rows", 0)
    trainer = tt.train(ut, it, stream, epochs=4, batch=32,
                       kvstore='dist_async', log=lambda *_: None)
    kv = trainer._kvstore
    rep = cli = None
    try:
        # the Trainer's fused step really rode the row-sparse wire
        assert profiler.channel_counts()["kvstore.sparse_rows"] > rows0
        assert tt.hit_rate(ut, it, stream[3]) >= 0.8

        rep, cli, topk = tt.serve_topk(ut, it, users, items, dim,
                                       param_servers=uri)
        got = topk(np.arange(16))   # largest serving bucket
        assert np.mean(_pool_hits(got, stream[3][:16])) >= 0.8

        probe = np.arange(8, dtype=np.float32)
        before = cli.predict(probe, name='user')[0].copy()
        compiles = profiler.dispatch_counts().get(
            "serving.predict_compile", 0)

        # keep training: server-side weights move, replica's don't (yet)
        tt.train(ut, it, stream, epochs=2, batch=32, kvstore=kv,
                 log=lambda *_: None)
        kv.barrier()
        assert cli.refresh()["refreshed"] is False   # no bump published

        v = publish_version(kv)
        r = cli.refresh()
        assert r["refreshed"] is True and r["version"] == v
        after = cli.predict(probe, name='user')[0]
        assert not np.allclose(before, after)
        # the refreshed table matches the trainer's view of the weights
        fresh = ut.weight.data().asnumpy()[probe.astype(np.int64)]
        np.testing.assert_allclose(
            fresh @ it.weight.data().asnumpy().T, after,
            rtol=1e-5, atol=1e-6)
        # hot swap: params are jit arguments, not constants
        assert profiler.dispatch_counts().get(
            "serving.predict_compile", 0) == compiles
    finally:
        if cli is not None:
            cli.close()
        if rep is not None:
            rep.stop()
        kv.close(stop_servers=False)
        ps.stop()
