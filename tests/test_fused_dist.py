"""Fused dist_async K-step driver (Module.run_steps / Trainer.step_k on
update-on-kvstore): the chunked scan with the wire overlapped behind
compute (docs/PERF_NOTES.md round 10).

The contracts pinned here, all CPU-provable:

* **no eager fallback** — a dist_async run_steps is exactly one host
  dispatch per MXNET_KVSTORE_FUSED_CHUNK steps (profiler.record_dispatch
  "run_steps.dist_chunk"), never the per-step executor.fwd_bwd loop.
* **staleness 0 == eager dist loop, bit-for-bit** — the worker-local
  update replica and the server's updater share Optimizer._update_impl,
  so with integer gradients and a power-of-two lr every quantity is
  exactly representable and the barrier'd chunked run must EQUAL the
  eager per-step push/pull loop.
* **staleness 1 == the analytic async-SGD golden** — the adopted pull
  lags exactly one chunk boundary (deterministic by design, never
  "freshest available"), so a numpy simulation of the chunk/adoption
  arithmetic predicts the final server weights bit-for-bit.
* **transport kills stay invisible** — a mid-window connection kill
  (faultinject.kill_when_unacked) rides the window replay + server
  dedup underneath the driver; the run stays bit-identical to an
  uninterrupted one.
* **overlap accounting** — executor.drive_chunked_dist's wire_wait /
  wire_round clocks: staleness 1 must block strictly less than
  staleness 0 and report a positive overlap fraction (the CPU
  regression gate ci/run_ci.sh asserts cross-process too).
"""
import math
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler as prof

K = 6
BATCH = 2
NIN = 3
NH = 2
LR = 0.25          # power of two: every update exact in fp32


def _int_data(seed=0, k=K):
    rs = np.random.RandomState(seed)
    data = rs.randint(-1, 2, (k, BATCH, NIN)).astype(np.float32)
    label = rs.randint(-2, 3, (k, BATCH, NH)).astype(np.float32)
    w0 = rs.randint(-2, 3, (NH, NIN)).astype(np.float32)
    return data, label, w0


def _make_module(w0):
    data = mx.sym.Variable('data')
    net = mx.sym.FullyConnected(data, num_hidden=NH, no_bias=True,
                                name='fc')
    sym = mx.sym.LinearRegressionOutput(net, name='lro')
    mod = mx.mod.Module(sym, data_names=('data',),
                        label_names=('lro_label',))
    mod.bind(data_shapes=[('data', (BATCH, NIN))],
             label_shapes=[('lro_label', (BATCH, NH))])
    mod.init_params(arg_params={'fc_weight': mx.nd.array(w0.copy())})
    mod.init_optimizer(
        kvstore='dist_async', optimizer='sgd',
        optimizer_params={'learning_rate': LR, 'momentum': 0.0,
                          'wd': 0.0, 'rescale_grad': 1.0})
    return mod


def _serve(monkeypatch, n=1, **kw):
    """n fresh in-process servers; every run gets its own (the server
    keeps weight state)."""
    from mxnet_tpu.kvstore_server import KVStoreServer
    srvs = [KVStoreServer(server_id=i, num_workers=1, **kw)
            for i in range(n)]
    for s in srvs:
        s.start_background()
    monkeypatch.setenv("MXT_SERVER_URIS",
                       ",".join(f"127.0.0.1:{s.port}" for s in srvs))
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    return srvs


def _run_module(monkeypatch, w0, data, label, staleness, chunk,
                fused=True, n_servers=1):
    """One full run against fresh servers; returns final weights."""
    srvs = _serve(monkeypatch, n=n_servers)
    try:
        monkeypatch.setenv("MXNET_KVSTORE_FUSED", "1" if fused else "0")
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_STALENESS",
                           str(staleness))
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_CHUNK", str(chunk))
        mod = _make_module(w0)
        mod.run_steps(data, label, k=data.shape[0])
        w = mod.get_params()[0]['fc_weight'].asnumpy().copy()
        mod._kvstore.close(stop_servers=True)
        return w
    finally:
        for s in srvs:
            s.stop()


def _simulate_chunked(w0, data, label, lr, chunk, staleness):
    """Numpy twin of the chunked-driver semantics (the analytic golden):
    chunk j adopts the pull issued after chunk j-1-S's pushes — the
    server's state after exactly those chunks (single worker) — and the
    in-chunk trajectory evolves through the local update replica.  The
    server applies every pushed gradient; the final pull is its state
    after all of them.  All quantities are exact dyadics, so float32
    reproduces the runtime bit-for-bit."""
    k = data.shape[0]
    n_chunks = math.ceil(k / chunk)
    srv = w0.astype(np.float32).copy()
    local = w0.astype(np.float32).copy()
    pulls = {}
    for j in range(n_chunks):
        due = j - 1 - staleness
        if due in pulls:
            local = pulls.pop(due).copy()
        lo, hi = j * chunk, min(k, (j + 1) * chunk)
        for s in range(lo, hi):
            pred = data[s] @ local.T
            g = ((pred - label[s]).T @ data[s]).astype(np.float32)
            local = local - np.float32(lr) * g
            srv = srv - np.float32(lr) * g
        pulls[j] = srv.copy()
    return srv


def test_staleness0_bit_identical_to_eager_dist_loop(monkeypatch):
    """Staleness 0 (barrier'd chunk boundary) == the eager per-step
    push/pull loop, bit-for-bit: the local replica and the server apply
    identical update sequences, and every quantity is an exact dyadic."""
    data, label, w0 = _int_data(seed=1)
    w_eager = _run_module(monkeypatch, w0, data, label, staleness=0,
                          chunk=2, fused=False)
    w_fused = _run_module(monkeypatch, w0, data, label, staleness=0,
                          chunk=2, fused=True)
    np.testing.assert_array_equal(w_fused, w_eager)
    # and both match the analytic simulation of the eager loop
    np.testing.assert_array_equal(
        w_fused, _simulate_chunked(w0, data, label, LR, 1, 0))


def test_staleness1_matches_analytic_async_golden(monkeypatch):
    """Staleness 1 == the numpy simulation of the chunk/adoption
    arithmetic, bit-for-bit — the lag is EXACT (chunk j always adopts
    chunk j-2's pull), which is what makes the golden computable."""
    data, label, w0 = _int_data(seed=2)
    sim_s0 = _simulate_chunked(w0, data, label, LR, 2, 0)
    sim_s1 = _simulate_chunked(w0, data, label, LR, 2, 1)
    # precondition: the data must actually expose the staleness (a
    # dataset where stale and fresh gradients coincide proves nothing)
    assert not np.array_equal(sim_s0, sim_s1)
    w_fused = _run_module(monkeypatch, w0, data, label, staleness=1,
                          chunk=2, fused=True)
    np.testing.assert_array_equal(w_fused, sim_s1)


def test_one_dispatch_per_chunk_no_eager_fallback(monkeypatch):
    """The acceptance pin: dist_async run_steps is ONE dispatch per
    chunk — never the per-step eager loop's executor.fwd_bwd — and the
    kill switch restores exactly that loop."""
    data, label, w0 = _int_data(seed=3)
    srvs = _serve(monkeypatch)
    try:
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_STALENESS", "1")
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_CHUNK", "2")
        mod = _make_module(w0)
        prof.reset_dispatch_counts()
        outs = mod.run_steps(data, label, k=K)
        counts = prof.dispatch_counts()
        assert counts.get("run_steps.dist_chunk") == math.ceil(K / 2)
        assert "executor.fwd_bwd" not in counts
        assert "run_steps.dispatch" not in counts
        assert outs[0].shape == (K, BATCH, NH)
        mod._kvstore.close(stop_servers=True)
    finally:
        for s in srvs:
            s.stop()
    # kill switch: MXNET_KVSTORE_FUSED=0 restores the eager dist loop
    srvs = _serve(monkeypatch)
    try:
        monkeypatch.setenv("MXNET_KVSTORE_FUSED", "0")
        mod = _make_module(w0)
        prof.reset_dispatch_counts()
        mod.run_steps(data, label, k=K)
        counts = prof.dispatch_counts()
        assert "run_steps.dist_chunk" not in counts
        assert counts.get("executor.fwd_bwd") == K
        mod._kvstore.close(stop_servers=True)
    finally:
        for s in srvs:
            s.stop()


def _serve_elastic(monkeypatch, n=2):
    """n elastic in-process servers sharing a roster (the
    tests/test_membership.py harness shape), env wired for fast
    retry/heartbeat budgets."""
    from mxnet_tpu.kvstore_server import KVStoreServer
    monkeypatch.setenv("MXNET_KVSTORE_ELASTIC", "1")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX", "2")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_INITIAL_MS", "10")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX_MS", "50")
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_INTERVAL", "0.1")
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_TIMEOUT", "0.5")
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    srvs = [KVStoreServer(server_id=i, num_workers=1, elastic=True)
            for i in range(n)]
    uris = ",".join(f"127.0.0.1:{s.port}" for s in srvs)
    monkeypatch.setenv("MXT_SERVER_URIS", uris)
    for s in srvs:
        s._roster_servers = uris.split(",")
        s.start_background()
    return srvs


def test_elastic_rides_fused_driver(monkeypatch):
    """MXNET_KVSTORE_ELASTIC no longer gates the chunked driver off:
    an elastic run_steps is one dispatch per chunk (never the eager
    per-step loop) and lands bit-identical to the analytic staleness
    golden — the fused×elastic composition the _PullHandle replan
    bought (docs/ROBUSTNESS.md replan contract)."""
    data, label, w0 = _int_data(seed=6)
    srvs = _serve_elastic(monkeypatch)
    try:
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_STALENESS", "1")
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_CHUNK", "2")
        mod = _make_module(w0)
        assert mod._kvstore._elastic
        prof.reset_dispatch_counts()
        mod.run_steps(data, label, k=K)
        counts = prof.dispatch_counts()
        assert counts.get("run_steps.dist_chunk") == math.ceil(K / 2), \
            counts
        assert "executor.fwd_bwd" not in counts
        w = mod.get_params()[0]['fc_weight'].asnumpy()
        np.testing.assert_array_equal(
            w, _simulate_chunked(w0, data, label, LR, 2, 1))
        mod._kvstore.close(stop_servers=True)
    finally:
        for s in srvs:
            s.stop()


def test_elastic_fused_survives_server_death(monkeypatch):
    """A server death BETWEEN chunked runs repairs mid-drive (the push
    leg re-routes, the pull handle replans) and the job completes
    bit-identical to the static golden: the surviving layout's final
    weights equal the simulation of every applied gradient."""
    monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "4")
    data, label, w0 = _int_data(seed=7)
    srvs = _serve_elastic(monkeypatch)
    try:
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_STALENESS", "0")
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_CHUNK", "2")
        mod = _make_module(w0)
        kv = mod._kvstore
        # fc_weight stripes across both servers under the tiny bound
        assert kv._stripe_plan('fc_weight', w0.shape) is not None
        half = K // 2
        mod.run_steps(data[:half], label[:half], k=half)
        kv.barrier()
        srvs[1].stop()   # SIGKILL-equivalent: stripe state lost
        prof.reset_dispatch_counts()
        mod.run_steps(data[half:], label[half:], k=half)
        counts = prof.dispatch_counts()
        assert counts.get("run_steps.dist_chunk") == math.ceil(half / 2)
        assert kv._roster_gen >= 1 and len(kv._conns) == 1
        w = mod.get_params()[0]['fc_weight'].asnumpy()
        np.testing.assert_array_equal(
            w, _simulate_chunked(w0, data, label, LR, 2, 0),
            err_msg="elastic fused run diverged from the static golden")
        mod._kvstore.close(stop_servers=True)
    finally:
        for s in srvs:
            s.stop()


def test_striped_keys_ride_the_fused_driver(monkeypatch):
    """A big weight striped across 2 servers pushes per-stripe and
    reassembles through pull_async exactly like the eager path: fused
    staleness-0 == eager, bit-for-bit, over a striped layout."""
    monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "4")
    data, label, w0 = _int_data(seed=4)
    w_eager = _run_module(monkeypatch, w0, data, label, staleness=0,
                          chunk=2, fused=False, n_servers=2)
    w_fused = _run_module(monkeypatch, w0, data, label, staleness=0,
                          chunk=2, fused=True, n_servers=2)
    np.testing.assert_array_equal(w_fused, w_eager)


def test_mid_window_kill_bit_identical(monkeypatch):
    """A connection kill with unacked envelopes in flight mid-run rides
    the window replay + server dedup underneath the fused driver: the
    interrupted run must EQUAL the uninterrupted one bit-for-bit (the
    eager path's existing guarantee, now on the chunked driver).
    Momentum is on — the replay must not double-advance server state."""
    from mxnet_tpu import faultinject

    def run(kill):
        data, label, w0 = _int_data(seed=5)
        srvs = _serve(monkeypatch)
        try:
            monkeypatch.setenv("MXNET_KVSTORE_FUSED_STALENESS", "1")
            monkeypatch.setenv("MXNET_KVSTORE_FUSED_CHUNK", "2")
            mod = _make_module(w0)
            ctx = faultinject.kill_when_unacked(3) if kill else None
            if ctx is not None:
                with ctx:
                    mod.run_steps(data, label, k=K)
            else:
                mod.run_steps(data, label, k=K)
            w = mod.get_params()[0]['fc_weight'].asnumpy().copy()
            stats = dict(prof.channel_counts())
            mod._kvstore.close(stop_servers=True)
            return w, stats
        finally:
            for s in srvs:
                s.stop()

    prof.reset_channel_counts()
    w_clean, _ = run(kill=False)
    prof.reset_channel_counts()
    w_killed, stats = run(kill=True)
    # the kill really happened and really recovered
    assert stats.get("kvstore.reconnect", 0) >= 1
    assert stats.get("kvstore.replay", 0) >= 1
    np.testing.assert_array_equal(w_killed, w_clean)


def test_trainer_step_k_dist_fused_matches_eager(monkeypatch):
    """Gluon twin: step_k on dist_async no longer falls back — one
    dispatch per chunk, and staleness 0 equals K eager step() calls
    bit-for-bit (same integer-exactness argument as the Module test)."""
    import mxnet_tpu.gluon as gluon
    from mxnet_tpu import autograd

    rs = np.random.RandomState(7)
    data = rs.randint(-1, 2, (K, BATCH, NIN)).astype(np.float32)
    label = rs.randint(-2, 3, (K, BATCH, 1)).astype(np.float32)
    w0 = rs.randint(-2, 3, (1, NIN)).astype(np.float32)

    def make_net():
        net = gluon.nn.Dense(1, use_bias=False, in_units=NIN)
        net.initialize()
        net.weight.data()._set_data(mx.nd.array(w0.copy())._data)
        return net

    def loss_of(net):
        def loss_fn(x, y):
            d = net(x) - y
            return (d * d).sum()
        return loss_fn

    # eager reference: K record/backward/step() round trips
    srvs = _serve(monkeypatch)
    try:
        net = make_net()
        tr = gluon.Trainer(net.collect_params(), 'sgd',
                           {'learning_rate': LR, 'momentum': 0.0,
                            'wd': 0.0}, kvstore='dist_async')
        fn = loss_of(net)
        for j in range(K):
            with autograd.record():
                loss = fn(mx.nd.array(data[j]), mx.nd.array(label[j]))
            loss.backward()
            tr.step(batch_size=1)
        w_eager = net.weight.data().asnumpy().copy()
        tr._kvstore.close(stop_servers=True)
    finally:
        for s in srvs:
            s.stop()

    # fused: one step_k call, chunked, staleness 0
    srvs = _serve(monkeypatch)
    try:
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_STALENESS", "0")
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_CHUNK", "2")
        net = make_net()
        tr = gluon.Trainer(net.collect_params(), 'sgd',
                           {'learning_rate': LR, 'momentum': 0.0,
                            'wd': 0.0}, kvstore='dist_async')
        prof.reset_dispatch_counts()
        losses = tr.step_k(loss_of(net), data, label, batch_size=1)
        counts = prof.dispatch_counts()
        assert counts.get("step_k.dist_chunk") == math.ceil(K / 2)
        assert "step_k.dispatch" not in counts
        assert losses.shape == (K,)
        np.testing.assert_array_equal(
            net.weight.data().asnumpy(), w_eager)
        tr._kvstore.close(stop_servers=True)
    finally:
        for s in srvs:
            s.stop()


def test_drive_chunked_dist_overlap_accounting():
    """The wire-overlap clocks, in isolation: with a synthetic 60 ms
    wire round and 30 ms chunks, staleness 1 must hide the computed
    fraction (wait strictly below staleness 0's, overlap_pct strictly
    positive) and staleness 0 must expose ~the whole round."""
    from mxnet_tpu.executor import drive_chunked_dist

    COMPUTE, RTT = 0.03, 0.06

    class _Handle:
        def __init__(self):
            self._t0 = time.monotonic()
            self._ready = self._t0 + RTT
            self._done = False

        def wait(self):
            if self._done:
                return {}
            t_wait = time.monotonic()
            if self._ready > t_wait:
                time.sleep(self._ready - t_wait)
            t1 = time.monotonic()
            prof.record_wire_wait(t1 - t_wait)
            prof.record_wire_round(t1 - self._t0)
            self._done = True
            return {}

    def run(staleness):
        prof.reset_wire_counters()
        adoptions = []

        def dispatch(j, lo, hi, adopted):
            adoptions.append((j, adopted is not None))
            time.sleep(COMPUTE)
            return [None]

        def ship(j, grads):
            return _Handle()

        drive_chunked_dist(6, 1, staleness, dispatch, ship)
        assert prof.wire_rounds() == 6          # every round resolved
        return (prof.wire_wait_ms(), prof.wire_overlap_pct(), adoptions)

    wait0, overlap0, adopt0 = run(0)
    wait1, overlap1, adopt1 = run(1)
    # staleness 0 adopts at every boundary after the first; staleness 1
    # starts one later (the exact-lag schedule)
    assert [a for _j, a in adopt0] == [False] + [True] * 5
    assert [a for _j, a in adopt1] == [False, False] + [True] * 4
    assert wait1 < wait0
    assert overlap1 > overlap0
    assert overlap1 > 25.0   # ~half of each round hides behind compute
    assert overlap0 < 25.0   # barrier'd boundaries expose the wire


def test_fused_epoch_serializes_zero_pickled_bytes(monkeypatch):
    """ISSUE 16 acceptance pin: with the binary codec negotiated
    (MXNET_KVSTORE_CODEC=binary forced), a fused dist_async run_steps
    epoch records pickle_bytes == 0 — every push/pull envelope and ack
    in the steady-state window rides the generated binary frame."""
    monkeypatch.setenv("MXNET_KVSTORE_CODEC", "binary")
    data, label, w0 = _int_data(seed=3)
    srvs = _serve(monkeypatch)
    try:
        monkeypatch.setenv("MXNET_KVSTORE_FUSED", "1")
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_STALENESS", "0")
        monkeypatch.setenv("MXNET_KVSTORE_FUSED_CHUNK", "2")
        mod = _make_module(w0)
        # warm-up epoch: init/optimizer shipping is cold-path pickle
        mod.run_steps(data, label, k=data.shape[0])
        prof.reset_serialization()
        mod.run_steps(data, label, k=data.shape[0])
        counts = prof.serialization_counts()
        assert counts.get("pickle_bytes", 0) == 0, counts
        assert counts.get("codec_bytes", 0) > 0, counts
        mod._kvstore.close(stop_servers=True)
    finally:
        for s in srvs:
            s.stop()
