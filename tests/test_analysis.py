"""mxnet_tpu.analysis: the lint rules, the allow-annotation machinery,
the knob registry, and the runtime lock-order sanitizer.

Static half: every rule family has a positive fixture (must flag) and
a negative fixture (must pass) under tests/analysis_fixtures/, the
annotation fixtures prove suppression requires a reason, and the LIVE
package must lint clean under the full rule set — the in-process twin
of the `python -m mxnet_tpu.analysis --strict` CI gate, whose exit
codes are pinned by subprocess below.

Runtime half: OrderedLock/LockGraph catch a synthetic two-lock
inversion (strict raise + recorded-violation modes), stay quiet on
reentrant RLock use, survive threading.Condition integration, and —
the acceptance scenario — the window=8 kill-and-replay fault-injection
run under the full `threading` shim records an ACYCLIC lock-order
graph while the replay arithmetic still comes out exact.
"""
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

from mxnet_tpu.analysis import (
    LockGraph, LockOrderError, OrderedLock, lint_paths, run_lint, shim)
from mxnet_tpu.analysis import knobs as knobs_mod
from mxnet_tpu.analysis.lint import package_root
from mxnet_tpu.analysis.rules import RULE_NAMES

FIXTURES = Path(__file__).parent / "analysis_fixtures"


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# static rules: fixture coverage (one positive + one negative per family)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("fixture,rule,min_hits", [
    ("host_sync_bad.py", "host-sync", 5),
    ("pickle_bad.py", "unsafe-pickle", 3),
    ("lock_order_bad.py", "lock-order", 2),
    ("lock_order_call_bad.py", "lock-order", 2),
    ("knobs_bad.py", "env-knob", 5),
    ("thread_bad.py", "bare-thread", 2),
    ("protocol_ops_bad.py", "protocol-op", 5),
    ("protocol_newops_bad.py", "protocol-op", 6),
    ("raw_send_bad.py", "raw-send", 4),
    ("blocking_lock_bad.py", "blocking-under-lock", 3),
    ("codec_bad.py", "codec-coverage", 3),
])
def test_positive_fixture_is_flagged(fixture, rule, min_hits):
    findings = run_lint([FIXTURES / fixture])
    hits = [f for f in findings if f.rule == rule]
    assert len(hits) >= min_hits, (fixture, findings)
    assert all(f.path.endswith(fixture) for f in hits)
    assert all(f.line > 0 for f in hits)


@pytest.mark.parametrize("fixture", [
    "host_sync_ok.py",
    "host_sync_not_hot.py",
    "pickle_ok.py",
    "lock_order_ok.py",
    "knobs_ok.py",
    "thread_ok.py",
    "protocol_ops_ok.py",
    "protocol_newops_ok.py",
    "raw_send_ok.py",
    "blocking_lock_ok.py",
    "codec_ok.py",
])
def test_negative_fixture_is_clean(fixture):
    findings = run_lint([FIXTURES / fixture])
    assert findings == [], [f.render() for f in findings]


def test_every_rule_family_has_fixture_coverage():
    """The parametrizations above must span the full rule catalog."""
    covered = {"host-sync", "unsafe-pickle", "lock-order", "env-knob",
               "bare-thread", "protocol-op", "raw-send",
               "blocking-under-lock", "codec-coverage"}
    assert covered == set(RULE_NAMES)


# ---------------------------------------------------------------------------
# allow-annotation machinery
# ---------------------------------------------------------------------------
def test_annotated_violations_are_suppressed_with_reasons():
    active, suppressed = lint_paths([FIXTURES / "annotated_bad.py"])
    assert active == [], [f.render() for f in active]
    # one suppression per rule family, each carrying its reason
    assert rules_of(suppressed) == set(RULE_NAMES)
    assert all(f.reason for f in suppressed)


def test_annotation_without_reason_suppresses_nothing():
    findings = run_lint([FIXTURES / "annotated_noreason.py"])
    assert rules_of(findings) == {"unsafe-pickle"}


# ---------------------------------------------------------------------------
# the live package passes the full rule set (the CI gate, in process)
# ---------------------------------------------------------------------------
def test_live_package_passes_strict():
    active, suppressed = lint_paths(None)
    assert active == [], "\n".join(f.render() for f in active)
    # every in-tree suppression must carry a reviewable reason
    assert all(f.reason for f in suppressed)


def test_knob_registry_is_complete_and_documented():
    reg = knobs_mod.registry()
    # spot-check knobs from every subsystem generation
    for name in ("MXNET_KVSTORE_WINDOW", "MXNET_DEVICE_METRICS",
                 "MXNET_FI_KILL_UNACKED", "MXNET_FUSED_DONATE"):
        assert name in reg, name
    table = knobs_mod.markdown_table()
    assert all(k in table for k in reg)
    missing, docs_path = knobs_mod.docs_missing(package_root())
    assert docs_path.exists(), "repo checkout should carry docs/"
    assert missing == [], missing


def test_docs_check_is_not_fooled_by_prefix_knobs():
    """RETRY_MAX must not count as documented just because the
    RETRY_MAX_MS row exists (backtick-delimited matching)."""
    text = "| `MXNET_KVSTORE_RETRY_MAX_MS` | int | `2000` | cap |"
    missing = knobs_mod.missing_in_text(text)
    assert "MXNET_KVSTORE_RETRY_MAX" in missing
    assert "MXNET_KVSTORE_RETRY_MAX_MS" not in missing


# ---------------------------------------------------------------------------
# entry-point exit codes (the acceptance contract of the CI gate)
# ---------------------------------------------------------------------------
def _run_analysis(*args):
    return subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", *args],
        capture_output=True, text=True, timeout=300,
        cwd=str(package_root().parent))


@pytest.mark.slow
def test_entry_point_strict_fails_on_fixture_violations():
    res = _run_analysis("--strict", str(FIXTURES))
    assert res.returncode != 0, res.stdout + res.stderr
    for rule in RULE_NAMES:
        assert "[%s]" % rule in res.stdout, (rule, res.stdout)


@pytest.mark.slow
def test_entry_point_strict_passes_on_live_tree():
    res = _run_analysis("--strict")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 finding(s)" in res.stdout


@pytest.mark.slow
def test_entry_point_json_findings_schema():
    """--json: one Finding per line, dataclass fields verbatim —
    the machine interface CI and the autotune journal consume."""
    import dataclasses
    import json
    from mxnet_tpu.analysis.lint import Finding
    res = _run_analysis("--json", str(FIXTURES / "pickle_bad.py"))
    lines = [l for l in res.stdout.splitlines() if l.strip()]
    assert lines, res.stdout + res.stderr
    fields = {f.name for f in dataclasses.fields(Finding)}
    for line in lines:
        obj = json.loads(line)
        assert set(obj) == fields, obj
    assert any(json.loads(l)["rule"] == "unsafe-pickle" for l in lines)


@pytest.mark.slow
def test_entry_point_check_passes_in_sync_on_live_tree():
    res = _run_analysis("--check")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "in sync" in res.stdout


def test_check_drift_detects_stale_and_missing_tables(tmp_path):
    """The drift helpers behind --check, against a SCRATCH docs layout
    (never the checked-in docs — a killed test must not corrupt the
    repo): verbatim copy -> in sync; edited copy -> STALE; file
    missing with docs/ present -> error; no docs checkout -> None."""
    from mxnet_tpu.analysis import protocol
    assert protocol.check_drift(package_root()) is None
    assert knobs_mod.check_drift(package_root()) is None
    pkg = tmp_path / "mxnet_tpu"
    docs = tmp_path / "docs"
    pkg.mkdir()
    # no docs checkout at all: nothing to check
    assert protocol.check_drift(pkg) is None
    assert knobs_mod.check_drift(pkg) is None
    docs.mkdir()
    # docs/ exists but the files are missing
    assert "PROTOCOL.md" in protocol.check_drift(pkg)
    assert "ROBUSTNESS.md" in knobs_mod.check_drift(pkg)
    # the protocol table is extracted from the tree NEXT TO the docs:
    # give the scratch package a real dispatch and check against IT
    (pkg / "srv.py").write_text(
        'class S:\n'
        '    def _handle(self, msg):\n'
        '        op = msg[0]\n'
        '        if op == "peek":'
        '  # protocol: replay(pure) reply(value)\n'
        '            return 1\n')
    scratch_table = protocol.markdown_table(protocol.extract_package(pkg))
    assert "`peek`" in scratch_table
    (docs / "PROTOCOL.md").write_text("# x\n\n%s\n" % scratch_table)
    (docs / "ROBUSTNESS.md").write_text(
        "# x\n\n%s\n" % knobs_mod.markdown_table())
    assert protocol.check_drift(pkg) is None
    assert knobs_mod.check_drift(pkg) is None
    # an edited copy (or a tree whose ops moved on) is stale
    (docs / "PROTOCOL.md").write_text(
        "# x\n\n%s\n" % scratch_table.replace("pure", "PURE", 1))
    assert "STALE" in protocol.check_drift(pkg)


def test_check_exit_code_2_on_drift(monkeypatch):
    """--check maps any drift problem to exit 2 (in-process, with the
    helper stubbed — the real-file stale path is covered above)."""
    from mxnet_tpu.analysis import __main__ as entry
    monkeypatch.setattr(entry.protocol, "check_drift",
                        lambda root: "docs/PROTOCOL.md ... STALE")
    assert entry.main(["--check"]) == 2


# ---------------------------------------------------------------------------
# the wire-protocol registry (mxnet_tpu.analysis.protocol)
# ---------------------------------------------------------------------------
def test_protocol_table_covers_the_wire_surface():
    """The extracted op table names every core dispatch op, the mesh
    fan-in ops and the serving extensions — with a declared replay
    guard on each (the live package lints strict, so none may be
    undeclared)."""
    from mxnet_tpu.analysis import protocol
    table = protocol.extract_package()
    names = table.op_names()
    for op in ("push", "pull", "barrier", "stats", "handoff",
               "roster_join", "roster_beat", "mesh_push",
               "mesh_collect", "predict", "serving_refresh"):
        assert op in names, op
    for op in table.ops:
        assert op.replay in protocol.REPLAY_GUARDS, \
            (op.name, op.path, op.line, op.replay)
    # the reserved tuple mirrors the core dispatch (no shadowable op)
    core = {o.name for o in table.ops
            if o.kind == "core" and o.owner == "KVStoreServer"}
    assert core <= set(table.reserved)
    # client sites only name dispatched ops
    known = names | {protocol.ENVELOPE_OP}
    for site in table.clients:
        assert site.op in known, (site.op, site.path, site.line)
    md = protocol.markdown_table(table)
    assert md.startswith(protocol.DOCS_BEGIN)
    assert "| `push` | core | dedup-window |" in md


# ---------------------------------------------------------------------------
# runtime lock-order sanitizer
# ---------------------------------------------------------------------------
def test_synthetic_inversion_strict_raises_before_deadlock():
    g = LockGraph(strict=True)
    a = OrderedLock("A", graph=g)
    b = OrderedLock("B", graph=g)
    with a:
        with b:
            pass
    # same thread, opposite order: the check fires BEFORE blocking
    with b:
        with pytest.raises(LockOrderError):
            a.acquire()
    assert g.violations()


def test_synthetic_inversion_two_threads_recorded():
    g = LockGraph(strict=False)
    a = OrderedLock("A", graph=g)
    b = OrderedLock("B", graph=g)
    first_done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        first_done.set()

    def t2():
        first_done.wait(5)
        with b:
            with a:
                pass

    th1, th2 = threading.Thread(target=t1), threading.Thread(target=t2)
    th1.start(); th2.start(); th1.join(5); th2.join(5)
    assert ("A", "B") in g.edges() and ("B", "A") in g.edges()
    assert g.violations()
    with pytest.raises(LockOrderError):
        g.assert_acyclic()


def test_reentrant_rlock_is_not_an_inversion():
    g = LockGraph(strict=True)
    r = OrderedLock("R", graph=g, rlock=True)
    with r:
        with r:
            pass
    assert g.violations() == []
    g.assert_acyclic()


def test_consistent_order_stays_clean():
    g = LockGraph(strict=True)
    a = OrderedLock("A", graph=g)
    b = OrderedLock("B", graph=g)
    for _ in range(3):
        with a:
            with b:
                pass
    assert g.edges().keys() == {("A", "B")}
    g.assert_acyclic()


def test_shim_instruments_condition_and_event():
    """Locks built under the shim — including the RLock inside a bare
    threading.Condition() and the Lock inside threading.Event() — must
    record without breaking wait/notify semantics."""
    with shim() as g:
        cond = threading.Condition()
        ready = []

        def waiter():
            with cond:
                while not ready:
                    cond.wait(1.0)

        t = threading.Thread(target=waiter)
        t.start()
        with cond:
            ready.append(1)
            cond.notify_all()
        t.join(5)
        assert not t.is_alive()
        ev = threading.Event()
        ev.set()
        assert ev.wait(1.0)
    g.assert_acyclic()


def test_shim_window8_kill_and_replay_graph_is_acyclic(monkeypatch):
    """THE runtime acceptance scenario: the window=8 kill-and-replay
    fault-injection run (pipelined pushes, mid-window connection kill,
    full-window replay, server dedup) under the full threading shim.
    Every lock in KVStoreServer + _ServerConn (+ queue internals) is
    instrumented; the recorded global lock-order graph must be
    non-trivial and ACYCLIC, and the replay arithmetic must still come
    out exact — instrumentation cannot change transport semantics."""
    import mxnet_tpu as mx
    from mxnet_tpu import faultinject
    from mxnet_tpu.kvstore_server import KVStoreServer

    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX", "8")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_INITIAL_MS", "10")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX_MS", "50")
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_INTERVAL", "0")
    monkeypatch.setenv("MXNET_KVSTORE_WINDOW", "8")
    faultinject.reset()
    shape = (2, 3)
    try:
        with shim() as g:
            srv = KVStoreServer(server_id=0, num_workers=1)
            srv.start_background()
            monkeypatch.setenv("MXT_SERVER_URIS",
                               "127.0.0.1:%d" % srv.port)
            monkeypatch.setenv("DMLC_NUM_WORKER", "1")
            monkeypatch.setenv("DMLC_WORKER_ID", "0")
            try:
                kv = mx.kv.create('dist_async')
                kv.init('w', mx.nd.ones(shape))
                kv.set_optimizer(mx.optimizer.SGD(
                    learning_rate=0.5, momentum=0.0, wd=0.0,
                    rescale_grad=1.0))
                out = mx.nd.zeros(shape)
                with faultinject.delay_acks(0.03):
                    with faultinject.kill_when_unacked(4):
                        for i in range(6):
                            kv.push('w', mx.nd.ones(shape) * (i + 1))
                        kv.pull('w', out=out)
                np.testing.assert_allclose(
                    out.asnumpy(), 1.0 - 0.5 * 21, rtol=1e-6)
                assert faultinject.stats()["kills_fired"] == 1
                kv.close(stop_servers=True)
            finally:
                srv.stop()
        # the transport's locking is FLAT on these paths (no lock is
        # taken while holding another instrumented one) — an empty edge
        # set is the correct strong result; acquire_count proves the
        # instrumentation was live, not silently bypassed
        assert g.acquire_count() > 0, "shim instrumented nothing"
        assert g.violations() == []
        g.assert_acyclic()
    finally:
        faultinject.reset()


def test_cross_thread_release_does_not_fabricate_edges():
    """A plain Lock released by a different thread than the acquirer
    (the handoff/signal pattern) must clear the acquirer's held entry —
    otherwise every later acquisition on that thread grows phantom
    edges and a correct program flags a false cycle."""
    g = LockGraph(strict=False)
    sig = OrderedLock("SIG", graph=g)
    x = OrderedLock("X", graph=g)
    sig.acquire()                      # main thread acquires...

    def releaser():
        sig.release()                  # ...worker releases (legal)

    t = threading.Thread(target=releaser)
    t.start()
    t.join(5)
    with x:                            # flat use: must record NO edge
        pass
    assert ("SIG", "X") not in g.edges(), g.edges()
    assert g.violations() == []
    g.assert_acyclic()


def test_shim_restores_threading_factories():
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    with shim():
        assert threading.Lock is not orig_lock
    assert threading.Lock is orig_lock
    assert threading.RLock is orig_rlock
