"""Shared-memory lane (mxnet_tpu.shmlane) — ring arithmetic, frame
framing, and the failure contract, all in-process:

* **ring units** — push/pop ordering across the wrap marker and the
  implicit tail skip, free-running u32 indices, too-big records
  refused (they ride TCP for that round), corruption detected rather
  than mis-framed.
* **frame fuzz through the ring** — randomized envelopes (binary v2
  AND pickle frames) pushed through a real shared segment decode
  bit-identical to the socket path, and `wirecodec.frame_len` agrees
  with every record's length (the lane's per-record cross-check).
* **wedge + watchdog** — MXNET_FI_SHM_WEDGE_AFTER stops the leader's
  drain after n frames; drain_stalled fires only when the ring sits
  non-empty with no reader progress past the budget.
* **gating** — MXNET_KVSTORE_SHM parsing and the auto-mode local-host
  pre-filter.
"""
import struct

import numpy as np
import pytest

from mxnet_tpu import faultinject, shmlane
from mxnet_tpu import wirecodec as wc
from mxnet_tpu.base import MXNetError
from mxnet_tpu.shmlane import _HEADER, _REQ_DESC, _Ring


# ---------------------------------------------------------------------------
# ring units (over a plain bytearray — no shared segment needed)
# ---------------------------------------------------------------------------
def _ring(cap=64):
    buf = bytearray(_HEADER + cap)
    _Ring.format(buf, _REQ_DESC, _HEADER, cap)
    return _Ring(buf, _REQ_DESC)


def test_ring_push_pop_fifo():
    r = _ring()
    assert r.try_pop() is None
    for i in range(3):
        assert r.try_push([b"rec%d" % i], 4)
    assert [r.try_pop() for _ in range(3)] == [b"rec0", b"rec1", b"rec2"]
    assert r.try_pop() is None


def test_ring_wraps_and_keeps_order():
    r = _ring(cap=32)
    # records are 4B header + 10B payload = 14B; the third forces a
    # wrap marker / tail skip every few pushes — order must survive
    # dozens of laps (free-running indices exercise the mod-2^32 math)
    for lap in range(50):
        payload = b"%010d" % lap
        assert r.try_push([payload], 10), lap
        assert r.try_pop() == payload
    # interleave at depth 2 where it fits
    a, b = b"aaaa", b"bbbb"
    assert r.try_push([a], 4) and r.try_push([b], 4)
    assert r.try_pop() == a and r.try_pop() == b


def test_ring_refuses_what_cannot_fit():
    r = _ring(cap=32)
    assert not r.try_push([b"x" * 40], 40)      # bigger than the ring
    assert r.try_push([b"y" * 20], 20)
    assert not r.try_push([b"z" * 20], 20)      # no free space NOW
    assert r.try_pop() == b"y" * 20
    assert r.try_push([b"z" * 20], 20)          # fits after the drain


def test_ring_multi_part_record_concatenates():
    r = _ring()
    parts = [b"head", memoryview(b"-mid-"), np.arange(3, dtype=np.uint8)]
    assert r.try_push(parts, 4 + 5 + 3)
    assert r.try_pop() == b"head-mid-" + bytes([0, 1, 2])


def test_ring_detects_corrupt_length():
    r = _ring(cap=32)
    assert r.try_push([b"abcd"], 4)
    # scribble a absurd length over the record header
    struct.pack_into("<I", r._buf, r._data, 0x7FFFFFFF)
    with pytest.raises(MXNetError, match="corruption"):
        r.try_pop()


# ---------------------------------------------------------------------------
# gating
# ---------------------------------------------------------------------------
def test_mode_parsing(monkeypatch):
    for raw, want in [("1", "on"), ("on", "on"), ("true", "on"),
                      ("0", "off"), ("off", "off"), ("no", "off"),
                      ("auto", "auto"), ("", "auto"), ("bogus", "auto")]:
        monkeypatch.setenv("MXNET_KVSTORE_SHM", raw)
        assert shmlane.mode() == want, raw


def test_client_enabled_auto_is_local_only(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_SHM", "auto")
    assert shmlane.client_enabled("127.0.0.1")
    assert shmlane.client_enabled("localhost")
    assert not shmlane.client_enabled("203.0.113.7")   # TEST-NET
    monkeypatch.setenv("MXNET_KVSTORE_SHM", "off")
    assert not shmlane.client_enabled("127.0.0.1")
    monkeypatch.setenv("MXNET_KVSTORE_SHM", "on")
    assert shmlane.client_enabled("203.0.113.7")


# ---------------------------------------------------------------------------
# lane over a real segment: frame fuzz, both codecs, frame_len agrees
# ---------------------------------------------------------------------------
def _lane_pair():
    follower = shmlane.ShmLane.create(nbytes=256 * 1024)
    leader = shmlane.ShmLane.attach(follower.name)
    return follower, leader


def test_lane_fuzz_round_trip_binary_and_pickle():
    """Envelopes through a REAL shared segment, alternating the binary
    v2 codec and the pickle fallback: decoded objects are bit-identical
    and every ring record is exactly one wire frame by frame_len."""
    rng = np.random.default_rng(0x5713)
    follower, leader = _lane_pair()
    try:
        for trial in range(40):
            arr = np.asarray(
                rng.random((int(rng.integers(1, 6)),
                            int(rng.integers(1, 6)))) * 64,
                dtype=[np.float32, np.float16, np.int64][trial % 3])
            inner = ("mesh_push", trial, [("w", arr)])
            msg = ("req", (1, "n%d" % trial), trial, inner)
            binary = trial % 2 == 0
            assert follower.send_request(msg, binary_ok=binary)
            got = leader.recv_request()
            assert got[0] == "req" and got[2] == trial
            g = dict(got[3][2])["w"]
            assert g.dtype == arr.dtype and np.array_equal(g, arr)
            reply = ("ok", {"w": arr * 2})
            assert leader.send_reply(reply, binary_ok=binary)
            back = follower.recv_reply()
            assert back[0] == "ok"
            assert np.array_equal(back[1]["w"], arr * 2)
        assert leader.recv_request() is None
        assert follower.recv_reply() is None
    finally:
        leader.close()
        follower.destroy()


def test_frame_len_names_both_framings():
    head, bufs = wc.encode_frame(("ok", np.arange(4, dtype=np.float32)))
    frame = bytes(head) + b"".join(bytes(b) for b in bufs)
    assert wc.frame_len(frame[:13]) == len(frame)
    import pickle
    skel = pickle.dumps(("ok", None), protocol=pickle.HIGHEST_PROTOCOL)
    pframe = struct.pack(">QI", 4 + len(skel), len(skel)) + skel
    assert wc.frame_len(pframe[:13]) == len(pframe)
    with pytest.raises(ValueError):
        wc.frame_len(b"\xb1\x00\x00")   # too short to name a length


def test_oversized_frame_reports_unsent():
    follower, leader = _lane_pair()
    try:
        big = ("req", (1, "n"), 0,
               ("mesh_push", 0, [("w", np.zeros(1 << 20,
                                               dtype=np.float64))]))
        assert not follower.send_request(big)    # rides TCP that round
        assert leader.recv_request() is None
    finally:
        leader.close()
        follower.destroy()


def test_dead_flag_is_shared_and_send_refuses():
    follower, leader = _lane_pair()
    try:
        assert not follower.dead() and not leader.dead()
        leader.mark_dead()
        assert follower.dead()
        assert not follower.send_request(("req", (1, "n"), 0,
                                          ("command", "flush")))
    finally:
        leader.close()
        follower.destroy()


# ---------------------------------------------------------------------------
# wedge gate + stall watchdog
# ---------------------------------------------------------------------------
def test_wedge_gate_stops_drain_after_n_frames():
    faultinject.reset()
    follower, leader = _lane_pair()
    try:
        with faultinject.shm_wedge_after_frames(2):
            for seq in range(4):
                assert follower.send_request(
                    ("req", (1, "n%d" % seq), seq, ("command", "x")))
            got = [leader.recv_request() for _ in range(6)]
            served = [g for g in got if g is not None]
            assert len(served) == 2, got       # then the drain wedges
            assert faultinject.stats()["shm_frames_wedged"] > 0
            assert follower.request_backlog() > 0
    finally:
        faultinject.reset()
        leader.close()
        follower.destroy()


def test_drain_stalled_fires_only_without_progress(monkeypatch):
    import time
    follower, leader = _lane_pair()
    try:
        assert not follower.drain_stalled(0.05)   # empty ring: never
        assert follower.send_request(("req", (1, "n"), 0,
                                      ("command", "x")))
        assert not follower.drain_stalled(0.05)   # first sight arms it
        time.sleep(0.08)
        assert follower.drain_stalled(0.05)       # no progress past budget
        assert leader.recv_request() is not None  # progress …
        assert not follower.drain_stalled(0.05)   # … clears the clock
    finally:
        leader.close()
        follower.destroy()
