"""Symbol composition / inference / serialization tests.

Modeled on the reference's tests/python/unittest/test_symbol.py and
test_infer_shape.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _mlp():
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, num_hidden=64, name='fc1')
    act = sym.Activation(fc1, act_type='relu', name='relu1')
    fc2 = sym.FullyConnected(act, num_hidden=10, name='fc2')
    return sym.SoftmaxOutput(fc2, name='softmax')


def test_list_arguments():
    out = _mlp()
    assert out.list_arguments() == [
        'data', 'fc1_weight', 'fc1_bias', 'fc2_weight', 'fc2_bias',
        'softmax_label']
    assert out.list_outputs() == ['softmax_output']


def test_infer_shape_mlp():
    out = _mlp()
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(
        data=(32, 100), softmax_label=(32,))
    assert arg_shapes == [(32, 100), (64, 100), (64,), (10, 64), (10,),
                          (32,)]
    assert out_shapes == [(32, 10)]
    assert aux_shapes == []


def test_infer_shape_conv_bn():
    data = sym.Variable('data')
    c = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name='c1')
    bn = sym.BatchNorm(c, name='bn1')
    p = sym.Pooling(bn, kernel=(2, 2), stride=(2, 2), pool_type='max',
                    name='p1')
    fc = sym.FullyConnected(sym.Flatten(p), num_hidden=10, name='fc')
    arg_shapes, out_shapes, aux_shapes = fc.infer_shape(data=(4, 3, 28, 28))
    args = dict(zip(fc.list_arguments(), arg_shapes))
    assert args['c1_weight'] == (8, 3, 3, 3)
    assert args['bn1_gamma'] == (8,)
    assert out_shapes == [(4, 10)]
    aux = dict(zip(fc.list_auxiliary_states(), aux_shapes))
    assert aux['bn1_moving_mean'] == (8,)
    assert aux['bn1_moving_var'] == (8,)


def test_no_bias_skips_variable():
    data = sym.Variable('data')
    fc = sym.FullyConnected(data, num_hidden=5, no_bias=True, name='fc')
    assert fc.list_arguments() == ['data', 'fc_weight']


def test_compose_named_inputs():
    data = sym.Variable('data')
    w = sym.Variable('myw')
    fc = sym.FullyConnected(data=data, weight=w, num_hidden=3, name='fc')
    assert fc.list_arguments() == ['data', 'myw', 'fc_bias']


def test_json_roundtrip():
    out = _mlp()
    js = out.tojson()
    out2 = sym.load_json(js)
    assert out2.list_arguments() == out.list_arguments()
    assert out2.list_outputs() == out.list_outputs()
    a1, o1, _ = out.infer_shape(data=(8, 20), softmax_label=(8,))
    a2, o2, _ = out2.infer_shape(data=(8, 20), softmax_label=(8,))
    assert a1 == a2 and o1 == o2


def test_save_load(tmp_path):
    out = _mlp()
    fn = str(tmp_path / "sym.json")
    out.save(fn)
    out2 = sym.load(fn)
    assert out2.list_arguments() == out.list_arguments()


def test_group_and_internals():
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, num_hidden=4, name='fc1')
    fc2 = sym.FullyConnected(fc1, num_hidden=2, name='fc2')
    g = sym.Group([fc1, fc2])
    assert g.list_outputs() == ['fc1_output', 'fc2_output']
    internals = fc2.get_internals()
    assert 'fc1_output' in internals.list_outputs()
    sub = internals['fc1_output']
    assert sub.list_outputs() == ['fc1_output']


def test_symbol_arithmetic_exec():
    a = sym.Variable('a')
    b = sym.Variable('b')
    c = 2.0 * a + b ** 2
    ex = c.simple_bind(a=(3,), b=(3,), grad_req='write')
    ex.arg_dict['a']._set_data(np.array([1., 2., 3.], np.float32))
    ex.arg_dict['b']._set_data(np.array([4., 5., 6.], np.float32))
    ex.forward()
    np.testing.assert_allclose(ex.outputs[0].asnumpy(),
                               [18., 29., 42.])


def test_attr_scope():
    with mx.AttrScope(group='4'):
        a = sym.Variable('a')
    assert a.attr('group') == '4'


def test_name_prefix():
    with mx.name.Prefix('mynet_'):
        d = sym.Variable('d')
        fc = sym.FullyConnected(d, num_hidden=2)
    assert fc.name.startswith('mynet_')


def test_variable_shape_attr():
    a = sym.Variable('a', shape=(4, 5))
    b = sym.Variable('b')
    c = a + b
    arg_shapes, out_shapes, _ = c.infer_shape()
    assert out_shapes == [(4, 5)]


def test_multi_output_slicechannel():
    data = sym.Variable('data')
    parts = sym.SliceChannel(data, num_outputs=3, axis=1, name='sc')
    assert len(parts.list_outputs()) == 3
    p0 = parts[0]
    ex = p0.simple_bind(data=(2, 6))
    ex.arg_dict['data']._set_data(np.arange(12, dtype=np.float32).reshape(2, 6))
    ex.forward()
    assert ex.outputs[0].shape == (2, 2)


def test_sym_random_namespace():
    """mx.sym.random mirrors mx.nd.random (reference symbol/random.py):
    same registered ops, so a graph draw matches shapes/moments."""
    import numpy as np
    s = mx.sym.random.uniform(low=-1.0, high=1.0, shape=(64, 32))
    ex = s.bind(mx.cpu(), {})
    mx.random.seed(3)
    out = ex.forward()[0].asnumpy()
    assert out.shape == (64, 32)
    assert out.min() >= -1.0 and out.max() <= 1.0

    n = mx.sym.random.normal(loc=2.0, scale=0.5, shape=(2000,))
    ex = n.bind(mx.cpu(), {})
    mx.random.seed(4)
    v = ex.forward()[0].asnumpy()
    assert abs(v.mean() - 2.0) < 0.1 and abs(v.std() - 0.5) < 0.1

    # tensor-parameter path composes with graph inputs
    mu = mx.sym.Variable("mu")
    samp = mx.sym.random.normal(loc=mu, scale=mx.sym.zeros((3,)) + 1e-6)
    ex = samp.bind(mx.cpu(), {"mu": mx.nd.array([1., 2., 3.])})
    got = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(got, [1., 2., 3.], atol=1e-3)


def test_sym_linalg_namespace():
    """mx.sym.linalg mirrors mx.nd.linalg through the executor."""
    import numpy as np
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    g = mx.sym.linalg.gemm2(a, b)
    rs = np.random.RandomState(0)
    A = rs.randn(4, 5).astype('f')
    B = rs.randn(5, 3).astype('f')
    ex = g.bind(mx.cpu(), {"a": mx.nd.array(A), "b": mx.nd.array(B)})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), A @ B,
                               rtol=1e-5, atol=1e-5)
    want = mx.nd.linalg.syrk(mx.nd.array(A)).asnumpy()
    s = mx.sym.linalg.syrk(a)
    got = s.bind(mx.cpu(), {"a": mx.nd.array(A)}).forward()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_record_iter_v1_aliases():
    import mxnet_tpu as _mx
    assert _mx.io.ImageRecordIter_v1 is _mx.io.ImageRecordIter
    assert _mx.io.ImageRecordUInt8Iter_v1 is _mx.io.ImageRecordUInt8Iter


def test_load_reference_legacy_symbol_json():
    """tests/golden/reference_save_000800.json is the reference's own
    checked-in pre-nnvm (v0.8) graph JSON (its test_symbol.py:239 loads
    it via the legacy_json_util.cc upgrade pass).  Our loader accepts
    pair-form edges, the separate 'attr'/'param' dicts, and synthesizes
    the implicit BatchNorm aux inputs — then the graph RUNS."""
    import os
    import numpy as np
    path = os.path.join(os.path.dirname(__file__), "golden",
                        "reference_save_000800.json")
    s = mx.sym.load(path)
    args = s.list_arguments()
    assert "softmax_label" in args and "fc1_weight" in args
    assert "batchnorm0_moving_mean" in s.list_auxiliary_states()
    # user attrs from the legacy 'attr' dicts survive
    ad = s.attr_dict()
    assert ad["data"]["lr_mult"] == "0.2"
    assert ad["data"]["ctx_group"] == "stage1"

    mod = mx.mod.Module(s, label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (2, 10))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.initializer.Xavier())
    from mxnet_tpu.io import DataBatch
    mod.forward(DataBatch([mx.nd.array(
        np.random.RandomState(0).rand(2, 10).astype("f"))],
        [mx.nd.zeros((2,))]), is_train=False)
    out = mod.get_outputs()[0].asnumpy()
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)


def test_load_json_roundtrip_no_phantom_args():
    """tojson+load_json must not fabricate skipped conditional args
    (no_bias FullyConnected, non-prelu LeakyReLU)."""
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                no_bias=True, name="fc1")
    net = mx.sym.LeakyReLU(net, act_type="leaky", name="lrelu")
    back = mx.sym.load_json(net.tojson())
    assert back.list_arguments() == net.list_arguments()
    assert "fc1_bias" not in back.list_arguments()
    assert "lrelu_gamma" not in back.list_arguments()


def test_symbol_children_semantics():
    """reference test_symbol.py:69 test_symbol_children — exact child
    enumeration and leaf behavior."""
    data = mx.sym.Variable('data')
    fc1 = mx.sym.FullyConnected(data, name='fc1', num_hidden=10)
    net = mx.sym.FullyConnected(fc1, name='fc2', num_hidden=100)
    assert net.get_children().list_outputs() == \
        ['fc1_output', 'fc2_weight', 'fc2_bias']
    assert net.get_children().get_children().list_outputs() == \
        ['data', 'fc1_weight', 'fc1_bias']
    assert net.get_children()['fc2_weight'].list_arguments() == \
        ['fc2_weight']
    assert net.get_children()['fc2_weight'].get_children() is None
    sliced = mx.sym.SliceChannel(data, num_outputs=3, name='slice')
    concat = mx.sym.Concat(*list(sliced))
    assert concat.get_children().list_outputs() == \
        ['slice_output0', 'slice_output1', 'slice_output2']
    assert sliced.get_children().list_outputs() == ['data']


def test_symbol_internal_arguments():
    """reference test_symbol.py:59: an internal head's arguments are the
    subgraph's arguments."""
    data = mx.sym.Variable('data')
    fc1 = mx.sym.FullyConnected(data, name='fc1', num_hidden=10)
    net = mx.sym.FullyConnected(fc1, name='fc2', num_hidden=100)
    assert net.list_arguments() == \
        ['data', 'fc1_weight', 'fc1_bias', 'fc2_weight', 'fc2_bias']
    internal = net.get_internals()
    assert internal['fc1_output'].list_arguments() == \
        fc1.list_arguments()


def test_symbol_pickle_roundtrip():
    """reference test_symbol.py:87: symbols pickle via their JSON."""
    import pickle
    data = mx.sym.Variable('data')
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, name='c')
    net = mx.sym.SoftmaxOutput(mx.sym.Flatten(net), name='softmax')
    clone = pickle.loads(pickle.dumps(net))
    assert clone.tojson() == net.tojson()
    assert clone.list_arguments() == net.list_arguments()
