"""Controlled-scheduler test surface (mxnet_tpu.analysis.sched).

Four layers, mirroring the explorer's own guarantees:

* unit — every yield-point SOURCE (lock, condition, queue, thread
  start/join, sleep, select, hb.track, hb.note_spsc) is visible in the
  recorded decision stream of a tiny scenario built right here;
* determinism — the same ``(seed, scenario)`` pair replays the same
  bit-identical decision sequence, run after run;
* detectors — a constructed two-lock cycle is declared a deadlock (with
  both locks named in the report) and a pinned-priority schedule trips
  the starvation budget at exactly ``MXNET_SCHED_STARVE_OPS``;
* acceptance — BOTH planted bugs (the ABBA deadlock and the
  check-then-act overdraw) survive hundreds of free-running iterations,
  are found by the explorer inside the CI schedule budget, and their
  journals replay bit-identically; and all seven real scenarios run
  N>=20 seeded schedules race-, deadlock-, and starvation-clean (as
  concurrent CLI subprocesses so wall time is the slowest scenario,
  not the sum).
"""
import json
import os
import select
import socket
import subprocess
import sys
import threading
import time

import pytest

from mxnet_tpu.analysis import hb, sched
from mxnet_tpu.analysis import scenarios as scen

# The explorer budget the CI gate uses (ci/run_ci.sh passes the same
# number): both seeded bugs must surface within this many schedules.
BUG_BUDGET = 25


def _adhoc(fn, name="adhoc", lease_s=0.5):
    return scen.Scenario(name, fn, None, "real", "", lease_s=lease_s)


def _run(fn, tmp_path, name="adhoc", **kw):
    kw.setdefault("journal_dir", str(tmp_path))
    return sched.run_schedule(_adhoc(fn, name=name), **kw)


def _ops(result):
    return [op for (_lid, op, _res) in result.decisions]


def _kinds(result):
    return [k for (k, _d) in result.findings]


# ---------------------------------------------------------------------------
# unit: one test per yield-point source
# ---------------------------------------------------------------------------
def test_yield_points_lock_acquire_release(tmp_path):
    hits = []

    def body():
        lock = threading.Lock()

        def worker():
            for _ in range(5):
                with lock:
                    hits.append(1)

        ts = [threading.Thread(target=worker) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    r = _run(body, tmp_path)
    assert r.clean, r.findings
    assert len(hits) == 10
    assert "acquire" in _ops(r) and "release" in _ops(r)


def test_yield_points_condition_wait_notify(tmp_path):
    def body():
        cv = threading.Condition()
        state = {"flag": False}

        def setter():
            with cv:
                state["flag"] = True
                cv.notify()

        t = threading.Thread(target=setter)
        with cv:
            t.start()
            while not state["flag"]:
                cv.wait()          # setter can't run while we hold cv
        t.join()

    r = _run(body, tmp_path)
    assert r.clean, r.findings
    ops = _ops(r)
    assert "wait-cv" in ops and "notify" in ops


def test_yield_points_queue_put_get(tmp_path):
    got = []

    def body():
        import queue
        q = queue.Queue(maxsize=2)

        def producer():
            for i in range(6):
                q.put(i)

        t = threading.Thread(target=producer)
        t.start()
        for _ in range(6):
            got.append(q.get())
        t.join()

    r = _run(body, tmp_path)
    assert r.clean, r.findings
    assert got == list(range(6))
    # queue.Queue is built on Condition + Lock: the bounded put/get
    # traffic must surface as modeled cv waits, not real blocking
    assert "wait-cv" in _ops(r)


def test_yield_points_thread_start_begin_join(tmp_path):
    def body():
        t = threading.Thread(target=lambda: None, name="leaf")
        t.start()
        t.join()

    r = _run(body, tmp_path)
    assert r.clean, r.findings
    ops = _ops(r)
    # "begin" only shows as a decision op when the new thread itself
    # triggers the pick; what IS structural: the start rendezvous, the
    # join, the leaf's end, and the leaf (T1) actually being scheduled
    assert "start" in ops and "join" in ops and "end" in ops
    assert "T1" in [lid for (lid, _o, _r) in r.decisions]


def test_yield_points_sleep(tmp_path):
    """A sleep records a pick only when someone else is RUNNABLE at
    that instant (a solo sleeper is woken by the monitor instead), so
    interleave two sleep loops: when one blocks, the other's fired
    deadline makes it the handoff target."""
    def body():
        def napper():
            for _ in range(20):
                time.sleep(0.001)

        ts = [threading.Thread(target=napper) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    t0 = time.monotonic()
    r = _run(body, tmp_path)
    assert r.clean, r.findings
    assert "sleep" in _ops(r)
    assert time.monotonic() - t0 < 30.0


def test_yield_points_select(tmp_path):
    seen = {}

    def body():
        a, b = socket.socketpair()
        c, d = socket.socketpair()
        try:
            b.sendall(b"x")
            # zero timeout: modeled as a plain yield + real probe
            seen["zero"] = select.select([a], [], [], 0)[0]
            # timed selects in two interleaved loops: each timed call
            # is modeled as sleep_yield + a zero-timeout real probe,
            # and the sibling's fired deadline makes the modeled wait
            # visible as a "sleep" pick (see test_yield_points_sleep)
            def poller():
                for _ in range(20):
                    select.select([d], [], [], 0.001)

            t = threading.Thread(target=poller)
            t.start()
            for _ in range(20):
                seen["timed"] = select.select([a], [], [], 0.001)[0]
            t.join()
        finally:
            for s in (a, b, c, d):
                s.close()

    r = _run(body, tmp_path)
    assert r.clean, r.findings
    assert seen["zero"] and seen["timed"]   # data was ready both times
    ops = _ops(r)
    assert "select" in ops     # the zero-timeout probe
    assert "sleep" in ops      # the timed probe's modeled wait


def test_yield_points_tracked_container(tmp_path):
    def body():
        d = hb.track({}, "sched.test.dict")
        lock = threading.Lock()

        def worker(i):
            with lock:
                d[i] = i

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sorted(d) == [0, 1, 2]

    r = _run(body, tmp_path)
    assert r.clean, r.findings
    assert "track" in _ops(r)


def test_yield_points_spsc_probe_and_single_writer(tmp_path):
    def clean_body():
        def writer():
            for _ in range(3):
                hb.note_spsc(("t", "k"), "sched.test.widx", True)

        t = threading.Thread(target=writer)
        t.start()
        t.join()

    r = _run(clean_body, tmp_path)
    assert r.clean, r.findings
    assert "spsc" in _ops(r)

    def racy_body():
        def writer():
            hb.note_spsc(("t2", "k"), "sched.test.widx2", True)

        ts = [threading.Thread(target=writer) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    r = _run(racy_body, tmp_path, name="adhoc-spsc-racy")
    assert "race" in _kinds(r)
    assert any("single-writer" in d for (_k, d) in r.findings)


# ---------------------------------------------------------------------------
# determinism and the journal
# ---------------------------------------------------------------------------
def _churn_body():
    d = hb.track({}, "sched.test.churn")
    lock = threading.Lock()

    def worker(i):
        for j in range(4):
            with lock:
                d[i] = j
            time.sleep(0)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def test_same_seed_same_schedule_bit_identical(tmp_path):
    for seed in (0, 1, 2):
        a = _run(_churn_body, tmp_path, seed=seed, index=0)
        b = _run(_churn_body, tmp_path, seed=seed, index=0)
        assert a.clean and b.clean
        assert a.decisions == b.decisions, seed


def test_journal_kept_on_findings_deleted_when_clean(tmp_path):
    r = _run(_churn_body, tmp_path)
    assert r.clean
    assert r.journal_path is None
    assert not any(f.endswith(".jsonl") for f in os.listdir(tmp_path))

    r = _run(_churn_body, tmp_path, keep_journal=True)
    assert r.journal_path and os.path.exists(r.journal_path)
    header, decisions, _ = sched.read_journal(r.journal_path)
    assert header["scenario"] == "adhoc"
    assert header["lease_s"] == 0.5
    assert [d["t"] for d in decisions] == [t for (t, _o, _r) in
                                           r.decisions]
    with open(r.journal_path) as f:
        last = [json.loads(ln) for ln in f if ln.strip()][-1]
    assert last["kind"] == "end" and last["status"] == "clean"


def test_journal_tolerates_torn_tail(tmp_path):
    r = _run(_churn_body, tmp_path, keep_journal=True)
    with open(r.journal_path, "a") as f:
        f.write('{"kind": "d", "i": 99')   # crash mid-write
    header, decisions, _ = sched.read_journal(r.journal_path)
    assert header is not None
    assert len(decisions) == len(r.decisions)


# ---------------------------------------------------------------------------
# detectors
# ---------------------------------------------------------------------------
def test_deadlock_detector_names_the_cycle(tmp_path):
    """A forced two-lock cycle (no seed luck involved): the spawned
    thread takes lb and publishes the fact, the main thread holds la
    throughout and only then goes for lb — every schedule deadlocks,
    and the detector must name both holders."""
    def body():
        la, lb = threading.Lock(), threading.Lock()
        state = {}

        def other():
            with lb:
                state["has_lb"] = True
                with la:        # cycle: holds lb, wants la
                    pass

        with la:
            t = threading.Thread(target=other, name="other")
            t.start()
            while not state.get("has_lb"):
                time.sleep(0.001)
            with lb:            # cycle: holds la, wants lb
                pass
        t.join()

    r = _run(body, tmp_path, name="adhoc-deadlock")
    kinds = _kinds(r)
    assert "deadlock" in kinds, r.findings
    detail = dict(r.findings)["deadlock"]
    assert "all 2 live threads blocked" in detail
    assert "holding" in detail and "waiting on" in detail
    assert r.journal_path is not None    # failing journals are kept


def test_starvation_budget_arithmetic(tmp_path):
    """depth=1 means zero PCT change points: the top-priority worker
    runs its whole loop while its sibling sits runnable, so the
    sibling's starve counter must hit the budget exactly."""
    def body():
        d = hb.track({}, "sched.test.starve")
        evt = threading.Event()

        def worker(i):
            evt.wait()
            for j in range(60):
                d[(i, j)] = 1

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(2)]
        for t in ts:
            t.start()
        evt.set()       # both runnable from here on
        for t in ts:
            t.join()

    r = _run(body, tmp_path, name="adhoc-starve", depth=1,
             starve_ops=10)
    kinds = _kinds(r)
    assert "starvation" in kinds, r.findings
    detail = [d for (k, d) in r.findings if k == "starvation"][0]
    assert "MXNET_SCHED_STARVE_OPS=10" in detail
    assert "10 consecutive" in detail   # reported AT the budget


def test_replay_divergence_is_a_finding(tmp_path, monkeypatch):
    monkeypatch.setattr(sched, "_REPLAY_STALL_S", 1.5)
    # replay resolves the scenario by its journal name, so the ad-hoc
    # body needs a registry entry for the duration of the test
    monkeypatch.setitem(scen._REGISTRY, "adhoc", _adhoc(_churn_body))
    r = _run(_churn_body, tmp_path, keep_journal=True)
    lines = open(r.journal_path).read().splitlines()
    doctored = []
    for ln in lines:
        obj = json.loads(ln)
        if obj.get("kind") == "thread" and obj["lid"] != "T0":
            continue            # pretend those threads never existed
        if obj.get("kind") == "d" and obj["t"] != "T0":
            obj["t"] = "T9"     # a thread that can never arrive
        doctored.append(json.dumps(obj))
    p = tmp_path / "doctored.jsonl"
    p.write_text("\n".join(doctored) + "\n")
    rep = sched.replay(str(p), journal_dir=str(tmp_path))
    # either the journal's impossible pick is called out or the replay
    # stalls out — both are loud, neither silently "passes"
    assert not rep.clean


# ---------------------------------------------------------------------------
# acceptance: the planted bugs
# ---------------------------------------------------------------------------
def test_bugs_survive_free_running():
    """The point of the explorer: the OS scheduler essentially never
    lands a preemption inside the microsecond-wide windows.  Hundreds
    of free-running rounds of both planted bugs must pass."""
    si = sys.getswitchinterval()
    sys.setswitchinterval(0.005)   # default-ish; restored below
    try:
        for _ in range(200):
            assert not scen.deadlock_once(join_timeout=5.0), \
                "ABBA deadlock fired free-running (astronomically " \
                "unlikely) — rerun"
        for _ in range(300):
            v = scen.atomicity_once()
            assert v >= 0, "overdraw fired free-running — rerun"
    finally:
        sys.setswitchinterval(si)


def _explore_until_finding(name, tmp_path):
    res = sched.explore(name, schedules=BUG_BUDGET, seed=0,
                        journal_dir=str(tmp_path))
    failing = res.failing
    assert failing is not None, \
        "%s not found within %d schedules" % (name, BUG_BUDGET)
    assert failing.journal_path and os.path.exists(failing.journal_path)
    return failing


def test_bug_deadlock_found_within_budget_and_replays(tmp_path):
    failing = _explore_until_finding("bug_deadlock", tmp_path)
    assert "deadlock" in _kinds(failing)
    rep = sched.replay(failing.journal_path,
                       journal_dir=str(tmp_path / "replay"))
    assert rep.decisions == failing.decisions       # bit-identical
    assert "deadlock" in _kinds(rep)


def test_bug_atomicity_found_within_budget_and_replays(tmp_path):
    failing = _explore_until_finding("bug_atomicity", tmp_path)
    assert "scenario-error" in _kinds(failing)
    assert any("overdrawn" in d for (_k, d) in failing.findings)
    rep = sched.replay(failing.journal_path,
                       journal_dir=str(tmp_path / "replay"))
    assert rep.decisions == failing.decisions       # bit-identical
    assert "scenario-error" in _kinds(rep)
    assert any("overdrawn" in d for (_k, d) in rep.findings)


# ---------------------------------------------------------------------------
# acceptance: the seven real scenarios, N>=20 schedules each, clean.
# Run as concurrent CLI subprocesses: the scenarios spend most of
# their time in real-clock waits (heartbeats, promote windows), so
# overlapping them makes wall time ~the slowest scenario instead of
# the ~7-minute serial sum.  The three slowest scenarios are split
# into two 10-schedule halves under different seeds (still 20
# distinct schedules each) so no single subprocess dominates the
# critical path.
# ---------------------------------------------------------------------------
_SPLIT = {"replan", "handoff", "failover", "mesh_fanin"}   # slowest: halve


def test_explore_all_real_scenarios_20_schedules_clean(tmp_path):
    assert len(scen.REAL) == 7, scen.REAL
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    procs = {}
    for name in scen.REAL:
        chunks = [(10, 0), (10, 1)] if name in _SPLIT else [(20, 0)]
        for n_sched, seed in chunks:
            procs["%s-seed%d" % (name, seed)] = subprocess.Popen(
                [sys.executable, "-m", "mxnet_tpu.analysis",
                 "--explore", name, "--schedules", str(n_sched),
                 "--seed", str(seed),
                 "--journal-dir", str(tmp_path / name / str(seed))],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env=env, cwd=root)
    deadline = time.monotonic() + 700
    failures = []
    for name, p in procs.items():
        try:
            out, _ = p.communicate(
                timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            failures.append((name, "TIMEOUT", out))
            continue
        if p.returncode != 0:
            failures.append((name, p.returncode, out))
    assert not failures, "\n\n".join(
        "-- %s (rc=%s) --\n%s" % (n, rc, o.decode(errors="replace")[-4000:])
        for (n, rc, o) in failures)
