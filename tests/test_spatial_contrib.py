"""Spatial-sampling + contrib op-tail tests.

Reference models: tests/python/unittest/test_operator.py
(test_bilinear_sampler, test_grid_generator, test_correlation,
test_spatial_transformer — numpy-reference forward + numeric gradients) and
the contrib op tests (fft/ifft, count_sketch, quantize, proposal, psroi,
deformable ops).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils as tu


# --- GridGenerator ---------------------------------------------------------

def test_grid_generator_affine_identity():
    # identity affine params -> pure normalized meshgrid
    theta = np.array([[1., 0., 0., 0., 1., 0.]], 'float32')
    g = mx.nd.GridGenerator(mx.nd.array(theta), transform_type="affine",
                            target_shape=(4, 5)).asnumpy()
    assert g.shape == (1, 2, 4, 5)
    np.testing.assert_allclose(g[0, 0, 0], np.linspace(-1, 1, 5), atol=1e-6)
    np.testing.assert_allclose(g[0, 1, :, 0], np.linspace(-1, 1, 4),
                               atol=1e-6)


def test_grid_generator_affine_translation():
    theta = np.array([[1., 0., 0.25, 0., 1., -0.5]], 'float32')
    g = mx.nd.GridGenerator(mx.nd.array(theta), transform_type="affine",
                            target_shape=(3, 3)).asnumpy()
    np.testing.assert_allclose(g[0, 0, 0], np.linspace(-1, 1, 3) + 0.25,
                               atol=1e-6)
    np.testing.assert_allclose(g[0, 1, :, 0], np.linspace(-1, 1, 3) - 0.5,
                               atol=1e-6)


def test_grid_generator_warp_zero_flow():
    flow = np.zeros((2, 2, 4, 6), 'float32')
    g = mx.nd.GridGenerator(mx.nd.array(flow),
                            transform_type="warp").asnumpy()
    np.testing.assert_allclose(g[0, 0, 0], np.linspace(-1, 1, 6), atol=1e-6)
    np.testing.assert_allclose(g[0, 1, :, 0], np.linspace(-1, 1, 4),
                               atol=1e-6)


# --- BilinearSampler / SpatialTransformer ----------------------------------

def _identity_grid(b, h, w):
    gx, gy = np.meshgrid(np.linspace(-1, 1, w), np.linspace(-1, 1, h))
    return np.tile(np.stack([gx, gy])[None], (b, 1, 1, 1)).astype('float32')


def test_bilinear_sampler_identity():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 5, 7).astype('float32')
    grid = _identity_grid(2, 5, 7)
    out = mx.nd.BilinearSampler(mx.nd.array(x), mx.nd.array(grid)).asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-5, atol=1e-5)


def test_bilinear_sampler_oob_zero():
    x = np.ones((1, 1, 4, 4), 'float32')
    grid = np.full((1, 2, 2, 2), -3.0, 'float32')  # far outside
    out = mx.nd.BilinearSampler(mx.nd.array(x), mx.nd.array(grid)).asnumpy()
    np.testing.assert_allclose(out, 0.0)


def test_bilinear_sampler_grad():
    rs = np.random.RandomState(1)
    data = mx.sym.Variable("data")
    grid = mx.sym.Variable("grid")
    sym = mx.sym.BilinearSampler(data=data, grid=grid)
    loc = {"data": rs.randn(1, 2, 4, 4).astype('float32'),
           "grid": (rs.rand(1, 2, 3, 3).astype('float32') - 0.5)}
    tu.check_numeric_gradient(sym, loc, rtol=3e-2, atol=3e-3)


def test_spatial_transformer_identity():
    rs = np.random.RandomState(2)
    x = rs.randn(2, 3, 6, 6).astype('float32')
    loc = np.tile(np.array([[1., 0., 0., 0., 1., 0.]], 'float32'), (2, 1))
    out = mx.nd.SpatialTransformer(mx.nd.array(x), mx.nd.array(loc),
                                   target_shape=(6, 6),
                                   transform_type="affine",
                                   sampler_type="bilinear").asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-5, atol=1e-5)


def test_spatial_transformer_matches_grid_plus_sampler():
    rs = np.random.RandomState(3)
    x = rs.randn(1, 2, 5, 5).astype('float32')
    theta = np.array([[0.8, 0.1, 0.05, -0.1, 0.9, -0.02]], 'float32')
    st = mx.nd.SpatialTransformer(mx.nd.array(x), mx.nd.array(theta),
                                  target_shape=(4, 4),
                                  transform_type="affine",
                                  sampler_type="bilinear").asnumpy()
    g = mx.nd.GridGenerator(mx.nd.array(theta), transform_type="affine",
                            target_shape=(4, 4))
    bs = mx.nd.BilinearSampler(mx.nd.array(x), g).asnumpy()
    np.testing.assert_allclose(st, bs, rtol=1e-5, atol=1e-6)


# --- Correlation -----------------------------------------------------------

def _np_correlation(d1, d2, kernel_size, max_d, s1, s2, pad, is_multiply):
    """Direct port of the reference CUDA forward (correlation.cu:44-104)."""
    b, c, h, w = d1.shape
    kr = (kernel_size - 1) // 2
    border = max_d + kr
    p1 = np.pad(d1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = np.pad(d2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    ph, pw = h + 2 * pad, w + 2 * pad
    ho = int(np.ceil((ph - 2 * border) / float(s1)))
    wo = int(np.ceil((pw - 2 * border) / float(s1)))
    nd = max_d // s2
    d = 2 * nd + 1
    out = np.zeros((b, d * d, ho, wo), 'float32')
    for bi in range(b):
        for oy in range(ho):
            for ox in range(wo):
                y1 = oy * s1 + max_d
                x1 = ox * s1 + max_d
                ci = 0
                for dy in range(-nd, nd + 1):
                    for dx in range(-nd, nd + 1):
                        y2, x2 = y1 + dy * s2, x1 + dx * s2
                        a = p1[bi, :, y1:y1 + kernel_size,
                               x1:x1 + kernel_size]
                        bb = p2[bi, :, y2:y2 + kernel_size,
                                x2:x2 + kernel_size]
                        v = (a * bb if is_multiply else np.abs(a - bb)).sum()
                        out[bi, ci, oy, ox] = v / (kernel_size ** 2 * c)
                        ci += 1
    return out


@pytest.mark.parametrize("k,md,s1,s2,pad,mult", [
    (1, 1, 1, 1, 1, True),
    (3, 2, 2, 1, 2, True),
    (1, 2, 1, 2, 2, False),
])
def test_correlation_vs_numpy(k, md, s1, s2, pad, mult):
    rs = np.random.RandomState(4)
    d1 = rs.randn(2, 3, 8, 9).astype('float32')
    d2 = rs.randn(2, 3, 8, 9).astype('float32')
    out = mx.nd.Correlation(mx.nd.array(d1), mx.nd.array(d2),
                            kernel_size=k, max_displacement=md, stride1=s1,
                            stride2=s2, pad_size=pad,
                            is_multiply=mult).asnumpy()
    ref = _np_correlation(d1, d2, k, md, s1, s2, pad, mult)
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


# --- contrib: fft / ifft / count_sketch / quantize -------------------------

def test_contrib_fft_matches_numpy():
    rs = np.random.RandomState(5)
    x = rs.randn(3, 8).astype('float32')
    out = mx.nd.contrib.fft(mx.nd.array(x)).asnumpy()
    ref = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(out[:, 0::2], ref.real, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(out[:, 1::2], ref.imag, rtol=1e-4, atol=1e-4)


def test_contrib_ifft_roundtrip():
    rs = np.random.RandomState(6)
    x = rs.randn(2, 16).astype('float32')
    f = mx.nd.contrib.fft(mx.nd.array(x))
    # reference ifft is unnormalized (cuFFT): divide by n manually
    back = (mx.nd.contrib.ifft(f) / 16.0).asnumpy()
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)


def test_contrib_count_sketch():
    rs = np.random.RandomState(7)
    x = rs.randn(4, 6).astype('float32')
    h = np.array([0, 2, 1, 2, 0, 1], 'float32')
    s = np.array([1, -1, 1, 1, -1, 1], 'float32')
    out = mx.nd.contrib.count_sketch(mx.nd.array(x), mx.nd.array(h),
                                     mx.nd.array(s), out_dim=3).asnumpy()
    ref = np.zeros((4, 3), 'float32')
    for i in range(6):
        ref[:, int(h[i])] += s[i] * x[:, i]
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_contrib_quantize_dequantize_roundtrip():
    x = np.linspace(-1.0, 2.0, 17).astype('float32')
    q, qmin, qmax = mx.nd.contrib.quantize(
        mx.nd.array(x), mx.nd.array([-1.0]), mx.nd.array([2.0]))
    assert q.asnumpy().dtype == np.uint8
    assert float(qmin.asnumpy()) == -1.0 and float(qmax.asnumpy()) == 2.0
    back = mx.nd.contrib.dequantize(
        q, mx.nd.array([-1.0]), mx.nd.array([2.0])).asnumpy()
    np.testing.assert_allclose(back, x, atol=3.0 / 255 * 3)


# --- contrib: Proposal / MultiProposal -------------------------------------

def _np_nms_keep(dets, thresh, post_n):
    n = dets.shape[0]
    area = (dets[:, 2] - dets[:, 0] + 1) * (dets[:, 3] - dets[:, 1] + 1)
    suppressed = np.zeros(n, bool)
    keep = []
    for i in range(n):
        if len(keep) >= post_n:
            break
        if suppressed[i]:
            continue
        keep.append(i)
        for j in range(i + 1, n):
            if suppressed[j]:
                continue
            xx1 = max(dets[i, 0], dets[j, 0])
            yy1 = max(dets[i, 1], dets[j, 1])
            xx2 = min(dets[i, 2], dets[j, 2])
            yy2 = min(dets[i, 3], dets[j, 3])
            inter = max(xx2 - xx1 + 1, 0) * max(yy2 - yy1 + 1, 0)
            if inter / (area[i] + area[j] - inter) > thresh:
                suppressed[j] = True
    return keep


def test_proposal_shapes_and_validity():
    rs = np.random.RandomState(8)
    h, w, a = 6, 7, 3
    cls = rs.rand(1, 2 * a, h, w).astype('float32')
    bbox = (rs.randn(1, 4 * a, h, w) * 0.1).astype('float32')
    im_info = np.array([[96., 112., 1.0]], 'float32')
    rois = mx.nd.contrib.Proposal(
        mx.nd.array(cls), mx.nd.array(bbox), mx.nd.array(im_info),
        feature_stride=16, scales=(2.,), ratios=(0.5, 1., 2.),
        rpn_pre_nms_top_n=30, rpn_post_nms_top_n=10,
        threshold=0.7, rpn_min_size=4).asnumpy()
    assert rois.shape == (10, 5)
    assert np.isfinite(rois).all()
    # boxes clipped to the image
    assert rois[:, 1].min() >= -4 and rois[:, 3].max() <= 112 + 4


def test_proposal_matches_numpy_pipeline():
    rs = np.random.RandomState(9)
    h, w = 5, 6
    scales, ratios, stride = (4.,), (1.,), 8
    a = len(scales) * len(ratios)
    cls = rs.rand(1, 2 * a, h, w).astype('float32')
    bbox = (rs.randn(1, 4 * a, h, w) * 0.2).astype('float32')
    im_info = np.array([[40., 48., 1.0]], 'float32')
    pre_n, post_n, thresh, min_size = 20, 8, 0.7, 4
    rois, = [mx.nd.contrib.Proposal(
        mx.nd.array(cls), mx.nd.array(bbox), mx.nd.array(im_info),
        feature_stride=stride, scales=scales, ratios=ratios,
        rpn_pre_nms_top_n=pre_n, rpn_post_nms_top_n=post_n,
        threshold=thresh, rpn_min_size=min_size)]
    rois = rois.asnumpy()
    assert rois.shape == (post_n, 5)
    np.testing.assert_allclose(rois[:, 0], 0.0)

    # numpy reference of the whole pipeline (proposal.cc flow)
    base = stride
    size = float(base * base)
    sr = np.floor(size / 1.0)
    nw = np.floor(np.sqrt(sr) + 0.5) * scales[0]
    nh = np.floor(nw / scales[0] * 1.0 + 0.5) * scales[0]
    ctr = 0.5 * (base - 1.0)
    anchor = np.array([ctr - 0.5 * (nw - 1), ctr - 0.5 * (nh - 1),
                       ctr + 0.5 * (nw - 1), ctr + 0.5 * (nh - 1)])
    props, scores = [], []
    for yy in range(h):
        for xx in range(w):
            box = anchor + np.array([xx * stride, yy * stride,
                                     xx * stride, yy * stride])
            d = bbox[0, :, yy, xx]
            bw = box[2] - box[0] + 1
            bh = box[3] - box[1] + 1
            cx = box[0] + 0.5 * (bw - 1)
            cy = box[1] + 0.5 * (bh - 1)
            pcx, pcy = d[0] * bw + cx, d[1] * bh + cy
            pw_, ph_ = np.exp(d[2]) * bw, np.exp(d[3]) * bh
            x1 = np.clip(pcx - 0.5 * (pw_ - 1), 0, im_info[0, 1] - 1)
            y1 = np.clip(pcy - 0.5 * (ph_ - 1), 0, im_info[0, 0] - 1)
            x2 = np.clip(pcx + 0.5 * (pw_ - 1), 0, im_info[0, 1] - 1)
            y2 = np.clip(pcy + 0.5 * (ph_ - 1), 0, im_info[0, 0] - 1)
            sc = cls[0, a + 0, yy, xx]
            real_h, real_w = im_info[0, 0] / stride, im_info[0, 1] / stride
            if yy >= real_h or xx >= real_w:
                sc = -1.0
            iw = x2 - x1 + 1
            ih = y2 - y1 + 1
            if iw < min_size or ih < min_size:
                x1 -= min_size / 2
                y1 -= min_size / 2
                x2 += min_size / 2
                y2 += min_size / 2
                sc = -1.0
            props.append([x1, y1, x2, y2])
            scores.append(sc)
    props = np.asarray(props, 'float32')
    scores = np.asarray(scores, 'float32')
    order = np.argsort(-scores, kind="stable")[:pre_n]
    dets = props[order]
    keep = _np_nms_keep(dets, thresh, post_n)
    expect = dets[[keep[i % len(keep)] for i in range(post_n)]
                  if len(keep) < post_n else keep[:post_n]]
    np.testing.assert_allclose(rois[:, 1:], expect, rtol=1e-4, atol=1e-3)


def test_proposal_output_score():
    rs = np.random.RandomState(15)
    cls = rs.rand(1, 2, 3, 3).astype('float32')
    bbox = (rs.randn(1, 4, 3, 3) * 0.1).astype('float32')
    im_info = np.array([[48., 48., 1.0]], 'float32')
    kw = dict(feature_stride=16, scales=(4.,), ratios=(1.,),
              rpn_pre_nms_top_n=9, rpn_post_nms_top_n=4,
              threshold=0.7, rpn_min_size=1)
    ret = mx.nd.contrib.Proposal(mx.nd.array(cls), mx.nd.array(bbox),
                                 mx.nd.array(im_info), output_score=True,
                                 **kw)
    assert isinstance(ret, list) and len(ret) == 2
    rois, scores = ret
    assert rois.shape == (4, 5) and scores.shape == (4, 1)
    # NMS keeps in score order; the first row is the best surviving score
    # (later rows may wrap around when fewer than post_n boxes survive)
    s = scores.asnumpy().ravel()
    assert np.isfinite(s).all() and s[0] == s.max()
    # default hides scores
    only = mx.nd.contrib.Proposal(mx.nd.array(cls), mx.nd.array(bbox),
                                  mx.nd.array(im_info), **kw)
    assert not isinstance(only, list)


def test_multi_proposal_batch():
    rs = np.random.RandomState(10)
    h, w, a, b = 4, 4, 2, 3
    cls = rs.rand(b, 2 * a, h, w).astype('float32')
    bbox = (rs.randn(b, 4 * a, h, w) * 0.1).astype('float32')
    im_info = np.tile(np.array([[64., 64., 1.0]], 'float32'), (b, 1))
    rois = mx.nd.contrib.MultiProposal(
        mx.nd.array(cls), mx.nd.array(bbox), mx.nd.array(im_info),
        feature_stride=16, scales=(4., 8.), ratios=(1.,),
        rpn_pre_nms_top_n=16, rpn_post_nms_top_n=5,
        threshold=0.7, rpn_min_size=2).asnumpy()
    assert rois.shape == (b * 5, 5)
    np.testing.assert_allclose(rois[:, 0],
                               np.repeat(np.arange(b), 5).astype('float32'))


# --- contrib: PSROIPooling -------------------------------------------------

def _np_psroi(data, rois, scale, od, p, g):
    # float32 throughout — the reference kernel computes bin edges in
    # float32, and edge ceil/floor results differ from float64 math
    r = rois.shape[0]
    _, c, h, w = data.shape
    f = np.float32
    scale = f(scale)
    out = np.zeros((r, od, p, p), 'float32')
    for n in range(r):
        bi = int(rois[n, 0])
        x1 = f(np.round(rois[n, 1]) * scale)
        y1 = f(np.round(rois[n, 2]) * scale)
        x2 = f((np.round(rois[n, 3]) + f(1)) * scale)
        y2 = f((np.round(rois[n, 4]) + f(1)) * scale)
        rw = max(f(x2 - x1), f(0.1))
        rh = max(f(y2 - y1), f(0.1))
        bh, bw = f(rh / f(p)), f(rw / f(p))
        for ct in range(od):
            for ph in range(p):
                for pw_ in range(p):
                    hs = min(max(int(np.floor(f(f(ph) * bh + y1))), 0), h)
                    he = min(max(int(np.ceil(f(f(ph + 1) * bh + y1))), 0), h)
                    ws = min(max(int(np.floor(f(f(pw_) * bw + x1))), 0), w)
                    we = min(max(int(np.ceil(f(f(pw_ + 1) * bw + x1))), 0), w)
                    gh = min(max(ph * g // p, 0), g - 1)
                    gw = min(max(pw_ * g // p, 0), g - 1)
                    ch = (ct * g + gh) * g + gw
                    if he <= hs or we <= ws:
                        continue
                    out[n, ct, ph, pw_] = data[bi, ch, hs:he, ws:we].mean()
    return out


def test_psroi_pooling_vs_numpy():
    rs = np.random.RandomState(11)
    od, p, g = 2, 3, 3
    data = rs.randn(2, od * g * g, 9, 9).astype('float32')
    rois = np.array([[0, 0, 0, 32, 32],
                     [1, 8, 4, 40, 28],
                     [0, 16, 16, 47, 47]], 'float32')
    out = mx.nd.contrib.PSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), spatial_scale=0.2,
        output_dim=od, pooled_size=p, group_size=g).asnumpy()
    ref = _np_psroi(data, rois, 0.2, od, p, g)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


# --- contrib: deformable ops ----------------------------------------------

def test_deformable_conv_zero_offset_matches_conv():
    rs = np.random.RandomState(12)
    x = rs.randn(2, 4, 7, 7).astype('float32')
    wgt = rs.randn(6, 4, 3, 3).astype('float32')
    bias = rs.randn(6).astype('float32')
    off = np.zeros((2, 2 * 3 * 3, 5, 5), 'float32')
    out = mx.nd.contrib.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(wgt),
        mx.nd.array(bias), kernel=(3, 3), num_filter=6).asnumpy()
    ref = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(wgt),
                            mx.nd.array(bias), kernel=(3, 3),
                            num_filter=6).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_deformable_conv_constant_shift():
    # offset (+1, +1) on a linear ramp == conv of the shifted image interior
    x = np.arange(36, dtype='float32').reshape(1, 1, 6, 6)
    wgt = np.ones((1, 1, 1, 1), 'float32')
    off = np.ones((1, 2, 6, 6), 'float32')
    out = mx.nd.contrib.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(wgt),
        kernel=(1, 1), num_filter=1, no_bias=True).asnumpy()
    # sample at (y+1, x+1): interior matches x shifted by one row+col
    np.testing.assert_allclose(out[0, 0, :5, :5], x[0, 0, 1:, 1:],
                               rtol=1e-5, atol=1e-5)
    # bottom/right samples fall outside -> 0
    np.testing.assert_allclose(out[0, 0, 5, :], 0.0)
    np.testing.assert_allclose(out[0, 0, :, 5], 0.0)


def test_deformable_conv_groups():
    rs = np.random.RandomState(13)
    x = rs.randn(1, 4, 5, 5).astype('float32')
    wgt = rs.randn(4, 2, 3, 3).astype('float32')  # 2 groups
    off = np.zeros((1, 2 * 9, 3, 3), 'float32')
    out = mx.nd.contrib.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(wgt),
        kernel=(3, 3), num_filter=4, num_group=2, no_bias=True).asnumpy()
    ref = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(wgt),
                            kernel=(3, 3), num_filter=4, num_group=2,
                            no_bias=True).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_deformable_psroi_no_trans_constant():
    # constant input -> every non-empty bin pools to that constant
    od, p = 2, 3
    g = p
    data = np.full((1, od * g * g, 8, 8), 2.5, 'float32')
    rois = np.array([[0, 4, 4, 28, 28]], 'float32')
    out = mx.nd.contrib.DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), mx.nd.array(
            np.zeros((1, 2, p, p), 'float32')),
        spatial_scale=0.25, output_dim=od, pooled_size=p, group_size=g,
        part_size=p, sample_per_part=2, trans_std=0.1).asnumpy()
    np.testing.assert_allclose(out, 2.5, rtol=1e-5)


def test_deformable_psroi_trans_shifts_window():
    # ramp image: positive x-translation increases pooled value
    od, p = 1, 1
    data = np.tile(np.arange(16, dtype='float32')[None, None, None, :],
                   (1, 1, 16, 1))
    rois = np.array([[0, 8, 8, 40, 40]], 'float32')
    trans0 = np.zeros((1, 2, 1, 1), 'float32')
    trans1 = np.zeros((1, 2, 1, 1), 'float32')
    trans1[0, 0] = 1.0  # x shift
    kw = dict(spatial_scale=0.25, output_dim=od, pooled_size=p,
              group_size=1, part_size=1, sample_per_part=4, trans_std=0.2)
    o0 = mx.nd.contrib.DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), mx.nd.array(trans0),
        **kw).asnumpy()
    o1 = mx.nd.contrib.DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), mx.nd.array(trans1),
        **kw).asnumpy()
    assert o1[0, 0, 0, 0] > o0[0, 0, 0, 0]


# --- op tail: round/reshape_like/slice_assign/sparse_retain/samplers -------

def test_round_half_away_from_zero():
    x = mx.nd.array(np.array([-2.5, -1.5, -0.5, 0.5, 1.5, 2.5], 'float32'))
    np.testing.assert_allclose(mx.nd.round(x).asnumpy(),
                               [-3., -2., -1., 1., 2., 3.])


def test_reshape_like():
    a = mx.nd.array(np.arange(6, dtype='float32'))
    b = mx.nd.array(np.zeros((2, 3), 'float32'))
    out = mx.nd.reshape_like(a, b)
    assert out.shape == (2, 3)
    np.testing.assert_allclose(out.asnumpy().ravel(), np.arange(6))


def test_slice_assign_and_scalar():
    a = mx.nd.array(np.zeros((4, 4), 'float32'))
    r = mx.nd.array(np.ones((2, 2), 'float32'))
    out = mx.nd._slice_assign(a, r, begin=(1, 1), end=(3, 3)).asnumpy()
    assert out[1:3, 1:3].sum() == 4 and out.sum() == 4
    out2 = mx.nd._slice_assign_scalar(a, scalar=7.0, begin=(0, 0),
                                      end=(1, 4)).asnumpy()
    np.testing.assert_allclose(out2[0], 7.0)
    np.testing.assert_allclose(out2[1:], 0.0)


def test_sparse_retain_op():
    d = mx.nd.array(np.arange(12, dtype='float32').reshape(4, 3))
    idx = mx.nd.array(np.array([1, 3], 'float32'))
    out = mx.nd.sparse_retain(d, idx).asnumpy()
    np.testing.assert_allclose(out[[1, 3]],
                               np.arange(12).reshape(4, 3)[[1, 3]])
    np.testing.assert_allclose(out[[0, 2]], 0.0)


def test_sample_negative_binomial_moments():
    k, p = 5.0, 0.4
    out = mx.nd._sample_negative_binomial(
        mx.nd.array(np.full((2,), k, 'float32')),
        mx.nd.array(np.full((2,), p, 'float32')), shape=(4000,)).asnumpy()
    assert out.shape == (2, 4000)
    mean = k * (1 - p) / p
    assert abs(out.mean() - mean) < 0.25 * mean
    assert (out >= 0).all() and np.allclose(out, np.round(out))


def test_sample_generalized_negative_binomial_moments():
    mu, alpha = 4.0, 0.25
    out = mx.nd._sample_generalized_negative_binomial(
        mx.nd.array(np.full((3,), mu, 'float32')),
        mx.nd.array(np.full((3,), alpha, 'float32')),
        shape=(4000,)).asnumpy()
    assert abs(out.mean() - mu) < 1.0
    # var = mu + alpha*mu^2 = 8
    assert 4.0 < out.var() < 14.0


def test_identity_attach_kl_sparse_reg_grad():
    rs = np.random.RandomState(14)
    x = rs.rand(6, 4).astype('float32') * 0.6 + 0.2  # sigmoid-like range
    data = mx.nd.array(x)
    data.attach_grad()
    moving_avg = mx.nd.zeros((4,))
    rho, penalty, momentum = 0.1, 0.01, 0.9
    with mx.autograd.record():
        y = mx.nd.IdentityAttachKLSparseReg(
            data, moving_avg, sparseness_target=rho, penalty=penalty,
            momentum=momentum)
        loss = y.sum()
    loss.backward()
    # forward is identity
    np.testing.assert_allclose(y.asnumpy(), x, rtol=1e-6)
    # moving avg after one step from 0 init: (1-momentum) * batch mean
    mu = (1 - momentum) * x.mean(axis=0)
    expect = 1.0 + penalty * (-rho / mu + (1 - rho) / (1 - mu))
    np.testing.assert_allclose(data.grad.asnumpy(),
                               np.tile(expect, (6, 1)), rtol=1e-4)


def test_grad_add_and_scatter_aliases():
    a = mx.nd.array(np.ones((3,), 'float32'))
    b = mx.nd.array(np.full((3,), 2.0, 'float32'))
    np.testing.assert_allclose(mx.nd._grad_add(a, b).asnumpy(), 3.0)
    np.testing.assert_allclose(
        mx.nd._scatter_minus_scalar(b, scalar=0.5).asnumpy(), 1.5)
    np.testing.assert_allclose(
        mx.nd._scatter_elemwise_div(b, a + 1).asnumpy(), 1.0)
    np.testing.assert_allclose(
        mx.nd._identity_with_attr_like_rhs(a, b).asnumpy(), 1.0)


def test_cast_storage_op_and_legacy_aliases():
    d = mx.nd.array(np.eye(3, dtype='float32'))
    np.testing.assert_allclose(mx.nd.cast_storage(d, stype="csr").asnumpy(),
                               np.eye(3))
    # legacy _v1 names resolve
    for name in ("BatchNorm_v1", "Convolution_v1", "Pooling_v1"):
        assert hasattr(mx.nd, name)


def test_spatial_grad_coverage():
    """Gradient checks for the differentiable spatial family beyond the
    single BilinearSampler case: SpatialTransformer end-to-end (grid +
    sampler + the affine loc-net weights), Correlation, and
    DeformableConvolution w.r.t. data/weight/offset."""
    rng = np.random.RandomState(2)
    # check_numeric_gradient draws its output-projection vectors from
    # GLOBAL np.random — pin it (and restore after) so the
    # kink-sensitive deformable check sees the same projections every
    # run without perturbing later tests' streams
    _state = np.random.get_state()
    np.random.seed(1234)
    try:
        _spatial_grad_checks(rng)
    finally:
        np.random.set_state(_state)


def _spatial_grad_checks(rng):
    # SpatialTransformer: d(out)/d(data) and d(out)/d(theta)
    data = rng.uniform(0.2, 1.0, (1, 1, 5, 5)).astype('f')
    theta = np.array([[0.9, 0.05, 0.02, -0.05, 0.95, -0.01]], 'f')
    st = mx.sym.SpatialTransformer(
        mx.sym.Variable('data'), mx.sym.Variable('theta'),
        target_shape=(4, 4), transform_type='affine',
        sampler_type='bilinear')
    tu.check_numeric_gradient(st, {'data': data, 'theta': theta},
                              numeric_eps=1e-3, rtol=5e-2, atol=1e-2)
    # Correlation: both inputs
    a = rng.uniform(0.2, 1.0, (1, 2, 5, 5)).astype('f')
    b = rng.uniform(0.2, 1.0, (1, 2, 5, 5)).astype('f')
    corr = mx.sym.Correlation(
        mx.sym.Variable('a'), mx.sym.Variable('b'), kernel_size=1,
        max_displacement=1, stride1=1, stride2=1, pad_size=1)
    tu.check_numeric_gradient(corr, {'a': a, 'b': b}, numeric_eps=1e-3,
                              rtol=5e-2, atol=1e-2)
    # DeformableConvolution: data, offset, weight all differentiable
    x = rng.uniform(0.2, 1.0, (1, 1, 5, 5)).astype('f')
    off = (0.1 * rng.randn(1, 18, 3, 3)).astype('f')
    w = rng.uniform(-0.5, 0.5, (2, 1, 3, 3)).astype('f')
    dc = mx.sym.contrib.DeformableConvolution(
        mx.sym.Variable('x'), mx.sym.Variable('off'),
        mx.sym.Variable('w'), kernel=(3, 3), num_filter=2, no_bias=True)
    # offset grads are piecewise (bilinear kinks at integer sample
    # positions): a finite difference that straddles a cell boundary is
    # off by the kink, so the tolerance is looser than for smooth args
    tu.check_numeric_gradient(dc, {'x': x, 'off': off, 'w': w},
                              numeric_eps=1e-3, rtol=8e-2, atol=4e-2)
