"""Pin JAX to a virtual multi-device CPU backend, stripping the axon tunnel.

One shared implementation of the backend-pinning dance every CPU-side entry
point needs (tests, CI, dist worker scripts, the multichip dryrun, ad-hoc
tools).  Why it exists:

* The axon TPU-tunnel plugin (registered by sitecustomize when
  ``PALLAS_AXON_POOL_IPS`` is set) admits ONE client at a time; letting a
  unit-test or dryrun process grab it deadlocks any concurrent benchmark
  and wastes the single real chip on work designed for virtual devices.
* ``xla_force_host_platform_device_count=N`` gives N CPU "chips" so
  sharding/collective paths compile and execute without TPU hardware —
  the reference's multiple-CPU-contexts test strategy (SURVEY.md §4).

Call :func:`pin_cpu` BEFORE any jax computation runs (import-time is fine:
XLA_FLAGS is read and the backend-factory table consulted at backend
*initialization*, which happens on first device use, not at ``import jax``).
"""
import os


def pin_cpu(n_devices=8, clear_backends=False):
    """Force the CPU platform with ``n_devices`` virtual devices.

    Returns the ``jax`` module for convenience.  ``clear_backends=True``
    additionally tears down any already-initialized backend (needed when a
    process may have touched devices before pinning, e.g. the driver
    calling ``dryrun_multichip`` after other jax work).

    ``n_devices=None`` leaves XLA_FLAGS untouched (one device per process —
    what the multi-process dist worker scripts want, where each process is
    its own "host" in the cluster).
    """
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + " --xla_force_host_platform_device_count=%d" % n_devices
            ).strip()
    import jax
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
    if clear_backends:
        try:
            jax.extend.backend.clear_backends()
        except Exception:  # noqa: BLE001 — older jax spells this differently
            pass
    return jax


def pin_if_cpu(n_devices=None):
    """Apply :func:`pin_cpu` iff the caller's environment selects the CPU
    platform (JAX_PLATFORMS=cpu[,...]).  The shared guard for every
    directly-runnable entry point (examples, tools, __graft_entry__,
    the embedded C ABI): with the axon tunnel plugin registered, backend
    init can block on a dead relay even when cpu is selected, so the
    factory must be stripped BEFORE the first jax touch."""
    import os
    if os.environ.get("JAX_PLATFORMS",
                      "").strip().lower().split(",")[0] == "cpu":
        pin_cpu(n_devices)
