/* C++ predict-equivalence harness: load a checkpoint (symbol JSON +
 * .params) through the Predictor API, run forward on a raw float32
 * input, write raw float32 logits.  Driven by tests/test_c_api.py,
 * which generates the checkpoint in Python and cross-asserts the C++
 * output against the Python forward — the reference proved its predict
 * path the same way (tests/python/gpu/test_forward.py over
 * c_predict_api consumers).
 *
 * usage: predict_golden <symbol.json> <file.params> <input.bin>
 *                       <N> <C> <H> <W> <out.bin>
 */
#include <cstdint>
#include <cstdio>
#include <fstream>
#include <iostream>
#include <sstream>
#include <string>
#include <vector>

#include "mxnet_tpu.hpp"

int main(int argc, char **argv) {
  if (argc != 9) {
    std::cerr << "usage: predict_golden <symbol.json> <file.params> "
                 "<input.bin> <N> <C> <H> <W> <out.bin>\n";
    return 2;
  }
  try {
    std::ifstream sf(argv[1]);
    std::stringstream ss;
    ss << sf.rdbuf();
    const std::string symbol_json = ss.str();

    const int64_t n = std::atoll(argv[4]), c = std::atoll(argv[5]),
                  h = std::atoll(argv[6]), w = std::atoll(argv[7]);
    std::vector<float> input(n * c * h * w);
    std::ifstream in(argv[3], std::ios::binary);
    in.read(reinterpret_cast<char *>(input.data()),
            input.size() * sizeof(float));
    if (!in) {
      std::cerr << "short read on " << argv[3] << "\n";
      return 2;
    }

    mxtpu::Predictor pred(symbol_json, argv[2], {"data"},
                          {{n, c, h, w}}, mxtpu::Context::cpu());
    pred.set_input("data", input);
    pred.forward();
    std::vector<float> out = pred.get_output(0);

    std::ofstream of(argv[8], std::ios::binary);
    of.write(reinterpret_cast<const char *>(out.data()),
             out.size() * sizeof(float));
    std::vector<int64_t> shape = pred.output_shape(0);
    std::cout << "output shape:";
    for (int64_t d : shape) std::cout << " " << d;
    std::cout << "\n";
    return 0;
  } catch (const std::exception &e) {
    std::cerr << "predict_golden failed: " << e.what() << "\n";
    return 1;
  }
}
