/*
 * C++ end-to-end TRAINING through the header-only user API
 * (mxnet_tpu.hpp Module/DataIter/KVStore over the round-4 C ABI rows) —
 * the reference cpp-package's train-from-C++ story
 * (reference: cpp-package/example/mlp.cpp: Symbol -> Executor ->
 * optimizer loop from C++).
 *
 * Trains the same MLP/dataset as cpp/train_smoke.c via the RAII
 * wrappers, then closes the loop deployment-style: save_checkpoint ->
 * Predictor over the saved params -> the predictor's probabilities on
 * the training batch must match the trained module's outputs.
 *
 * Prints "TRAIN GOLDEN OK nll=<x>" on success.
 */
#include <cmath>
#include <cstdio>
#include <cstring>
#include <unistd.h>

#include "mxnet_tpu.hpp"

static const char *kSymbolJson =
    "{\"nodes\":[{\"op\":\"null\",\"name\":\"data\",\"inputs\":[]},"
    "{\"op\":\"null\",\"name\":\"fc1_weight\",\"inputs\":[]},"
    "{\"op\":\"null\",\"name\":\"fc1_bias\",\"inputs\":[]},"
    "{\"op\":\"FullyConnected\",\"name\":\"fc1\",\"inputs\":[[0,0,0],[1,0,"
    "0],[2,0,0]],\"attrs\":{\"num_hidden\":\"16\"}},"
    "{\"op\":\"Activation\",\"name\":\"relu1\",\"inputs\":[[3,0,0]],"
    "\"attrs\":{\"act_type\":\"relu\"}},"
    "{\"op\":\"null\",\"name\":\"fc2_weight\",\"inputs\":[]},"
    "{\"op\":\"null\",\"name\":\"fc2_bias\",\"inputs\":[]},"
    "{\"op\":\"FullyConnected\",\"name\":\"fc2\",\"inputs\":[[4,0,0],[5,0,"
    "0],[6,0,0]],\"attrs\":{\"num_hidden\":\"2\"}},"
    "{\"op\":\"null\",\"name\":\"softmax_label\",\"inputs\":[]},"
    "{\"op\":\"SoftmaxOutput\",\"name\":\"softmax\",\"inputs\":[[7,0,0],"
    "[8,0,0]]}],\"arg_nodes\":[0,1,2,5,6,8],"
    "\"node_row_ptr\":[0,1,2,3,4,5,6,7,8,9,10],\"heads\":[[9,0,0]],"
    "\"attrs\":{\"mxnet_version\":[\"int\",1200]}}";

static const int N = 256, D = 8, BATCH = 64, EPOCHS = 8;

static unsigned long long lcg_state = 12345;
static float lcg_uniform() {
  lcg_state = lcg_state * 6364136223846793005ULL + 1442695040888963407ULL;
  return static_cast<float>((lcg_state >> 33) / 2147483648.0);
}

int main() {
  try {
    mxtpu::check(MXTInit(nullptr), "MXTInit");
    mxtpu::check(MXTRandomSeed(7), "RandomSeed");

    // same deterministic blobs as train_smoke.c
    static float x[N * D];
    static float y[N];
    for (int i = 0; i < N; ++i) {
      int cls = i % 2;
      y[i] = static_cast<float>(cls);
      for (int j = 0; j < D; ++j) {
        float noise = lcg_uniform() - 0.5f;
        x[i * D + j] =
            noise + (cls ? 0.9f : -0.9f) * (j % 3 == 0 ? 1.f : .3f);
      }
    }

    auto sym = mxtpu::Symbol::from_json(kSymbolJson);
    auto xa = mxtpu::NDArray::from_data(x, {N, D});
    auto ya = mxtpu::NDArray::from_data(y, {N});
    auto it = mxtpu::DataIter::from_arrays(xa, ya, BATCH);

    mxtpu::Module mod(sym, {"data"}, {"softmax_label"});
    mod.bind({"data"}, {{BATCH, D}}, {"softmax_label"}, {{BATCH}});
    mod.init_params("xavier",
                    {{"rnd_type", "gaussian"}, {"magnitude", "2.0"}});
    mod.init_optimizer("sgd",
                       {{"learning_rate", "0.2"}, {"momentum", "0.9"}});

    double nll = 0.0;
    int cnt = 0;
    for (int epoch = 0; epoch < EPOCHS; ++epoch) {
      it.before_first();
      nll = 0.0;
      cnt = 0;
      while (it.next()) {
        auto bx = it.data();
        auto by = it.label();
        mod.forward({&bx}, {&by});
        auto prob = mod.output(0).to_vector();
        auto lab = by.to_vector();
        for (int i = 0; i < BATCH; ++i) {
          float p = prob[i * 2 + static_cast<int>(lab[i])];
          nll += -std::log(p > 1e-8f ? p : 1e-8f);
          ++cnt;
        }
        mod.backward();
        mod.update();
      }
    }
    nll /= cnt;
    if (!(nll < 0.25)) {
      std::fprintf(stderr, "final nll %.4f did not reach 0.25\n", nll);
      return 1;
    }

    // deployment round-trip: checkpoint -> Predictor -> same probs
    // (per-process prefix: parallel runs must not clobber each other)
    char prefix[64], params_path[96];
    std::snprintf(prefix, sizeof(prefix), "/tmp/mxt_train_golden.%d",
                  static_cast<int>(getpid()));
    std::snprintf(params_path, sizeof(params_path), "%s-%04d.params",
                  prefix, EPOCHS);
    mod.save_checkpoint(prefix, EPOCHS);
    it.before_first();
    it.next();
    auto bx = it.data();
    mod.forward({&bx}, {}, /*is_train=*/false);
    auto want = mod.output(0).to_vector();

    mxtpu::Predictor pred(sym.to_json(), params_path, {"data"},
                          {{BATCH, D}});
    pred.set_input("data", bx.to_vector());
    pred.forward();
    auto got = pred.get_output(0);
    if (got.size() != want.size()) {
      std::fprintf(stderr, "predictor size %zu != module %zu\n",
                   got.size(), want.size());
      return 1;
    }
    for (size_t i = 0; i < got.size(); ++i) {
      if (std::fabs(got[i] - want[i]) > 1e-4f) {
        std::fprintf(stderr, "predictor[%zu] %g != %g\n", i, got[i],
                     want[i]);
        return 1;
      }
    }

    std::printf("TRAIN GOLDEN OK nll=%.6f\n", nll);
    return 0;
  } catch (const std::exception &e) {
    std::fprintf(stderr, "exception: %s\n", e.what());
    return 1;
  }
}
