/* Pure-C smoke of the embedded runtime: proves the flat C ABI
 * (mxnet_tpu/native/c_api.h) works from a plain C program with no
 * Python process around it — the reference's bindings consumed
 * include/mxnet/c_api.h the same way.  Prints "SMOKE OK" on success. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "../mxnet_tpu/native/c_api.h"

#define CHECK(rc, what)                                            \
  do {                                                             \
    if ((rc) != 0) {                                               \
      fprintf(stderr, "%s failed: %s\n", what, MXTGetLastError()); \
      return 1;                                                    \
    }                                                              \
  } while (0)

int main(void) {
  fprintf(stderr, "[smoke] init...\n");
  CHECK(MXTInit(NULL), "MXTInit");
  fprintf(stderr, "[smoke] init done\n");

  float data[4] = {1.0f, -2.0f, 3.0f, -4.0f};
  int64_t shape[2] = {2, 2};
  MXTHandle x = 0;
  fprintf(stderr, "[smoke] from_data (first jax touch)...\n");
  CHECK(MXTNDArrayFromData(data, shape, 2, "float32", 1, 0, &x),
        "MXTNDArrayFromData");
  fprintf(stderr, "[smoke] from_data done\n");

  int ndim = 0;
  CHECK(MXTNDArrayGetNDim(x, &ndim), "GetNDim");
  if (ndim != 2) {
    fprintf(stderr, "ndim %d != 2\n", ndim);
    return 1;
  }

  /* relu through the generic op invoke */
  MXTHandle outs[4];
  int nout = 4;
  CHECK(MXTImperativeInvoke("relu", 1, &x, 0, NULL, NULL, &nout, outs),
        "Invoke relu");
  if (nout != 1) {
    fprintf(stderr, "nout %d != 1\n", nout);
    return 1;
  }
  float got[4];
  CHECK(MXTNDArraySyncCopyToCPU(outs[0], got, sizeof(got)), "CopyToCPU");
  float want[4] = {1.0f, 0.0f, 3.0f, 0.0f};
  if (memcmp(got, want, sizeof(want)) != 0) {
    fprintf(stderr, "relu mismatch: [%g %g %g %g]\n", got[0], got[1],
            got[2], got[3]);
    return 1;
  }

  /* scalar-kwarg op: (x + 10) */
  const char *keys[1] = {"scalar"};
  const char *vals[1] = {"10"};
  MXTHandle out2[1];
  int nout2 = 1;
  CHECK(MXTImperativeInvoke("_plus_scalar", 1, &x, 1, keys, vals, &nout2,
                            out2),
        "Invoke _plus_scalar");
  CHECK(MXTNDArraySyncCopyToCPU(out2[0], got, sizeof(got)), "CopyToCPU2");
  if (got[0] != 11.0f || got[3] != 6.0f) {
    fprintf(stderr, "_plus_scalar mismatch: [%g %g %g %g]\n", got[0],
            got[1], got[2], got[3]);
    return 1;
  }

  /* op registry is visible */
  size_t needed = 0;
  CHECK(MXTListAllOpNames(NULL, 0, &needed), "ListAllOpNames");
  if (needed < 1000) {
    fprintf(stderr, "op list suspiciously small: %zu bytes\n", needed);
    return 1;
  }

  /* error path: bogus op must fail and set a message */
  MXTHandle out3[1];
  int nout3 = 1;
  if (MXTImperativeInvoke("no_such_op_xyz", 1, &x, 0, NULL, NULL, &nout3,
                          out3) == 0) {
    fprintf(stderr, "bogus op unexpectedly succeeded\n");
    return 1;
  }
  if (strlen(MXTGetLastError()) == 0) {
    fprintf(stderr, "error message empty after failure\n");
    return 1;
  }

  CHECK(MXTNDArrayFree(outs[0]), "Free");
  CHECK(MXTNDArrayFree(out2[0]), "Free2");
  CHECK(MXTNDArrayFree(x), "FreeX");
  CHECK(MXTShutdown(), "Shutdown");
  printf("SMOKE OK\n");
  return 0;
}
