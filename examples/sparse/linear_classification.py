"""Sparse end-to-end linear classification
(reference: benchmark/python/sparse/sparse_end2end.py — CSR inputs,
row_sparse weight gradients, kvstore row_sparse_pull of just the rows a
batch touches, and a sparse optimizer update that leaves untouched rows
alone).

TPU-native shape of the same pipeline:
 * the CSR batch's column indices drive ``nd.Embedding(sparse_grad=True)``
   — mathematically X_csr · W with O(nnz) work, and autograd returns the
   gradient as a RowSparseNDArray over exactly the touched rows (the
   reference's ``mx.symbol.sparse.dot`` + row_sparse grad);
 * before each step the touched rows are fetched with
   ``kv.row_sparse_pull(row_ids=...)`` — the reference's
   ``row_sparse_pull(kv, 'w', data, ...)`` move;
 * the optimizer's sparse path updates ONLY the touched rows (lazy
   update semantics, as the reference documents for sparse sgd/adam);
 * the whole run is asserted densify-free: the O(nnz) claim is checked
   by the densify telltale, not taken on faith.

Run:  python examples/sparse/linear_classification.py [--epochs 5]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, nd  # noqa: E402
from mxnet_tpu.ndarray import sparse  # noqa: E402


def make_dataset(num_samples=2048, num_features=10000, nnz=16, seed=0):
    """Synthetic multi-hot dataset with a planted sparse weight: each row
    has `nnz` active features with +-1 values; the label is the sign of
    the planted weight's response (what criteo/avazu look like once
    hashed, reference sparse_end2end.py's data shape)."""
    rng = np.random.RandomState(seed)
    planted = rng.randn(num_features).astype(np.float32)
    cols = np.stack([rng.choice(num_features, nnz, replace=False)
                     for _ in range(num_samples)])          # (N, nnz)
    vals = rng.choice([-1.0, 1.0], (num_samples, nnz)).astype(np.float32)
    margin = (planted[cols] * vals).sum(axis=1)
    y = (margin > 0).astype(np.float32)
    return cols.astype(np.float32), vals, y, planted


def train(epochs=5, batch=128, num_features=10000, nnz=16, lr=0.5,
          optimizer='sgd', seed=0, log=print):
    cols, vals, y, planted = make_dataset(num_features=num_features,
                                          nnz=nnz, seed=seed)
    n = len(y)
    kv = mx.kv.create('local')

    w = nd.zeros((num_features, 1))
    w.attach_grad(stype='row_sparse')   # autograd emits row_sparse grads
    bias = nd.zeros((1,))
    bias.attach_grad()
    kv.init('w', w)

    opt = mx.optimizer.create(optimizer, learning_rate=lr)
    w_state = opt.create_state(0, w)
    b_state = opt.create_state(1, bias)

    densify_start = sparse.DENSIFY_COUNT
    history = []
    for epoch in range(epochs):
        loss_sum = 0.0
        correct = 0
        for i in range(n // batch):
            sl = slice(i * batch, (i + 1) * batch)
            bc = nd.array(cols[sl])          # (B, nnz) column ids
            bv = nd.array(vals[sl])          # (B, nnz) values
            by = nd.array(y[sl])             # (B,)

            # the reference's row_sparse_pull: fetch only touched rows,
            # and VERIFY them against the published weight (the store
            # holds what the last kv.push sent)
            row_ids = np.unique(cols[sl]).astype(np.float32)
            pulled = sparse.zeros('row_sparse', w.shape)
            kv.row_sparse_pull('w', out=pulled, row_ids=nd.array(row_ids))
            np.testing.assert_allclose(
                pulled.data.asnumpy(),
                w.asnumpy()[row_ids.astype(int)], rtol=1e-6, atol=1e-7,
                err_msg="row_sparse_pull returned stale/wrong rows")

            with autograd.record():
                # X_csr . W via embedding-gather: O(nnz), sparse grad
                emb = nd.Embedding(bc, w, input_dim=num_features,
                                   output_dim=1, sparse_grad=True)
                logits = (emb.reshape((batch, nnz)) * bv).sum(axis=1) \
                    + bias
                p = nd.sigmoid(logits)
                eps = 1e-7
                loss = -(by * nd.log(p + eps)
                         + (1 - by) * nd.log(1 - p + eps)).mean()
            loss.backward()

            assert isinstance(w.grad, sparse.RowSparseNDArray), \
                "gradient densified — the O(nnz) contract broke"
            opt.update(0, w, w.grad, list(w_state))
            opt.update(1, bias, bias.grad, list(b_state))
            # the reference's sparse push: publish updated rows
            kv.push('w', w)

            loss_sum += float(loss.asscalar())
            correct += int(((p.asnumpy() > 0.5) == (y[sl] > 0.5)).sum())
        history.append({'epoch': epoch,
                        'loss': loss_sum / (n // batch),
                        'acc': correct / ((n // batch) * batch)})
        log("epoch %d loss %.4f acc %.4f"
            % (epoch, history[-1]['loss'], history[-1]['acc']))
    # O(nnz) held end-to-end: nothing on the sparse path densified
    assert sparse.DENSIFY_COUNT == densify_start, \
        "sparse path densified %d time(s)" \
        % (sparse.DENSIFY_COUNT - densify_start)
    return history, w


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=5)
    ap.add_argument('--batch', type=int, default=128)
    ap.add_argument('--num-features', type=int, default=10000)
    ap.add_argument('--optimizer', type=str, default='sgd')
    a = ap.parse_args()
    history, _ = train(epochs=a.epochs, batch=a.batch,
                       num_features=a.num_features, optimizer=a.optimizer)
    print("final acc %.4f" % history[-1]['acc'])


if __name__ == '__main__':
    main()
