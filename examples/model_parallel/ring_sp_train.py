#!/usr/bin/env python
"""Long-context LM training with ring attention over the ``sp`` axis.

The long-context recipe this framework ships (no reference analog —
MXNet 0.12 predates sequence parallelism, SURVEY.md §5.7): tokens are
sharded along the SEQUENCE over the sp ring, attention runs as the
exact blockwise ring (``parallel.ring_attention`` — K/V rotate via
ppermute, online softmax, O((S/n)^2) score memory per device), and the
loss head is the chunked CE (``ops/chunked_loss.py`` — the (N, V)
logits never materialize).  Peak per-device memory is therefore
independent of BOTH quadratic attention scores AND the vocab axis: the
two walls that cap context length.

One jitted SPMD train step over a dp×sp mesh; GSPMD shards the
embedding/FFN math from the input shardings, ring attention rides
shard_map inside the same program.

Runs on the virtual CPU mesh out of the box:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/model_parallel/ring_sp_train.py --steps 30
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..', '..'))
from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from mxnet_tpu import parallel as par  # noqa: E402
from mxnet_tpu.ops.chunked_loss import chunked_lm_loss  # noqa: E402


def init_params(key, vocab, d_model, d_ff, heads):
    ks = jax.random.split(key, 6)
    s = lambda k, shp, fan: (jax.random.normal(k, shp) / np.sqrt(fan))
    return {
        "embed": s(ks[0], (vocab, d_model), d_model),
        "wqkv": s(ks[1], (d_model, 3 * d_model), d_model),
        "wo": s(ks[2], (d_model, d_model), d_model),
        "w1": s(ks[3], (d_model, d_ff), d_model),
        "w2": s(ks[4], (d_ff, d_model), d_ff),
        "head_b": jnp.zeros((vocab,)),
    }


def model_loss(params, tokens, labels, mesh, heads):
    B, S = tokens.shape
    d_model = params["embed"].shape[1]
    hd = d_model // heads
    x = params["embed"][tokens.astype(jnp.int32)]          # (B, S, D)
    qkv = x @ params["wqkv"]                               # (B, S, 3D)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def bhsd(t):  # (B, S, D) -> (B, H, S, hd)
        return t.reshape(B, S, heads, hd).transpose(0, 2, 1, 3)

    # the sp ring: exact causal attention with seq-sharded q/k/v
    a = par.ring_attention(bhsd(q), bhsd(k), bhsd(v), mesh, causal=True)
    a = a.transpose(0, 2, 1, 3).reshape(B, S, d_model)
    x = x + a @ params["wo"]
    x = x + jax.nn.gelu(x @ params["w1"]) @ params["w2"]
    # chunked CE against the TIED embedding: no (B*S, V) logits
    loss = chunked_lm_loss(x.reshape(B * S, d_model), params["embed"],
                           params["head_b"],
                           labels.reshape(B * S), 4)
    return loss.mean()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=80)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--lr", type=float, default=1.0)
    args = ap.parse_args()
    if args.steps < 1:
        ap.error("--steps must be >= 1")

    mesh = par.make_mesh(dp=2, sp=4, devices=jax.devices())
    data_sh = NamedSharding(mesh, P("dp", "sp"))   # (B, S) tokens
    rep = NamedSharding(mesh, P())

    rs = np.random.RandomState(0)
    first = rs.randint(0, args.vocab, (args.batch, 1))
    seq = (first + np.arange(args.seq + 1)) % args.vocab
    tokens = jax.device_put(seq[:, :-1].astype(np.int32), data_sh)
    labels = jax.device_put(seq[:, 1:].astype(np.int32), data_sh)

    params = jax.device_put(
        init_params(jax.random.PRNGKey(0), args.vocab, args.d_model,
                    4 * args.d_model, args.heads), rep)

    @jax.jit
    def step(params, tokens, labels):
        loss, grads = jax.value_and_grad(model_loss)(
            params, tokens, labels, mesh, args.heads)
        params = jax.tree_util.tree_map(
            lambda p, g: p - args.lr * g, params, grads)
        return params, loss

    first_loss = None
    for i in range(args.steps):
        params, loss = step(params, tokens, labels)
        if first_loss is None:
            first_loss = float(loss)
        if i % 10 == 0:
            print("step %d loss %.4f" % (i, float(loss)), flush=True)
    final = float(loss)
    print("ring-sp train: loss %.4f -> %.4f" % (first_loss, final),
          flush=True)
    assert final < 0.5 * first_loss, (first_loss, final)


if __name__ == "__main__":
    main()
