#!/usr/bin/env python
"""Model-parallel training example (reference:
example/model-parallel-lstm + tests/python/unittest/test_model_parallel.py).

The reference places layer groups on different GPUs with
``group2ctx``/``__ctx_group__`` and lets the nnvm PlaceDevice pass insert
cross-device copies.  TPU-native, placement is DECLARATIVE: build a
dp×tp mesh, derive Megatron-style sharding rules for the symbol
(FC/conv weights split along output features over ``tp``), and GSPMD
inserts the collectives.  The same script runs an LSTM LM with its
projection layers tensor-sharded — the modern form of the reference's
model-parallel LSTM.

Runs on the virtual CPU mesh out of the box:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/model_parallel/train_model_parallel.py --synthetic
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..'))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..', '..'))
from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu()
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import parallel as par  # noqa: E402


def build_lstm_lm(vocab, num_embed, num_hidden, seq_len):
    data = mx.sym.Variable('data')
    emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_embed,
                           name='embed')
    cell = mx.rnn.FusedRNNCell(num_hidden, num_layers=2, mode='lstm',
                               prefix='lstm_')
    out, _ = cell.unroll(seq_len, emb, merge_outputs=True, layout='NTC')
    out = mx.sym.Reshape(out, shape=(-1, num_hidden))
    # the projection FC is the tensor-sharded hot matmul
    fc = mx.sym.FullyConnected(out, num_hidden=vocab, name='decoder')
    label = mx.sym.Reshape(mx.sym.Variable('softmax_label'), shape=(-1,))
    return mx.sym.SoftmaxOutput(fc, label, name='softmax')


def synthetic_corpus(n, seq_len, vocab, seed=0):
    rs = np.random.RandomState(seed)
    first = rs.randint(0, vocab, (n, 1))
    seq = (first + np.arange(seq_len + 1)) % vocab  # learnable pattern
    return (seq[:, :seq_len].astype('float32'),
            seq[:, 1:].astype('float32'))


if __name__ == '__main__':
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument('--tp', type=int, default=2,
                    help='tensor-parallel ways (mesh tp axis)')
    ap.add_argument('--seq-len', type=int, default=12)
    ap.add_argument('--vocab', type=int, default=64)
    ap.add_argument('--num-embed', type=int, default=32)
    ap.add_argument('--num-hidden', type=int, default=64)
    ap.add_argument('--batch-size', type=int, default=16)
    ap.add_argument('--num-epochs', type=int, default=4)
    ap.add_argument('--num-examples', type=int, default=512)
    ap.add_argument('--synthetic', action='store_true')
    args = ap.parse_args()

    net = build_lstm_lm(args.vocab, args.num_embed, args.num_hidden,
                        args.seq_len)
    mesh = par.make_mesh(tp=args.tp)  # dp = remaining devices
    rules = par.tp_rules_for_symbol(net, mesh)
    logging.info('mesh: %s; %d sharded params', mesh.shape,
                 len(rules.rules) if hasattr(rules, 'rules') else -1)

    x, y = synthetic_corpus(args.num_examples, args.seq_len, args.vocab)
    it = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True)

    mod = mx.mod.Module(net, mesh=mesh, sharding_rules=rules,
                        data_names=('data',),
                        label_names=('softmax_label',))
    metric = mx.metric.Perplexity(ignore_label=None)
    mod.fit(it, num_epoch=args.num_epochs, optimizer='adam',
            optimizer_params={'learning_rate': 3e-3},
            initializer=mx.initializer.Xavier(),
            eval_metric=metric,
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       16))
    # show the decoder weight really is sharded over tp
    w = mod._exec.arg_dict['decoder_weight']._data
    shard_shapes = sorted({s.data.shape for s in w.addressable_shards})
    logging.info('decoder_weight global %s, shard shapes %s',
                 tuple(w.shape), shard_shapes)
    print('model-parallel training done; decoder shards:', shard_shapes)
