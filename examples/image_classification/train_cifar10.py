#!/usr/bin/env python
"""Train ResNet on CIFAR-10 (reference:
example/image-classification/train_cifar10.py).

With --synthetic (or when the RecordIO files are missing) a generated
CIFAR-shaped dataset is used so the script runs in no-egress CI; point
--data-dir at cifar10_train.rec / cifar10_val.rec for the real thing.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..'))
import common  # noqa: E402
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402


def get_cifar_iters(args):
    rec = os.path.join(args.data_dir, 'cifar10_train.rec')
    if not args.synthetic and os.path.exists(rec):
        train = mx.io.ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 28, 28),
            batch_size=args.batch_size, rand_crop=True, rand_mirror=True,
            shuffle=True)
        vrec = os.path.join(args.data_dir, 'cifar10_val.rec')
        val = (mx.io.ImageRecordIter(
            path_imgrec=vrec, data_shape=(3, 28, 28),
            batch_size=args.batch_size) if os.path.exists(vrec) else None)
        return train, val
    # synthetic: class = dominant color/position pattern
    rng = np.random.RandomState(0)
    n = min(args.num_examples, 5000)
    y = rng.randint(0, 10, (n,)).astype('float32')
    x = rng.rand(n, 3, 28, 28).astype('float32') * 0.2
    for i in range(n):
        c = int(y[i])
        x[i, c % 3, (c // 3) * 7:(c // 3) * 7 + 7, :] += 0.7
    split = int(n * 0.9)
    train = mx.io.NDArrayIter(x[:split], y[:split], args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(x[split:], y[split:], args.batch_size)
    return train, val


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    common.add_fit_args(parser)
    parser.add_argument('--data-dir', type=str, default='data/cifar10')
    parser.add_argument('--synthetic', action='store_true')
    parser.set_defaults(network='resnet', num_layers=20, num_epochs=10,
                        batch_size=128, lr=0.05, num_examples=50000)
    args = parser.parse_args()
    net = models.resnet(num_classes=10,
                        num_layers=getattr(args, 'num_layers', 20) or 20,
                        image_shape=(3, 28, 28))
    train, val = get_cifar_iters(args)
    common.fit(args, net, train, val)
