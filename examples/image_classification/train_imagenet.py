#!/usr/bin/env python
"""Train ImageNet-class CNNs (reference:
example/image-classification/train_imagenet.py — the north-star entry).

Data comes from RecordIO files produced by tools/im2rec.py
(--data-train/--data-val), or --benchmark 1 runs on synthetic data — the
reference script's own throughput-benchmark mode.  --dtype bfloat16
enables mixed precision (fp32 master weights).
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..'))
import common  # noqa: E402
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402


class SyntheticIter(mx.io.DataIter):
    """reference: train_imagenet.py --benchmark synthetic data path."""

    def __init__(self, batch_size, image_shape, num_classes, batches=50):
        super().__init__(batch_size)
        rng = np.random.RandomState(0)
        self._x = mx.nd.array(rng.uniform(
            -1, 1, (batch_size,) + image_shape).astype('float32'))
        self._y = mx.nd.array(
            rng.randint(0, num_classes, (batch_size,)).astype('float32'))
        self._n = batches
        self._i = 0
        self.provide_data = [mx.io.DataDesc(
            'data', (batch_size,) + image_shape)]
        self.provide_label = [mx.io.DataDesc(
            'softmax_label', (batch_size,))]

    def reset(self):
        self._i = 0

    def next(self):
        if self._i >= self._n:
            raise StopIteration
        self._i += 1
        return mx.io.DataBatch([self._x], [self._y],
                               provide_data=self.provide_data,
                               provide_label=self.provide_label)


def get_iters(args, image_shape):
    if args.benchmark:
        return (SyntheticIter(args.batch_size, image_shape,
                              args.num_classes, args.benchmark_iters),
                None)
    if args.uint8_rec:
        # raw pre-decoded records (tools/im2rec.py --pack-raw 256): no JPEG
        # decode at training time; normalization happens on device (the
        # net's bn_data input BatchNorm) so batches stay uint8 end-to-end
        train = mx.io.ImageRecordUInt8Iter(
            path_imgrec=args.data_train, data_shape=image_shape,
            batch_size=args.batch_size, shuffle=True, rand_mirror=True,
            rand_crop=True, part_index=0, num_parts=1)
        val = None
        if args.data_val:
            val = mx.io.ImageRecordUInt8Iter(
                path_imgrec=args.data_val, data_shape=image_shape,
                batch_size=args.batch_size)
        return train, val
    train = mx.io.ImageRecordIter(
        path_imgrec=args.data_train, data_shape=image_shape,
        batch_size=args.batch_size, shuffle=True, rand_mirror=True,
        rand_crop=True, resize=256,
        mean_r=123.68, mean_g=116.779, mean_b=103.939,
        part_index=0, num_parts=1)
    val = None
    if args.data_val:
        val = mx.io.ImageRecordIter(
            path_imgrec=args.data_val, data_shape=image_shape,
            batch_size=args.batch_size, resize=256,
            mean_r=123.68, mean_g=116.779, mean_b=103.939)
    return train, val


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    common.add_fit_args(parser)
    parser.add_argument('--data-train', type=str, default=None)
    parser.add_argument('--data-val', type=str, default=None)
    parser.add_argument('--image-shape', type=str, default='3,224,224')
    parser.add_argument('--num-classes', type=int, default=1000)
    parser.add_argument('--num-layers', type=int, default=50)
    parser.add_argument('--benchmark', type=int, default=0)
    parser.add_argument('--benchmark-iters', type=int, default=50)
    parser.add_argument('--uint8-rec', action='store_true',
                        help='data-train/-val are raw pre-decoded records '
                        '(tools/im2rec.py --pack-raw); skips JPEG decode')
    parser.set_defaults(network='resnet', num_epochs=1, batch_size=256,
                        lr=0.1, lr_step_epochs='30,60,90',
                        num_examples=1281167, dtype='bfloat16')
    args = parser.parse_args()
    image_shape = tuple(int(x) for x in args.image_shape.split(','))
    net = models.get_symbol(args.network, num_classes=args.num_classes,
                            num_layers=args.num_layers,
                            image_shape=args.image_shape)
    train, val = get_iters(args, image_shape)
    common.fit(args, net, train, val)
