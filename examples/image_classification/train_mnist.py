#!/usr/bin/env python
"""Train an MLP / LeNet on MNIST (reference:
example/image-classification/train_mnist.py — BASELINE config 1).

With --synthetic (or when the IDX files are missing) a generated
MNIST-shaped dataset is used, so the script runs in no-egress CI; point
--data-dir at real train-images-idx3-ubyte/train-labels-idx1-ubyte files
for the real thing.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..'))
import common  # noqa: E402
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402


def get_mnist_iters(args):
    ip = os.path.join(args.data_dir, 'train-images-idx3-ubyte')
    lp = os.path.join(args.data_dir, 'train-labels-idx1-ubyte')
    flat = args.network == 'mlp'
    if not args.synthetic and os.path.exists(ip):
        train = mx.io.MNISTIter(image=ip, label=lp,
                                batch_size=args.batch_size, flat=flat)
        return train, None
    # synthetic: class = quadrant-mean pattern, learnable by an MLP
    rng = np.random.RandomState(0)
    n = min(args.num_examples, 6000)
    y = rng.randint(0, 10, (n,)).astype('float32')
    x = rng.rand(n, 1, 28, 28).astype('float32') * 0.1
    for i in range(n):
        c = int(y[i])
        x[i, 0, (c // 5) * 14:(c // 5) * 14 + 14,
          (c % 5) * 5:(c % 5) * 5 + 5] += 0.8
    if flat:
        x = x.reshape(n, 784)
    split = int(n * 0.9)
    train = mx.io.NDArrayIter(x[:split], y[:split], args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(x[split:], y[split:], args.batch_size)
    return train, val


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    common.add_fit_args(parser)
    parser.add_argument('--data-dir', type=str, default='data/mnist')
    parser.add_argument('--synthetic', action='store_true')
    parser.set_defaults(network='mlp', num_epochs=5, batch_size=64,
                        lr=0.05, num_examples=60000)
    args = parser.parse_args()
    if args.network == 'mlp':
        net = models.mlp(num_classes=10)
    else:
        net = models.lenet(num_classes=10)
    train, val = get_mnist_iters(args)
    common.fit(args, net, train, val)
