#!/usr/bin/env python
"""Fine-tune a pretrained checkpoint on a new dataset (reference:
example/image-classification/fine-tune.py).

The classifier head is cut at the last flatten layer and replaced with a
fresh FullyConnected + SoftmaxOutput sized for the new task; all other
weights start from the checkpoint (``get_fine_tune_model``, like the
reference's).  With --synthetic a small LeNet is first trained and saved,
then fine-tuned to a different label space — the whole flow runs without
any downloads.
"""
import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..'))
import common  # noqa: E402
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402


def get_fine_tune_model(symbol, arg_params, num_classes,
                        layer_name='flatten'):
    """Replace everything above ``layer_name`` with a fresh classifier
    (reference: fine-tune.py get_fine_tune_model)."""
    internals = symbol.get_internals()
    outputs = [o for o in internals.list_outputs()
               if layer_name in o and o.endswith('_output')]
    if not outputs:
        raise ValueError(
            f"no internal output matching {layer_name!r}; "
            f"have {internals.list_outputs()[-10:]}")
    net = internals[outputs[-1]]
    net = mx.sym.FullyConnected(data=net, num_hidden=num_classes,
                                name='fc_finetune')
    net = mx.sym.SoftmaxOutput(data=net, name='softmax')
    keep = set(net.list_arguments())
    new_args = {k: v for k, v in arg_params.items()
                if k in keep and not k.startswith('fc_finetune')}
    return net, new_args


def _synthetic_data(num_classes, n, seed):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, num_classes, (n,)).astype('float32')
    x = rng.rand(n, 1, 28, 28).astype('float32') * 0.1
    for i in range(n):
        c = int(y[i])
        x[i, 0, (c % 4) * 7:(c % 4) * 7 + 7, :] += 0.8
    return x, y


if __name__ == '__main__':
    import logging
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    common.add_fit_args(parser)
    parser.add_argument('--pretrained-model', type=str, default=None,
                        help='checkpoint prefix to start from')
    parser.add_argument('--pretrained-epoch', type=int, default=0)
    parser.add_argument('--layer-name', type=str, default='flatten')
    parser.add_argument('--num-classes', type=int, default=4)
    parser.add_argument('--synthetic', action='store_true')
    parser.set_defaults(network='lenet', num_epochs=2, batch_size=32,
                        lr=0.01, num_examples=1024)
    args = parser.parse_args()

    if args.pretrained_model is None:
        if not args.synthetic:
            parser.error('--pretrained-model required without --synthetic')
        # pretrain a tiny LeNet on a 10-class synthetic task, save it
        prefix = os.path.join(tempfile.mkdtemp(), 'pretrain')
        net = models.lenet(num_classes=10)
        x, y = _synthetic_data(10, args.num_examples, seed=0)
        it = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True)
        mod = mx.mod.Module(net, context=mx.tpu(0))
        mod.fit(it, num_epoch=1,
                optimizer='sgd',
                optimizer_params={'learning_rate': 0.05},
                initializer=mx.initializer.Xavier(),
                batch_end_callback=mx.callback.Speedometer(
                    args.batch_size, 50))
        mod.save_checkpoint(prefix, 1)
        args.pretrained_model, args.pretrained_epoch = prefix, 1

    sym, arg_params, aux_params = mx.model.load_checkpoint(
        args.pretrained_model, args.pretrained_epoch)
    net, new_args = get_fine_tune_model(sym, arg_params, args.num_classes,
                                        args.layer_name)

    x, y = _synthetic_data(args.num_classes, args.num_examples, seed=1)
    split = min(int(len(y) * 0.9), len(y) - args.batch_size)
    train = mx.io.NDArrayIter(x[:split], y[:split], args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(x[split:], y[split:], args.batch_size)

    mod = mx.mod.Module(net, context=mx.tpu(0))
    mod.fit(train, val, num_epoch=args.num_epochs,
            arg_params=new_args, aux_params=aux_params,
            allow_missing=True,
            optimizer='sgd',
            optimizer_params={'learning_rate': args.lr, 'momentum': 0.9},
            initializer=mx.initializer.Xavier(rnd_type='gaussian',
                                              factor_type='in',
                                              magnitude=2),
            eval_metric='acc',
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    print('fine-tune done')
