"""Fast Gradient Sign Method adversarial examples
(reference: example/adversary/adversary_generation.ipynb — train a
small net, take d(loss)/d(input), perturb the image by
eps * sign(grad), watch accuracy collapse).

The distinctive API here is gradients THROUGH a trained module back to
the data: the reference bound its executor with inputs_need_grad; this
port trains with Module, then drives the attack imperatively with
``autograd`` over the module's parameters — same math, the tape instead
of a bound executor slot.

Run:  python examples/adversary/fgsm.py [--eps 0.15]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, nd  # noqa: E402


def load_digits_data():
    from sklearn.datasets import load_digits
    d = load_digits()
    x = (d.images / 16.0).astype(np.float32)[:, None, :, :]  # (N,1,8,8)
    y = d.target.astype(np.float32)
    return x[:1500], y[:1500], x[1500:], y[1500:]


def net_symbol():
    data = mx.sym.Variable('data')
    h = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                           pad=(1, 1), name='c1')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type='max')
    h = mx.sym.Flatten(h)
    h = mx.sym.FullyConnected(h, num_hidden=64, name='f1')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=10, name='f2')
    return mx.sym.SoftmaxOutput(h, name='softmax')


def train_model(xtr, ytr, epochs=6, batch=100, seed=0):
    it = mx.io.NDArrayIter(xtr, ytr, batch, shuffle=True,
                           last_batch_handle='discard')
    mx.random.seed(seed)
    mod = mx.mod.Module(net_symbol(), context=mx.cpu())
    mod.fit(it, num_epoch=epochs, optimizer='adam',
            optimizer_params={'learning_rate': 2e-3},
            initializer=mx.initializer.Xavier())
    return mod


def fgsm_attack(mod, x, y, eps):
    """eps * sign(d NLL / d x), computed on the tape against the trained
    module's parameters."""
    args, _ = mod.get_params()
    w = {k: v for k, v in args.items()}
    xv = nd.array(x)
    xv.attach_grad()
    with autograd.record():
        h = nd.Convolution(xv, w['c1_weight'], w['c1_bias'],
                           kernel=(3, 3), pad=(1, 1), num_filter=16)
        h = nd.relu(h)
        h = nd.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type='max')
        h = nd.Flatten(h)
        h = nd.relu(nd.FullyConnected(h, w['f1_weight'], w['f1_bias'],
                                      num_hidden=64))
        logits = nd.FullyConnected(h, w['f2_weight'], w['f2_bias'],
                                   num_hidden=10)
        logp = nd.log_softmax(logits)
        idx = nd.one_hot(nd.array(y), 10)
        loss = -(logp * idx).sum() / len(y)
    loss.backward()
    return np.clip(x + eps * np.sign(xv.grad.asnumpy()), 0.0, 1.0)


def accuracy(mod, x, y, batch=100):
    it = mx.io.NDArrayIter(x, y, batch)
    return mod.score(it, 'acc')[0][1]


def run(eps=0.15, epochs=6, log=print):
    xtr, ytr, xte, yte = load_digits_data()
    mod = train_model(xtr, ytr, epochs=epochs)
    clean = accuracy(mod, xte, yte)
    x_adv = fgsm_attack(mod, xte, yte, eps)
    adv = accuracy(mod, x_adv, yte)
    log("clean acc %.4f -> adversarial acc %.4f (eps=%.3f, "
        "mean |dx|=%.4f)" % (clean, adv, eps,
                             float(np.abs(x_adv - xte).mean())))
    return clean, adv


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--eps', type=float, default=0.15)
    ap.add_argument('--epochs', type=int, default=6)
    a = ap.parse_args()
    clean, adv = run(eps=a.eps, epochs=a.epochs)
    print("fgsm done: clean %.4f adversarial %.4f" % (clean, adv))


if __name__ == '__main__':
    main()
