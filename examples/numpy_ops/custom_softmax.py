"""Training through a pure-numpy custom operator
(reference: example/numpy-ops/custom_softmax.py — a softmax loss head
written as a Python CustomOp: numpy forward, hand-written backward
``prob - onehot``, plugged into a symbolic net and trained).

This is the extensibility story: ops the framework doesn't ship can be
written in Python/numpy and still participate in symbolic training —
the executor routes them through ``jax.pure_callback`` so the rest of
the graph remains one compiled XLA program.

Run:  python examples/numpy_ops/custom_softmax.py [--epochs 10]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402


@mx.operator.register("numpy_softmax")
class NumpySoftmaxProp(mx.operator.CustomOpProp):
    """reference custom_softmax.py NumpySoftmaxProp: loss head, no top
    grad (the gradient is defined by the op itself)."""

    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ['data', 'label']

    def list_outputs(self):
        return ['output']

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return NumpySoftmax()


class NumpySoftmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        lab = in_data[1].asnumpy().ravel().astype(int)
        y = out_data[0].asnumpy().copy()
        y[np.arange(lab.shape[0]), lab] -= 1.0
        self.assign(in_grad[0], req[0], y)


def net_symbol():
    data = mx.sym.Variable('data')
    label = mx.sym.Variable('softmax_label')
    h = mx.sym.FullyConnected(data, num_hidden=64, name='fc1')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=10, name='fc2')
    return mx.sym.Custom(h, label, op_type='numpy_softmax',
                         name='softmax')


def run(epochs=10, batch=100, seed=0, log=print):
    from sklearn.datasets import load_digits
    d = load_digits()
    x = (d.images.reshape(len(d.images), -1) / 16.0).astype(np.float32)
    y = d.target.astype(np.float32)
    n = 1500
    # seed numpy BEFORE building the iterators: NDArrayIter's shuffle
    # draws from global np.random at construction time
    np.random.seed(seed)
    mx.random.seed(seed)
    train = mx.io.NDArrayIter(x[:n], y[:n], batch, shuffle=True,
                              last_batch_handle='discard')
    test = mx.io.NDArrayIter(x[n:], y[n:], batch)
    mod = mx.mod.Module(net_symbol(), context=mx.cpu())
    mod.fit(train, num_epoch=epochs, optimizer='sgd',
            optimizer_params={'learning_rate': 0.1, 'momentum': 0.9},
            initializer=mx.initializer.Xavier())
    acc = mod.score(test, 'acc')[0][1]
    log("numpy-softmax custom op test acc %.4f" % acc)
    return acc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=10)
    a = ap.parse_args()
    acc = run(epochs=a.epochs)
    print("final custom-op acc %.4f" % acc)


if __name__ == '__main__':
    main()
