#!/usr/bin/env python
"""ZeRO-1 training example — both APIs, virtual mesh out of the box.

The reference's big-model memory lever was update-on-kvstore: push the
optimizer into parameter servers so workers hold no state
(kvstore_dist_server.h applies updates server-side).  The SPMD form is
ZeRO-1: every dp rank owns 1/dp of each optimizer-state array and GSPMD
schedules reduce-scatter(grads) → sharded update → all-gather(params)
inside the one fused step.  docs/design/kvstore.md has the design note.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  JAX_PLATFORMS=cpu python examples/zero1_train.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..'))
from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu()
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import parallel as par  # noqa: E402


def module_api(mesh, x, y, epochs):
    """Symbolic Module path: zero_stage=1 is one constructor argument."""
    data = mx.sym.Variable('data')
    net = mx.sym.FullyConnected(data, num_hidden=64, name='fc1')
    net = mx.sym.Activation(net, act_type='relu')
    net = mx.sym.FullyConnected(net, num_hidden=10, name='fc2')
    net = mx.sym.SoftmaxOutput(net, name='softmax')

    mod = mx.mod.Module(net, mesh=mesh, zero_stage=1)
    it = mx.io.NDArrayIter(data=x, label=y, batch_size=64, shuffle=True)
    mod.fit(it, num_epoch=epochs,
            optimizer='adam', optimizer_params={'learning_rate': 1e-3},
            eval_metric='acc',
            batch_end_callback=mx.callback.Speedometer(64, 10))
    # show a sharded Adam moment: each chip holds 1/dp of it
    name = 'fc1_weight'
    moment = mod._opt_states[name][-1]
    logging.info("%s adam moment: global %s, per-chip shard %s", name,
                 moment.shape,
                 moment._data.addressable_shards[0].data.shape)
    return mod


def gluon_api(mesh, x, y, epochs):
    """Gluon path: place params on the mesh, then Trainer(zero_stage=1)."""
    from mxnet_tpu import gluon, autograd, nd
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(64, activation='relu'))
    net.add(gluon.nn.Dense(10))
    net.initialize(mx.initializer.Xavier())

    xs = nd.array(x)
    ys = nd.array(y)
    net(xs[:1])                              # materialize deferred shapes
    net.collect_params().place(mesh)         # params → mesh (replicated)
    xs._set_data(jax.device_put(xs._data, NamedSharding(mesh, P('dp'))))
    ys._set_data(jax.device_put(ys._data, NamedSharding(mesh, P('dp'))))

    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': 1e-3},
                            mesh=mesh, zero_stage=1)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(epochs):
        with autograd.record():
            loss = loss_fn(net(xs), ys)
        loss.backward()
        trainer.step(xs.shape[0])
        logging.info("epoch %d loss %.4f", epoch,
                     float(loss.mean().asnumpy()))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=2)
    ap.add_argument('--api', choices=['module', 'gluon', 'both'],
                    default='both')
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    mesh = par.make_mesh()  # dp = all visible devices
    dp = par.mesh_shape(mesh)['dp']
    logging.info("mesh: dp=%d", dp)

    rng = np.random.RandomState(0)
    n = 64 * 8
    x = rng.randn(n, 32).astype(np.float32)
    y = rng.randint(0, 10, (n,)).astype(np.float32)

    if args.api in ('module', 'both'):
        module_api(mesh, x, y, args.epochs)
    if args.api in ('gluon', 'both'):
        gluon_api(mesh, x, y, args.epochs)
    logging.info("done")


if __name__ == '__main__':
    main()
