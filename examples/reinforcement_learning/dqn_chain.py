"""DQN on a chain MDP — replay buffer, target network, epsilon-greedy
(reference: example/reinforcement-learning/dqn — the same agent loop:
online Q-network trained on TD targets from a periodically-synced
target network over replayed transitions).

Environment (self-contained, no gym in this image): an N-state chain.
Action 1 moves right, action 0 teleports back to the start with a small
immediate reward; only reaching the far end pays 10.  Greedy play on
the optimal policy walks the whole chain, which epsilon-greedy
exploration must discover past the distractor reward.

Framework surface exercised: two Modules sharing an architecture,
``get_params -> set_params`` for the target sync, gather via ``pick``
for Q(s, a), and a custom TD-loss training loop.

Run:  python examples/reinforcement_learning/dqn_chain.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402


class ChainEnv:
    """N states in a row; right-moves reach the +10 goal, action 0
    pays +0.1 but resets (3.2/episode max) — the exploration trap."""

    def __init__(self, n=8):
        self.n = n
        self.state = 0

    def reset(self):
        self.state = 0
        return self.state

    def step(self, action):
        if action == 1:
            self.state += 1
            if self.state >= self.n - 1:
                return self.state, 10.0, True
            return self.state, 0.0, False
        self.state = 0
        return self.state, 0.1, False


def q_net(n_actions=2):
    data = mx.sym.Variable('data')
    h = mx.sym.FullyConnected(data, num_hidden=32, name='q1')
    h = mx.sym.Activation(h, act_type='relu')
    return mx.sym.FullyConnected(h, num_hidden=n_actions, name='q2')


def make_module(n_states, batch):
    mod = mx.mod.Module(q_net(), context=mx.cpu(), label_names=None)
    mod.bind(data_shapes=[('data', (batch, n_states))],
             label_shapes=None, for_training=True,
             inputs_need_grad=False)
    mod.init_params(mx.initializer.Xavier())
    return mod


def one_hot(idx, n):
    out = np.zeros((len(idx), n), np.float32)
    out[np.arange(len(idx)), idx] = 1.0
    return out


def run(episodes=250, n_states=8, batch=32, gamma=0.95, lr=5e-3,
        sync_every=20, seed=0, log=print):
    rng = np.random.RandomState(seed)
    mx.random.seed(seed)
    env = ChainEnv(n_states)

    online = make_module(n_states, batch)
    online.init_optimizer(optimizer='adam',
                          optimizer_params={'learning_rate': lr})
    target = make_module(n_states, batch)
    target.set_params(*online.get_params())
    # batch-1 policy head so greedy actions never force the batch-32
    # training executor to rebind; synced from the online params each
    # episode (jax-array handle swaps, no compute — the per-forward
    # copy BucketingModule does, at episode granularity)
    policy = mx.mod.Module(q_net(), context=mx.cpu(), label_names=None)
    policy.bind(data_shapes=[('data', (1, n_states))], label_shapes=None,
                for_training=False, shared_module=online)

    replay = []
    eps = 1.0
    returns = []
    for ep in range(episodes):
        policy._exec.copy_params_from(*online.get_params(),
                                      allow_extra_params=True)
        s = env.reset()
        total = 0.0
        for _ in range(4 * n_states):
            if rng.uniform() < eps:
                a = rng.randint(2)
            else:
                policy.forward(mx.io.DataBatch(
                    data=[nd.array(one_hot([s], n_states))]),
                    is_train=False)
                a = int(policy.get_outputs()[0].asnumpy()[0].argmax())
            s2, r, done = env.step(a)
            replay.append((s, a, r, s2, done))
            total += r
            s = s2
            if done:
                break
        returns.append(total)
        eps = max(0.05, eps * 0.97)
        replay = replay[-2000:]

        if len(replay) >= batch:
            idx = rng.choice(len(replay), batch)
            ss, aa, rr, s2s, dd = zip(*[replay[i] for i in idx])
            # TD target from the frozen network
            target.forward(mx.io.DataBatch(
                data=[nd.array(one_hot(s2s, n_states))]), is_train=False)
            q_next = target.get_outputs()[0].asnumpy().max(axis=1)
            y = np.array(rr, np.float32) + gamma * q_next * \
                (1.0 - np.array(dd, np.float32))
            # gradient of the TD error only through Q(s, a)
            online.forward(mx.io.DataBatch(
                data=[nd.array(one_hot(ss, n_states))]), is_train=True)
            q = online.get_outputs()[0]
            q_sa = nd.pick(q, nd.array(np.array(aa, np.float32)), axis=1)
            grad_q = np.zeros((batch, 2), np.float32)
            td = q_sa.asnumpy() - y
            grad_q[np.arange(batch), list(aa)] = td / batch
            online.backward(out_grads=[nd.array(grad_q)])
            online.update()

        if (ep + 1) % sync_every == 0:
            target.set_params(*online.get_params())

    tail = float(np.mean(returns[-20:]))
    log("mean return (last 20 episodes): %.3f" % tail)
    return tail


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--episodes', type=int, default=250)
    a = ap.parse_args()
    tail = run(episodes=a.episodes)
    print("final dqn mean return %.3f" % tail)


if __name__ == '__main__':
    main()
