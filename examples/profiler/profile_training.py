"""Profiling a training loop
(reference: example/profiler/profiler_executor.py — set the profiler
config, bracket the hot loop with profiler state changes, dump a
chrome://tracing JSON).

Same workflow here, two capture layers:
 * ``mx.profiler`` — host-side op/scope events, chrome-trace JSON
   (load it at chrome://tracing or perfetto.dev);
 * on real hardware pass ``--xplane-dir DIR`` (or set
   ``MXNET_PROFILER_XLA_LOGDIR``) to also capture the XLA xplane trace
   (summarize without TensorBoard via
   ``python tools/xplane_summary.py DIR``).

Run:  python examples/profiler/profile_training.py
"""
import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402


def run(iters=12, batch=64, out="profile_training.json",
        xplane_dir=None, log=print):
    rng = np.random.RandomState(0)
    x = rng.randn(512, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, 512).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch, last_batch_handle='discard')

    data = mx.sym.Variable('data')
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                             name='c1')
    net = mx.sym.Activation(net, act_type='relu')
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=10, name='f1')
    net = mx.sym.SoftmaxOutput(net, name='softmax')
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1})

    # warm up OUTSIDE the capture so the trace shows steady-state steps,
    # not the first-step XLA compile (reference profiler_executor.py
    # skipped warmup the same way)
    b0 = next(iter(it))
    mod.forward(b0, is_train=True)
    mod.backward()
    mod.update()

    # mode='all' records eager AND symbolic op events; xla_logdir (or
    # the MXNET_PROFILER_XLA_LOGDIR env) makes set_state('run') also
    # capture the device xplane trace — no manual jax.profiler calls
    mx.profiler.set_config(mode='all', filename=out,
                           xla_logdir=xplane_dir)
    mx.profiler.set_state('run')
    n = 0
    it.reset()
    for bt in it:
        with mx.profiler.scope('train_step'):
            mod.forward(bt, is_train=True)
            mod.backward()
            mod.update()
        n += 1
        if n >= iters:
            break
    mx.profiler.set_state('stop')
    mx.profiler.dump()

    with open(out) as f:
        events = json.load(f)['traceEvents']
    steps = [e for e in events if e.get('name') == 'train_step']
    log("captured %d events (%d train_step scopes) -> %s"
        % (len(events), len(steps), out))
    return len(events), len(steps)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--iters', type=int, default=12)
    ap.add_argument('--out', type=str, default='profile_training.json')
    ap.add_argument('--xplane-dir', type=str, default=None)
    a = ap.parse_args()
    n_events, n_steps = run(iters=a.iters, out=a.out,
                            xplane_dir=a.xplane_dir)
    print("profiler example done: %d events, %d steps"
          % (n_events, n_steps))


if __name__ == '__main__':
    main()
