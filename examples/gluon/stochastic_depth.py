"""Stochastic-depth ResNet as a custom gluon HybridBlock
(reference: example/gluon/... stochastic-depth — residual blocks that
randomly SKIP their conv branch during training, scaling it at test
time; Huang et al. 2016).

The gluon extensibility story: a user-defined HybridBlock whose
hybrid_forward makes a per-forward random keep/skip decision, composed
into a trainable net with ``gluon.Trainer`` + autograd.  The blocks run
EAGERLY (each op jit-cached individually): the keep decision is plain
host-side Python, so the skip path does zero conv work.  Do NOT
hybridize() this net — a whole-graph cache would bake one random
decision into the cached program and silently freeze the depth.

Run:  python examples/gluon/stochastic_depth.py [--epochs 8]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402


class StochasticResidual(gluon.HybridBlock):
    """Residual block kept with probability `p_keep` during training;
    at inference the branch is always on, scaled by p_keep."""

    def __init__(self, channels, p_keep=0.8, rng=None, **kwargs):
        super().__init__(**kwargs)
        self.p_keep = p_keep
        self._rng = rng or np.random.RandomState(0)
        with self.name_scope():
            self.conv1 = gluon.nn.Conv2D(channels, 3, padding=1)
            self.bn1 = gluon.nn.BatchNorm()
            self.conv2 = gluon.nn.Conv2D(channels, 3, padding=1)
            self.bn2 = gluon.nn.BatchNorm()

    def hybrid_forward(self, F, x):
        if autograd.is_training() and self._rng.uniform() >= self.p_keep:
            # skipped: no conv compute at all, and the skipped block's
            # BatchNorm running stats stay untouched
            return F.Activation(x, act_type='relu')
        branch = self.bn2(self.conv2(
            F.Activation(self.bn1(self.conv1(x)), act_type='relu')))
        if autograd.is_training():
            return F.Activation(x + branch, act_type='relu')
        return F.Activation(x + self.p_keep * branch, act_type='relu')


def build_net(p_keep=0.8, seed=0):
    rng = np.random.RandomState(seed)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Conv2D(16, 3, padding=1),
                gluon.nn.BatchNorm(),
                gluon.nn.Activation('relu'),
                StochasticResidual(16, p_keep, rng),
                StochasticResidual(16, p_keep, rng),
                gluon.nn.MaxPool2D(2),
                StochasticResidual(16, p_keep, rng),
                gluon.nn.GlobalAvgPool2D(),
                gluon.nn.Dense(10))
    return net


def run(epochs=8, batch=100, p_keep=0.8, seed=0, log=print):
    from sklearn.datasets import load_digits
    d = load_digits()
    x = (d.images / 16.0).astype(np.float32)[:, None, :, :]
    y = d.target.astype(np.float32)
    n = 1500

    mx.random.seed(seed)
    np.random.seed(seed)
    net = build_net(p_keep, seed)
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(epochs):
        perm = np.random.permutation(n)
        total = 0.0
        for i in range(n // batch):
            sl = perm[i * batch:(i + 1) * batch]
            bx, by = nd.array(x[sl]), nd.array(y[sl])
            with autograd.record():
                out = net(bx)
                loss = loss_fn(out, by)
            loss.backward()
            trainer.step(batch)
            total += float(loss.mean().asscalar())
        log("epoch %d train loss %.4f" % (epoch, total / (n // batch)))

    # eval: deterministic scaled-branch path
    pred = net(nd.array(x[n:])).asnumpy().argmax(axis=1)
    acc = float((pred == y[n:]).mean())
    log("stochastic-depth test acc %.4f" % acc)
    return acc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=8)
    ap.add_argument('--p-keep', type=float, default=0.8)
    a = ap.parse_args()
    acc = run(epochs=a.epochs, p_keep=a.p_keep)
    print("final stochastic-depth acc %.4f" % acc)


if __name__ == '__main__':
    main()
