#!/usr/bin/env python
"""Train SSD (reference: example/ssd/train.py — BASELINE config 5).

--data-train points at a detection .rec (ImageDetRecordIter format, e.g.
produced by mxnet_tpu.image.detection.pack_det_dataset or im2rec det
packing).  With --synthetic a toy squares dataset is generated and the
small ssd_toy network is used, so the script runs end-to-end in
no-egress CI; otherwise the VGG16-reduced SSD-300 trains.
"""
import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..'))
import common  # noqa: E402
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402
from mxnet_tpu.image.detection import pack_det_dataset  # noqa: E402


def synthetic_rec(path, n=64, size=64, seed=0):
    rng = np.random.RandomState(seed)
    images, classes, boxes = [], [], []
    for _ in range(n):
        im = rng.randint(0, 60, (size, size, 3)).astype(np.uint8)
        s = rng.randint(size // 4, size // 2)
        y0 = rng.randint(0, size - s)
        x0 = rng.randint(0, size - s)
        im[y0:y0 + s, x0:x0 + s] = 255
        images.append(im)
        classes.append([0.0])
        boxes.append([[x0 / size, y0 / size, (x0 + s) / size,
                       (y0 + s) / size]])
    pack_det_dataset(path, images, classes, boxes)


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    common.add_fit_args(parser)
    parser.add_argument('--data-train', type=str, default=None)
    parser.add_argument('--synthetic', action='store_true')
    parser.add_argument('--num-classes', type=int, default=20)
    parser.add_argument('--data-shape', type=int, default=300)
    parser.set_defaults(num_epochs=3, batch_size=8, lr=0.004,
                        wd=5e-4)
    args = parser.parse_args()

    if args.synthetic or not args.data_train:
        tmp = os.path.join(tempfile.gettempdir(), 'ssd_toy.rec')
        synthetic_rec(tmp)
        args.data_train = tmp
        args.num_classes = 1
        args.data_shape = 64
        net = models.ssd_toy(num_classes=1, mode='train')
    else:
        net = models.ssd_vgg16(num_classes=args.num_classes, mode='train')

    shape = (3, args.data_shape, args.data_shape)
    train = mx.io.ImageDetRecordIter(
        args.data_train, data_shape=shape, batch_size=args.batch_size,
        max_objects=16, rand_mirror=True, shuffle=True)

    import logging
    logging.basicConfig(level=logging.INFO)
    mod = mx.mod.Module(net, context=mx.tpu(0), data_names=('data',),
                        label_names=('label',))
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.initializer.Xavier())
    # reference example/ssd/train.py defaults: lr 0.004, wd 5e-4,
    # gradient clipping for early-training stability
    mod.init_optimizer(optimizer=args.optimizer,
                       optimizer_params={'learning_rate': args.lr,
                                         'momentum': args.mom,
                                         'wd': args.wd,
                                         'clip_gradient': 4.0})
    for epoch in range(args.num_epochs):
        train.reset()
        tot, n = 0.0, 0
        for batch in train:
            mod.forward(batch, is_train=True)
            tot += float(mod.get_outputs()[1].asnumpy().sum())
            n += 1
            mod.backward()
            mod.update()
        logging.info('Epoch[%d] loc_loss=%.4f', epoch, tot / max(n, 1))
