"""Matrix-factorization recommender
(reference: example/recommenders/matrix_fact.py / demo1-MF.ipynb — the
classic MovieLens MF: user & item embeddings, dot-product score,
trained with the legacy FeedForward estimator).

Same shape here: two Embedding towers composed symbolically, an
elementwise-dot score head, LinearRegressionOutput loss, trained through
``mx.model.FeedForward`` (the estimator the reference demo uses) over a
multi-input NDArrayIter.  Data is a synthetic MovieLens stand-in (zero
egress): ratings generated from planted low-rank factors + noise, so
recoverable structure exists and RMSE has a meaningful floor.

Run:  python examples/recommender/matrix_fact.py [--epochs 10]
"""
import argparse
import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402


def plain_net(max_user, max_item, hidden=16):
    """reference matrix_fact.py plain_net: embed users & items, dot."""
    user = mx.sym.Variable('user')
    item = mx.sym.Variable('item')
    score = mx.sym.Variable('score')
    user = mx.sym.Embedding(user, input_dim=max_user, output_dim=hidden,
                            name='user_embed')
    item = mx.sym.Embedding(item, input_dim=max_item, output_dim=hidden,
                            name='item_embed')
    pred = user * item
    pred = mx.sym.sum(pred, axis=1)
    pred = mx.sym.Flatten(pred)
    return mx.sym.LinearRegressionOutput(data=pred, label=score,
                                         name='lro')


def make_ratings(num_users=200, num_items=100, num_ratings=8000, rank=4,
                 noise=0.1, seed=0):
    rng = np.random.RandomState(seed)
    U = rng.randn(num_users, rank).astype(np.float32) / math.sqrt(rank)
    V = rng.randn(num_items, rank).astype(np.float32) / math.sqrt(rank)
    u = rng.randint(0, num_users, num_ratings)
    i = rng.randint(0, num_items, num_ratings)
    r = (U[u] * V[i]).sum(axis=1) + noise * rng.randn(num_ratings)
    return (u.astype(np.float32), i.astype(np.float32),
            r.astype(np.float32))


def rmse_metric():
    def rmse(label, pred):
        pred = pred.reshape(-1)
        return float(np.sqrt(((label - pred) ** 2).mean()))
    return mx.metric.np(rmse, name='rmse')


def train(epochs=30, batch=256, hidden=8, lr=0.02, seed=0, log=print):
    num_users, num_items = 200, 100
    u, i, r = make_ratings(num_users, num_items, seed=seed)
    n_train = int(0.9 * len(r))
    train_it = mx.io.NDArrayIter(
        {'user': u[:n_train], 'item': i[:n_train]},
        {'score': r[:n_train]}, batch_size=batch, shuffle=True,
        last_batch_handle='discard')
    val_it = mx.io.NDArrayIter(
        {'user': u[n_train:], 'item': i[n_train:]},
        {'score': r[n_train:]}, batch_size=batch)

    mx.random.seed(seed)
    np.random.seed(seed)
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter('ignore', DeprecationWarning)
        # init at the data's scale: the score is a dot of TWO embeddings,
        # so tiny init (0.05^2 per term) starts the model ~10x below the
        # rating magnitudes and sgd crawls; Normal(0.3) + adam converges
        # to the noise floor in ~30 epochs
        model = mx.model.FeedForward(
            plain_net(num_users, num_items, hidden), ctx=mx.cpu(),
            num_epoch=epochs, optimizer='adam', learning_rate=lr,
            initializer=mx.initializer.Normal(0.3))
    model.fit(train_it, eval_data=val_it, eval_metric=rmse_metric())
    val_rmse = model.score(val_it, rmse_metric())
    log("validation rmse %.4f" % val_rmse)
    return model, val_rmse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=30)
    ap.add_argument('--batch', type=int, default=256)
    ap.add_argument('--hidden', type=int, default=8)
    a = ap.parse_args()
    _, val_rmse = train(epochs=a.epochs, batch=a.batch, hidden=a.hidden)
    print("final rmse %.4f" % val_rmse)


if __name__ == '__main__':
    main()
