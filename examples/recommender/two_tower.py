"""Two-tower embedding retrieval on the row-sparse kvstore wire.

The canonical sparse-PS workload (reference: example/recommenders +
the row_sparse embedding path, src/kvstore/kvstore_dist_server.h
DataHandleRowSparse): a user tower and an item tower, each a single
``sparse_grad=True`` Embedding, trained on a synthetic clickstream.
Each step touches only the batch's rows, so under ``--kvstore
dist_async`` the gluon Trainer's one-list-push step rides the
row-sparse wire — only touched rows move, striped across however many
servers ``MXT_SERVER_URIS`` names.

After training the item tower doubles as a retrieval head: serving
scores are ``user_embed @ item_table.T``, which is exactly a
``FullyConnected(no_bias)`` whose weight IS the item table — so the
live table serves top-k through :class:`ServingReplica` with the
normal bucketed predict path, and a weight refresh is a data swap
(zero recompiles).

Run:  python examples/recommender/two_tower.py [--epochs 10] [--serve]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402


def make_clickstream(num_users=64, num_items=256, events=4096, rank=4,
                     pool=16, seed=0):
    """Synthetic clickstream (zero egress): planted low-rank affinity,
    positives drawn from each user's top-``pool`` items, negatives
    uniform.  Returns (user, item, label) plus the planted preference
    pools the retrieval metric scores against."""
    rng = np.random.RandomState(seed)
    U = rng.randn(num_users, rank).astype(np.float32)
    V = rng.randn(num_items, rank).astype(np.float32)
    prefs = np.argsort(-(U @ V.T), axis=1)[:, :pool]   # per-user pool
    users = rng.randint(0, num_users, events)
    picks = rng.randint(0, pool, events)
    pos = prefs[users, picks]
    neg = rng.randint(0, num_items, events)
    u = np.concatenate([users, users])
    i = np.concatenate([pos, neg])
    y = np.concatenate([np.ones(events, np.float32),
                        np.zeros(events, np.float32)])
    perm = rng.permutation(len(y))
    return (u[perm].astype(np.float32), i[perm].astype(np.float32),
            y[perm], prefs)


def build_towers(num_users, num_items, dim, seed=0):
    """The two towers; prefixes pin the param names ('user_embed_weight',
    'item_scores_weight') to the SERVING symbol's, so a replica
    refreshes straight from the training kvstore by name."""
    mx.random.seed(seed)
    user_tower = gluon.nn.Embedding(num_users, dim, sparse_grad=True,
                                    prefix='user_embed_')
    item_tower = gluon.nn.Embedding(num_items, dim, sparse_grad=True,
                                    prefix='item_scores_')
    init = mx.initializer.Normal(0.3)
    user_tower.initialize(init)
    item_tower.initialize(init)
    return user_tower, item_tower


def train(user_tower, item_tower, stream, epochs=10, batch=64, lr=0.5,
          kvstore='device', log=print):
    """SGD over dot-product click regression.  Every grad is a
    RowSparseNDArray (only the batch's rows), so the dist_async step —
    one list push, one batched pull — moves O(touched rows) bytes."""
    u, i, y, _prefs = stream
    params = (list(user_tower.collect_params().values())
              + list(item_tower.collect_params().values()))
    trainer = gluon.Trainer(params, 'sgd', {'learning_rate': lr},
                            kvstore=kvstore)
    n = len(y)
    for epoch in range(epochs):
        total = 0.0
        for lo in range(0, n - batch + 1, batch):
            uids = nd.array(u[lo:lo + batch])
            iids = nd.array(i[lo:lo + batch])
            label = nd.array(y[lo:lo + batch])
            with autograd.record():
                ue = user_tower(uids)
                ve = item_tower(iids)
                score = mx.nd.sum(ue * ve, axis=1)
                loss = mx.nd.sum((score - label) ** 2)
            loss.backward()
            trainer.step(batch)
            total += float(loss.asnumpy())
        log("epoch %d click mse %.4f" % (epoch, total / n))
    return trainer


def hit_rate(user_tower, item_tower, prefs, k=10):
    """Retrieval metric: fraction of users whose top-k retrieved items
    intersect their planted preference pool."""
    ut = user_tower.weight.data().asnumpy()
    it = item_tower.weight.data().asnumpy()
    scores = ut @ it.T
    topk = np.argsort(-scores, axis=1)[:, :k]
    hits = [len(set(topk[r]) & set(prefs[r])) > 0
            for r in range(ut.shape[0])]
    return float(np.mean(hits))


def serving_symbol(num_users, num_items, dim):
    """user ids -> user embedding -> scores over EVERY item: the
    FullyConnected weight is the item table itself."""
    user = mx.sym.Variable('user')
    emb = mx.sym.Embedding(user, input_dim=num_users, output_dim=dim,
                           name='user_embed')
    return mx.sym.FullyConnected(emb, num_hidden=num_items, no_bias=True,
                                 name='item_scores')


def serve_topk(user_tower, item_tower, num_users, num_items, dim, k=10,
               param_servers=None):
    """Stand up a ServingReplica on the trained tables and return
    (replica, client, topk) where topk(ids) -> (n, k) item ids."""
    from mxnet_tpu.serving import ServingClient, ServingReplica
    params = {'user_embed_weight': user_tower.weight.data(),
              'item_scores_weight': item_tower.weight.data()}
    rep = ServingReplica(
        serving_symbol(num_users, num_items, dim), {'user': ()}, params,
        buckets=[1, 4, 16], max_wait_s=0.0, param_servers=param_servers)
    rep.start_background()
    cli = ServingClient(f"127.0.0.1:{rep.port}")

    def topk(ids):
        scores = cli.predict(np.asarray(ids, np.float32),
                             name='user')[0]
        return np.argsort(-scores, axis=1)[:, :k]

    return rep, cli, topk


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=10)
    ap.add_argument('--batch', type=int, default=64)
    ap.add_argument('--dim', type=int, default=8)
    ap.add_argument('--users', type=int, default=64)
    ap.add_argument('--items', type=int, default=256)
    ap.add_argument('--lr', type=float, default=0.5)
    ap.add_argument('--kvstore', default='device',
                    help="'device' (local) or 'dist_async' "
                         "(needs MXT_SERVER_URIS)")
    ap.add_argument('--serve', action='store_true',
                    help='stand up a ServingReplica and query top-k')
    a = ap.parse_args()
    stream = make_clickstream(a.users, a.items)
    user_tower, item_tower = build_towers(a.users, a.items, a.dim)
    train(user_tower, item_tower, stream, epochs=a.epochs, batch=a.batch,
          lr=a.lr, kvstore=a.kvstore)
    hr = hit_rate(user_tower, item_tower, stream[3])
    print("final hit@10 %.3f" % hr)
    if a.serve:
        rep, cli, topk = serve_topk(user_tower, item_tower, a.users,
                                    a.items, a.dim)
        try:
            got = topk(np.arange(min(4, a.users)))
            hits = [len(set(got[r]) & set(stream[3][r])) > 0
                    for r in range(got.shape[0])]
            print("served top-k for %d users, %d hit their pool"
                  % (got.shape[0], sum(hits)))
        finally:
            cli.close()
            rep.stop()
        print("serving done")


if __name__ == '__main__':
    main()
