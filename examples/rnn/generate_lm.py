#!/usr/bin/env python
"""Train a small transformer LM, then generate with a KV cache.

Demonstrates the inference path the reference lacks a modern analog for:
``models.transformer_decode_step`` shares parameter names with
``models.transformer_lm``, so trained weights load directly into a
single-token decode graph whose rolled KV cache rides Module
``state_names`` (set_states/get_states) — each step is one jitted
program with static shapes.

  python examples/rnn/generate_lm.py --synthetic --num-epochs 25
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..', '..'))
from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu()
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402


def synthetic_corpus(n, seq_len, vocab, seed=0):
    rs = np.random.RandomState(seed)
    first = rs.randint(0, vocab, (n, 1))
    seq = (first + np.arange(seq_len + 1)) % vocab
    return seq[:, :seq_len].astype('float32'), seq[:, 1:].astype('float32')


if __name__ == '__main__':
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument('--vocab', type=int, default=50)
    ap.add_argument('--seq-len', type=int, default=16)
    ap.add_argument('--num-layers', type=int, default=2)
    ap.add_argument('--d-model', type=int, default=64)
    ap.add_argument('--num-heads', type=int, default=4)
    ap.add_argument('--num-kv-heads', type=int, default=2)
    ap.add_argument('--num-epochs', type=int, default=25)
    ap.add_argument('--batch-size', type=int, default=32)
    ap.add_argument('--gen-len', type=int, default=12)
    ap.add_argument('--beam', type=int, default=0,
                    help='beam size (0 = greedy argmax)')
    ap.add_argument('--synthetic', action='store_true')
    args = ap.parse_args()

    if args.gen_len > args.seq_len:
        # gen_len steps consume positions 0..gen_len-1, which must fit
        # the trained positional embedding (clamping would silently
        # degrade generations — see transformer_decode_step docs)
        raise SystemExit(
            f"--gen-len {args.gen_len} must not exceed --seq-len "
            f"{args.seq_len}")
    kw = dict(num_layers=args.num_layers, d_model=args.d_model,
              num_heads=args.num_heads, num_kv_heads=args.num_kv_heads)
    net = models.transformer_lm(args.vocab, args.seq_len, **kw)
    x, y = synthetic_corpus(512, args.seq_len, args.vocab)
    it = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True)
    mod = mx.mod.Module(net, context=mx.tpu(0), data_names=('data',),
                        label_names=('softmax_label',))
    mod.fit(it, num_epoch=args.num_epochs, optimizer='adam',
            optimizer_params={'learning_rate': 5e-3},
            initializer=mx.initializer.Xavier(),
            eval_metric=mx.metric.Perplexity(ignore_label=None))
    arg_params, aux_params = mod.get_params()

    prompts = (np.array([3, 7, 11, 20]) % args.vocab).astype('float32')
    B = len(prompts) * max(args.beam, 1)
    dec = models.transformer_decode_step(args.vocab, args.seq_len, B, **kw)
    state_names = []
    for i in range(args.num_layers):
        state_names += [f'layer{i}_k_cache', f'layer{i}_v_cache']
    state_names.append('cur_pos')
    dmod = mx.mod.Module(dec, context=mx.tpu(0), data_names=('data',),
                         label_names=None, state_names=state_names)
    dmod.bind(data_shapes=[('data', (B,))], for_training=False)
    dmod.init_params(arg_params=arg_params, aux_params=aux_params)
    dmod.set_states(value=0)

    # beam_size=1 IS greedy (pinned by
    # test_beam_search_beam1_equals_greedy) — one decode path, no drift
    seqs, scores = models.beam_search(dmod, prompts, max(args.beam, 1),
                                      args.gen_len)
    label = 'beam' if args.beam > 1 else 'greedy'
    for b in range(len(prompts)):
        print('generated (%s, score %.3f):' % (label, scores[b, 0]),
              ' '.join(str(int(t)) for t in seqs[b, 0]))
    print('generation done')
