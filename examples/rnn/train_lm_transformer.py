#!/usr/bin/env python
"""Train the transformer language model (the long-context flagship;
flash-attention Pallas kernels fwd+bwd, optional MoE experts).

With --synthetic (or missing --data) a Markov corpus is generated so the
script runs in no-egress CI; --dtype bfloat16 enables mixed precision;
--moe-experts N switches the FFN to expert-parallel-ready MoE.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..'))
import common  # noqa: E402
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402


def synthetic_tokens(n=512, seq=64, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    toks = np.zeros((n, seq + 1), np.float32)
    toks[:, 0] = rng.randint(1, vocab, n)
    for t in range(seq):
        nxt = (toks[:, t] * 3 + 1) % (vocab - 1) + 1
        noise = rng.rand(n) < 0.1
        nxt[noise] = rng.randint(1, vocab, noise.sum())
        toks[:, t + 1] = nxt
    return toks, vocab


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    common.add_fit_args(parser)
    parser.add_argument('--synthetic', action='store_true')
    parser.add_argument('--seq-len', type=int, default=64)
    parser.add_argument('--num-tf-layers', type=int, default=2)
    parser.add_argument('--d-model', type=int, default=128)
    parser.add_argument('--num-heads', type=int, default=4)
    parser.add_argument('--moe-experts', type=int, default=0)
    parser.add_argument('--pos-type', choices=['learned', 'rope'],
                        default='learned')
    parser.add_argument('--ffn-type', choices=['gelu', 'swiglu'],
                        default='gelu')
    parser.set_defaults(num_epochs=3, batch_size=32, lr=3e-3,
                        optimizer='adam')
    args = parser.parse_args()

    toks, vocab = synthetic_tokens(seq=args.seq_len)
    it = mx.io.NDArrayIter({'data': toks[:, :-1]},
                           {'softmax_label': toks[:, 1:]},
                           batch_size=args.batch_size, shuffle=True)
    net = models.transformer_lm(vocab, args.seq_len,
                                num_layers=args.num_tf_layers,
                                d_model=args.d_model,
                                num_heads=args.num_heads,
                                moe_experts=args.moe_experts,
                                pos_type=args.pos_type,
                                ffn_type=args.ffn_type)
    import logging
    logging.basicConfig(level=logging.INFO)
    compute_dtype = None
    if args.dtype in ('bfloat16', 'float16'):
        import jax.numpy as jnp
        compute_dtype = jnp.dtype(args.dtype)
    mod = mx.mod.Module(net, context=mx.tpu(0),
                        compute_dtype=compute_dtype)
    mod.fit(it, num_epoch=args.num_epochs, optimizer=args.optimizer,
            optimizer_params={'learning_rate': args.lr, 'wd': args.wd},
            initializer=mx.initializer.Xavier(),
            eval_metric=mx.metric.Perplexity(ignore_label=None),
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches))
