"""Sorting short digit sequences with a bidirectional LSTM
(reference: example/bi-lstm-sort/lstm_sort.py — the classic seq->seq
toy proving bidirectional context: each output position must know the
WHOLE input to emit the sorted element).

Where the reference hand-unrolled forward and backward LSTM stacks and
spliced them per step (lstm.py bi_lstm_unroll over SliceChannel), here
``rnn.BidirectionalCell`` composes two LSTMCells and ``unroll`` builds
the same computation — then one Dense head per step predicts the sorted
token.  Trained with Module on synthetic data (the reference generated
its sequences synthetically too).

Run:  python examples/rnn/bi_lstm_sort.py [--epochs 15]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import rnn  # noqa: E402


def sort_symbol(seq_len, vocab, num_hidden=64, num_embed=32):
    data = mx.sym.Variable('data')
    label = mx.sym.Variable('softmax_label')
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_embed,
                             name='embed')
    cell = rnn.BidirectionalCell(
        rnn.LSTMCell(num_hidden, prefix='l_'),
        rnn.LSTMCell(num_hidden, prefix='r_'))
    outputs, _ = cell.unroll(seq_len, inputs=embed, merge_outputs=True,
                             layout='NTC')
    # per-step classification over the vocabulary
    pred = mx.sym.Reshape(outputs, shape=(-1, 2 * num_hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name='cls')
    label_flat = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, label_flat, name='softmax')


def make_data(num=2000, seq_len=6, vocab=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(0, vocab, (num, seq_len))
    y = np.sort(x, axis=1)
    return x.astype(np.float32), y.astype(np.float32)


def train(epochs=15, batch=64, seq_len=6, vocab=10, seed=0, log=print):
    x, y = make_data(seq_len=seq_len, vocab=vocab, seed=seed)
    n = int(0.9 * len(x))
    train_it = mx.io.NDArrayIter(x[:n], y[:n], batch, shuffle=True,
                                 last_batch_handle='discard')
    val_it = mx.io.NDArrayIter(x[n:], y[n:], batch,
                               last_batch_handle='discard')
    mx.random.seed(seed)
    mod = mx.mod.Module(sort_symbol(seq_len, vocab), context=mx.cpu())
    mod.bind(data_shapes=train_it.provide_data,
             label_shapes=train_it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer='adam',
                       optimizer_params={'learning_rate': 5e-3})

    acc = None
    for epoch in range(epochs):
        train_it.reset()
        for b in train_it:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
        # per-token accuracy on held-out sequences
        val_it.reset()
        correct = total = 0
        for b in val_it:
            mod.forward(b, is_train=False)
            pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
            lab = b.label[0].asnumpy().reshape(-1)
            correct += int((pred == lab).sum())
            total += len(lab)
        acc = correct / total
        log("epoch %d val per-token acc %.4f" % (epoch, acc))
    return mod, acc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=15)
    ap.add_argument('--batch', type=int, default=64)
    ap.add_argument('--seq-len', type=int, default=6)
    a = ap.parse_args()
    _, acc = train(epochs=a.epochs, batch=a.batch, seq_len=a.seq_len)
    print("final sort acc %.4f" % acc)


if __name__ == '__main__':
    main()
