"""Encoder-decoder seq2seq with teacher forcing
(reference: the rnn example family's encoder-decoder scripts — encode a
source sequence into LSTM states, hand those states to a decoder as its
``begin_state``, teacher-force the target during training, decode
greedily at inference).

Task: output the INPUT SEQUENCE REVERSED — position i of the output
depends on position L-1-i of the input, so nothing short of real
encoder-state transport solves it.

Framework surface: two LSTM stacks composed in ONE symbol with
``unroll(begin_state=encoder_states)``, per-step softmax heads, Module
training, and an iterative greedy decode that re-feeds the generated
prefix.

Run:  python examples/rnn/seq2seq_reverse.py [--epochs 20]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import rnn  # noqa: E402

GO = 1  # decoder start token; PAD=0; real symbols start at 2


def seq2seq_symbol(seq_len, vocab, num_hidden=64, num_embed=32):
    src = mx.sym.Variable('data')           # (N, T) source tokens
    tgt_in = mx.sym.Variable('tgt_in')      # (N, T) <go> + target[:-1]
    label = mx.sym.Variable('softmax_label')

    embed = mx.sym.Embedding(src, input_dim=vocab, output_dim=num_embed,
                             name='src_embed')
    enc = rnn.LSTMCell(num_hidden, prefix='enc_')
    _, enc_states = enc.unroll(seq_len, inputs=embed, layout='NTC',
                               merge_outputs=True)

    dembed = mx.sym.Embedding(tgt_in, input_dim=vocab,
                              output_dim=num_embed, name='tgt_embed')
    dec = rnn.LSTMCell(num_hidden, prefix='dec_')
    # the seq2seq move: decoder starts FROM the encoder's final states
    dec_out, _ = dec.unroll(seq_len, inputs=dembed,
                            begin_state=enc_states, layout='NTC',
                            merge_outputs=True)
    pred = mx.sym.Reshape(dec_out, shape=(-1, num_hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name='cls')
    return mx.sym.SoftmaxOutput(pred, mx.sym.Reshape(label, shape=(-1,)),
                                name='softmax')


def make_data(num=3000, seq_len=6, vocab=12, seed=0):
    rng = np.random.RandomState(seed)
    src = rng.randint(2, vocab, (num, seq_len))
    tgt = src[:, ::-1].copy()
    tgt_in = np.concatenate([np.full((num, 1), GO), tgt[:, :-1]], axis=1)
    return (src.astype(np.float32), tgt_in.astype(np.float32),
            tgt.astype(np.float32))


def train(epochs=20, batch=64, seq_len=6, vocab=12, seed=0, log=print):
    src, tgt_in, tgt = make_data(seq_len=seq_len, vocab=vocab, seed=seed)
    n = int(0.9 * len(src))
    np.random.seed(seed)
    mx.random.seed(seed)
    train_it = mx.io.NDArrayIter(
        {'data': src[:n], 'tgt_in': tgt_in[:n]}, {'softmax_label': tgt[:n]},
        batch, shuffle=True, last_batch_handle='discard')
    mod = mx.mod.Module(seq2seq_symbol(seq_len, vocab),
                        data_names=('data', 'tgt_in'), context=mx.cpu())
    mod.bind(data_shapes=train_it.provide_data,
             label_shapes=train_it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer='adam',
                       optimizer_params={'learning_rate': 5e-3})
    for epoch in range(epochs):
        train_it.reset()
        for b in train_it:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()

    # greedy decode on held-out sources: re-unroll with the generated
    # prefix in the teacher slot (PAD for the not-yet-generated tail)
    vsrc, vtgt = src[n:n + batch], tgt[n:n + batch]
    dec_in = np.zeros_like(vsrc)
    dec_in[:, 0] = GO
    steps = []
    for t in range(seq_len):
        mod.forward(mx.io.DataBatch(
            data=[mx.nd.array(vsrc), mx.nd.array(dec_in)],
            label=[mx.nd.array(np.zeros_like(vsrc))]), is_train=False)
        prob = mod.get_outputs()[0].asnumpy().reshape(
            batch, seq_len, vocab)
        step_tok = prob[:, t].argmax(axis=1)
        steps.append(step_tok)
        if t + 1 < seq_len:
            dec_in[:, t + 1] = step_tok
    generated = np.stack(steps, axis=1)
    token_acc = float((generated == vtgt).mean())
    seq_acc = float((generated == vtgt).all(axis=1).mean())
    log("greedy decode: token acc %.4f, full-sequence acc %.4f"
        % (token_acc, seq_acc))
    return token_acc, seq_acc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=20)
    a = ap.parse_args()
    tok, seq = train(epochs=a.epochs)
    print("final seq2seq token acc %.4f seq acc %.4f" % (tok, seq))


if __name__ == '__main__':
    main()
