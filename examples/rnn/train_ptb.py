#!/usr/bin/env python
"""Bucketing LSTM language model (reference: example/rnn/lstm_bucketing.py
— BASELINE config 4, PTB).

Reads PTB-style text (--data path, one sentence per line, space-separated
tokens); with --synthetic (or when the file is missing) a generated
corpus with learnable bigram structure is used so the script runs in
no-egress CI.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..'))
import common  # noqa: E402
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import symbol as sym  # noqa: E402


def tokenize(path, vocab=None):
    """reference: lstm_bucketing.py tokenize_text."""
    sentences = []
    vocab = vocab or {'<pad>': 0, '<unk>': 1}
    with open(path) as f:
        for line in f:
            toks = line.split()
            if not toks:
                continue
            ids = []
            for t in toks:
                if t not in vocab:
                    vocab[t] = len(vocab)
                ids.append(vocab[t])
            sentences.append(ids)
    return sentences, vocab


def synthetic_corpus(n=600, vocab_size=60, seed=0):
    """Markov-chain corpus: next token = (token * 3 + 1) % V with noise."""
    rng = np.random.RandomState(seed)
    sentences = []
    for _ in range(n):
        ln = rng.randint(8, 25)
        s = [int(rng.randint(2, vocab_size))]
        for _ in range(ln - 1):
            if rng.rand() < 0.85:
                s.append((s[-1] * 3 + 1) % (vocab_size - 2) + 2)
            else:
                s.append(int(rng.randint(2, vocab_size)))
        sentences.append(s)
    return sentences, vocab_size


def sym_gen_factory(num_hidden, num_layers, num_embed, vocab_size):
    """reference: lstm_bucketing.py sym_gen — per-bucket symbol builder."""
    def sym_gen(seq_len):
        data = sym.Variable('data')
        label = sym.Variable('softmax_label')
        embed = sym.Embedding(data, input_dim=vocab_size,
                              output_dim=num_embed, name='embed')
        stack = mx.rnn.SequentialRNNCell()
        for i in range(num_layers):
            stack.add(mx.rnn.LSTMCell(num_hidden=num_hidden,
                                      prefix='lstm_l%d_' % i))
        outputs, _ = stack.unroll(seq_len, inputs=embed,
                                  merge_outputs=True)
        pred = sym.Reshape(outputs, shape=(-1, num_hidden))
        pred = sym.FullyConnected(pred, num_hidden=vocab_size, name='pred')
        lab = sym.Reshape(label, shape=(-1,))
        out = sym.SoftmaxOutput(data=pred, label=lab, name='softmax')
        return out, ('data',), ('softmax_label',)
    return sym_gen


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    common.add_fit_args(parser)
    parser.add_argument('--data', type=str, default='data/ptb.train.txt')
    parser.add_argument('--synthetic', action='store_true')
    parser.add_argument('--num-hidden', type=int, default=200)
    parser.add_argument('--num-embed', type=int, default=200)
    parser.add_argument('--num-lstm-layers', type=int, default=2)
    parser.add_argument('--buckets', type=str, default='10,20,30,40')
    parser.set_defaults(num_epochs=5, batch_size=32, lr=0.1,
                        optimizer='sgd')
    args = parser.parse_args()

    if not args.synthetic and os.path.exists(args.data):
        sentences, vocab = tokenize(args.data)
        vocab_size = len(vocab)
    else:
        sentences, vocab_size = synthetic_corpus()
    buckets = [int(b) for b in args.buckets.split(',')]
    train = mx.rnn.BucketSentenceIter(sentences, args.batch_size,
                                      buckets=buckets)

    sym_gen = sym_gen_factory(args.num_hidden, args.num_lstm_layers,
                              args.num_embed, vocab_size)
    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=train.default_bucket_key,
                                 context=mx.tpu(0))
    import logging
    logging.basicConfig(level=logging.INFO)
    mod.fit(train, num_epoch=args.num_epochs,
            eval_metric=mx.metric.Perplexity(ignore_label=None),
            optimizer=args.optimizer,
            optimizer_params={'learning_rate': args.lr,
                              'momentum': args.mom, 'wd': args.wd},
            initializer=mx.initializer.Xavier(),
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches))
