"""Stacked autoencoder with greedy layerwise pretraining
(reference: example/autoencoder/{autoencoder,mnist_sae}.py — pretrain
each encoder/decoder pair on the previous layer's codes, then finetune
the whole reconstruction stack end-to-end).

The workflow the reference demonstrated: building symbols per stage,
transferring trained weights between Modules by parameter NAME
(get_params -> set_params with allow_missing), and a two-phase training
schedule.  Data: sklearn digits (64-d), dims 64-32-16.

Run:  python examples/autoencoder/stacked_ae.py [--pretrain-epochs 8]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402


def pair_sym(i, n_in, n_hidden):
    data = mx.sym.Variable('data')
    label = mx.sym.Variable('recon_label')
    enc = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=n_hidden,
                              name='enc%d' % i), act_type='relu')
    dec = mx.sym.FullyConnected(enc, num_hidden=n_in, name='dec%d' % i)
    return mx.sym.LinearRegressionOutput(dec, label, name='recon')


def full_sym(dims):
    """encoder chain then mirrored decoder chain, names matching the
    stage symbols so pretrained weights transfer by name."""
    data = mx.sym.Variable('data')
    label = mx.sym.Variable('recon_label')
    h = data
    for i, d in enumerate(dims[1:]):
        h = mx.sym.Activation(
            mx.sym.FullyConnected(h, num_hidden=d, name='enc%d' % i),
            act_type='relu')
    for i in reversed(range(len(dims) - 1)):
        h = mx.sym.FullyConnected(h, num_hidden=dims[i],
                                  name='dec%d' % i)
        if i > 0:
            h = mx.sym.Activation(h, act_type='relu')
    return mx.sym.LinearRegressionOutput(h, label, name='recon')


def _fit(sym, x, y_label, epochs, lr, batch=100, params=None, seed=0):
    it = mx.io.NDArrayIter(x, y_label, batch, shuffle=True,
                           last_batch_handle='discard',
                           label_name='recon_label')
    mx.random.seed(seed)
    mod = mx.mod.Module(sym, context=mx.cpu(),
                        label_names=('recon_label',))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    if params:
        mod.set_params(*params, allow_missing=True, allow_extra=True)
    mod.init_optimizer(optimizer='adam',
                       optimizer_params={'learning_rate': lr})
    for _ in range(epochs):
        it.reset()
        for b in it:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
    return mod


def _encode(x, args, i):
    w = args['enc%d_weight' % i].asnumpy()
    b = args['enc%d_bias' % i].asnumpy()
    return np.maximum(x @ w.T + b, 0.0)


def run(pretrain_epochs=8, finetune_epochs=8, dims=(64, 32, 16),
        seed=0, log=print):
    from sklearn.datasets import load_digits
    d = load_digits()
    x = (d.images.reshape(len(d.images), -1) / 16.0).astype(np.float32)
    # NDArrayIter(shuffle=True) draws from GLOBAL np.random at
    # construction — seed it here or no later seeding makes runs
    # reproducible
    np.random.seed(seed)

    # greedy layerwise pretraining: stage i reconstructs stage i-1 codes
    arg_all, aux_all = {}, {}
    cur = x
    for i in range(len(dims) - 1):
        mod = _fit(pair_sym(i, dims[i], dims[i + 1]), cur, cur,
                   pretrain_epochs, 2e-3, seed=seed + i)
        args, auxs = mod.get_params()
        arg_all.update(args)
        aux_all.update(auxs)
        cur = _encode(cur, args, i)
        log("pretrained stack %d (%d -> %d)" % (i, dims[i], dims[i + 1]))

    def recon_mse(mod):
        it = mx.io.NDArrayIter(x, x, 100, label_name='recon_label')
        out = mod.predict(it).asnumpy()
        return float(((out - x[:len(out)]) ** 2).mean())

    # reconstruction error with pretrained weights only (0 epochs =
    # just bind + load the stage params), then finetune end-to-end
    pre_mse = recon_mse(_fit(full_sym(dims), x, x, 0, 1e-3,
                             params=(arg_all, aux_all), seed=seed))
    mod = _fit(full_sym(dims), x, x, finetune_epochs, 1e-3,
               params=(arg_all, aux_all), seed=seed)
    ft_mse = recon_mse(mod)
    log("recon mse pretrained %.5f -> finetuned %.5f" % (pre_mse, ft_mse))
    return pre_mse, ft_mse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--pretrain-epochs', type=int, default=8)
    ap.add_argument('--finetune-epochs', type=int, default=8)
    a = ap.parse_args()
    pre, ft = run(pretrain_epochs=a.pretrain_epochs,
                  finetune_epochs=a.finetune_epochs)
    print("final ae mse %.5f (pretrain-only %.5f)" % (ft, pre))


if __name__ == '__main__':
    main()
