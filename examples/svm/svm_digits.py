"""Deep net with an SVM loss head
(reference: example/svm_mnist/svm_mnist.py — the same MLP trained with
``SVMOutput`` (squared hinge loss on one-vs-all margins) instead of
softmax cross-entropy, the "deep learning features + SVM objective"
recipe).

Run:  python examples/svm/svm_digits.py [--epochs 12]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402


def svm_net(regularization_coefficient=1.0, use_linear=False):
    data = mx.sym.Variable('data')
    h = mx.sym.FullyConnected(data, num_hidden=128, name='fc1')
    h = mx.sym.Activation(h, act_type='relu')
    h = mx.sym.FullyConnected(h, num_hidden=10, name='fc2')
    return mx.sym.SVMOutput(
        h, name='svm',
        regularization_coefficient=regularization_coefficient,
        use_linear=use_linear)


def run(epochs=12, batch=100, use_linear=False, seed=0, log=print):
    from sklearn.datasets import load_digits
    d = load_digits()
    x = (d.images.reshape(len(d.images), -1) / 16.0).astype(np.float32)
    y = d.target.astype(np.float32)
    n = 1500
    # the SVM head names its label 'svm_label' — both the iterator and
    # the module must agree (reference svm_mnist.py used the same pair)
    train = mx.io.NDArrayIter(x[:n], y[:n], batch, shuffle=True,
                              last_batch_handle='discard',
                              label_name='svm_label')
    test = mx.io.NDArrayIter(x[n:], y[n:], batch, label_name='svm_label')
    mx.random.seed(seed)
    mod = mx.mod.Module(svm_net(use_linear=use_linear), context=mx.cpu(),
                        label_names=('svm_label',))
    mod.fit(train, num_epoch=epochs, optimizer='sgd',
            optimizer_params={'learning_rate': 0.1, 'momentum': 0.9,
                              'wd': 1e-4},
            initializer=mx.initializer.Xavier())
    acc = mod.score(test, 'acc')[0][1]
    log("svm (%s hinge) test acc %.4f"
        % ("linear" if use_linear else "squared", acc))
    return acc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=12)
    ap.add_argument('--use-linear', action='store_true')
    a = ap.parse_args()
    acc = run(epochs=a.epochs, use_linear=a.use_linear)
    print("final svm acc %.4f" % acc)


if __name__ == '__main__':
    main()
