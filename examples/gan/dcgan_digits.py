"""DCGAN with the two-module adversarial training loop
(reference: example/gan/dcgan.py — generator + discriminator Modules,
two optimizers, and the custom alternating loop that feeds the
discriminator's INPUT gradient into the generator's backward).

TPU-native notes vs the reference:
 * same Module mechanics: `modD` binds with ``inputs_need_grad=True`` so
   ``get_input_grads()`` yields dL/d(fake image), which drives
   ``modG.backward(out_grads=...)`` — the structural capability this
   example exists to exercise;
 * every forward/backward/update is one fused XLA program per module
   (no per-op kernel launches to schedule);
 * data: sklearn's bundled ``digits`` upscaled to 32x32 (this
   environment has no egress for MNIST), generator architecture is the
   same Deconvolution→BN→relu ladder at one scale smaller.

Run:  python examples/gan/dcgan_digits.py [--epochs 3] [--batch 64]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402


def make_generator(ngf=16, nc=1):
    """rand (B, z, 1, 1) -> image (B, nc, 32, 32); reference
    make_dcgan_sym's generator one Deconv rung shorter."""
    no_bias, fix_gamma, eps = True, True, 1e-5 + 1e-12
    rand = mx.sym.Variable('rand')
    g = mx.sym.Deconvolution(rand, name='g1', kernel=(4, 4),
                             num_filter=ngf * 4, no_bias=no_bias)
    g = mx.sym.BatchNorm(g, name='gbn1', fix_gamma=fix_gamma, eps=eps)
    g = mx.sym.Activation(g, name='gact1', act_type='relu')
    g = mx.sym.Deconvolution(g, name='g2', kernel=(4, 4), stride=(2, 2),
                             pad=(1, 1), num_filter=ngf * 2,
                             no_bias=no_bias)
    g = mx.sym.BatchNorm(g, name='gbn2', fix_gamma=fix_gamma, eps=eps)
    g = mx.sym.Activation(g, name='gact2', act_type='relu')
    g = mx.sym.Deconvolution(g, name='g3', kernel=(4, 4), stride=(2, 2),
                             pad=(1, 1), num_filter=ngf, no_bias=no_bias)
    g = mx.sym.BatchNorm(g, name='gbn3', fix_gamma=fix_gamma, eps=eps)
    g = mx.sym.Activation(g, name='gact3', act_type='relu')
    g = mx.sym.Deconvolution(g, name='g4', kernel=(4, 4), stride=(2, 2),
                             pad=(1, 1), num_filter=nc, no_bias=no_bias)
    return mx.sym.Activation(g, name='gact4', act_type='tanh')


def make_discriminator(ndf=16, fix_gamma=True):
    """image -> P(real); reference make_dcgan_sym's discriminator."""
    no_bias, eps = True, 1e-5 + 1e-12
    data = mx.sym.Variable('data')
    label = mx.sym.Variable('label')
    d = mx.sym.Convolution(data, name='d1', kernel=(4, 4), stride=(2, 2),
                           pad=(1, 1), num_filter=ndf, no_bias=no_bias)
    d = mx.sym.LeakyReLU(d, name='dact1', act_type='leaky', slope=0.2)
    d = mx.sym.Convolution(d, name='d2', kernel=(4, 4), stride=(2, 2),
                           pad=(1, 1), num_filter=ndf * 2, no_bias=no_bias)
    d = mx.sym.BatchNorm(d, name='dbn2', fix_gamma=fix_gamma, eps=eps)
    d = mx.sym.LeakyReLU(d, name='dact2', act_type='leaky', slope=0.2)
    d = mx.sym.Convolution(d, name='d3', kernel=(4, 4), stride=(2, 2),
                           pad=(1, 1), num_filter=ndf * 4, no_bias=no_bias)
    d = mx.sym.BatchNorm(d, name='dbn3', fix_gamma=fix_gamma, eps=eps)
    d = mx.sym.LeakyReLU(d, name='dact3', act_type='leaky', slope=0.2)
    d = mx.sym.Convolution(d, name='d4', kernel=(4, 4), num_filter=1,
                           no_bias=no_bias)
    d = mx.sym.Flatten(d)
    return mx.sym.LogisticRegressionOutput(data=d, label=label,
                                           name='dloss')


def load_digits_32():
    from sklearn.datasets import load_digits
    d = load_digits()
    x = (d.images / 16.0).astype(np.float32)     # (N, 8, 8) in [0, 1]
    x = x.repeat(4, axis=1).repeat(4, axis=2)    # 32x32
    x = x[:, None, :, :] * 2.0 - 1.0             # (N, 1, 32, 32) in [-1,1]
    return x


def train(epochs=3, batch=64, zdim=32, lr=0.0002, ctx=None, seed=0,
          log=print):
    ctx = ctx or mx.cpu()
    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    x = load_digits_32()

    symG, symD = make_generator(), make_discriminator()

    modG = mx.mod.Module(symG, data_names=('rand',), label_names=None,
                         context=ctx)
    modG.bind(data_shapes=[('rand', (batch, zdim, 1, 1))])
    modG.init_params(mx.initializer.Normal(0.02))
    modG.init_optimizer(optimizer='adam',
                        optimizer_params={'learning_rate': lr,
                                          'beta1': 0.5})

    modD = mx.mod.Module(symD, data_names=('data',),
                         label_names=('label',), context=ctx)
    # inputs_need_grad: the generator trains on dL_D/d(input)
    modD.bind(data_shapes=[('data', (batch, 1, 32, 32))],
              label_shapes=[('label', (batch,))],
              inputs_need_grad=True)
    modD.init_params(mx.initializer.Normal(0.02))
    modD.init_optimizer(optimizer='adam',
                        optimizer_params={'learning_rate': lr,
                                          'beta1': 0.5})

    ones = mx.nd.ones((batch,))
    zeros = mx.nd.zeros((batch,))
    history = []
    for epoch in range(epochs):
        perm = rng.permutation(len(x))
        d_loss_sum = g_loss_sum = 0.0
        nbatch = 0
        for i in range(len(x) // batch):
            real = mx.nd.array(x[perm[i * batch:(i + 1) * batch]])
            noise = mx.nd.array(rng.randn(batch, zdim, 1, 1)
                                .astype(np.float32))

            # generator forward -> fake batch
            modG.forward(mx.io.DataBatch(data=[noise]), is_train=True)
            fake = modG.get_outputs()[0]

            # discriminator on fake (label 0) — update
            modD.forward(mx.io.DataBatch(data=[fake], label=[zeros]),
                         is_train=True)
            pf = modD.get_outputs()[0].asnumpy()
            modD.backward()
            modD.update()
            # discriminator on real (label 1) — update
            modD.forward(mx.io.DataBatch(data=[real], label=[ones]),
                         is_train=True)
            pr = modD.get_outputs()[0].asnumpy()
            modD.backward()
            modD.update()

            # generator step: run D on fake with label=REAL, take the
            # input gradient, push it back through G (the reference's
            # modG.backward(diffD) move)
            modD.forward(mx.io.DataBatch(data=[fake], label=[ones]),
                         is_train=True)
            modD.backward()
            diffD = modD.get_input_grads()
            modG.backward(out_grads=diffD)
            modG.update()

            eps = 1e-7
            d_loss_sum += float(-(np.log(pr + eps).mean()
                                  + np.log(1 - pf + eps).mean()))
            g_loss_sum += float(-np.log(pf + eps).mean())
            nbatch += 1
        history.append({'epoch': epoch,
                        'd_loss': d_loss_sum / nbatch,
                        'g_loss': g_loss_sum / nbatch})
        log("epoch %d d_loss %.4f g_loss %.4f"
            % (epoch, history[-1]['d_loss'], history[-1]['g_loss']))

    # a sheet of generated samples, as the reference visualized
    modG.forward(mx.io.DataBatch(data=[mx.nd.array(
        rng.randn(batch, zdim, 1, 1).astype(np.float32))]),
        is_train=False)
    samples = modG.get_outputs()[0].asnumpy()
    return history, samples


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--epochs', type=int, default=3)
    ap.add_argument('--batch', type=int, default=64)
    ap.add_argument('--zdim', type=int, default=32)
    ap.add_argument('--lr', type=float, default=0.0002)
    a = ap.parse_args()
    history, samples = train(epochs=a.epochs, batch=a.batch, zdim=a.zdim,
                             lr=a.lr)
    print("final d_loss %.4f g_loss %.4f; %d samples in [%.2f, %.2f]"
          % (history[-1]['d_loss'], history[-1]['g_loss'],
             len(samples), samples.min(), samples.max()))


if __name__ == '__main__':
    main()
