"""Neural style transfer — optimization OVER THE INPUT image
(reference: example/neural-style/nstyle.py — pretrained VGG19 features,
content loss + Gram-matrix style losses, and a gradient loop that
updates the IMAGE, not the network).

What this port exercises is the distinctive API shape: gradients with
respect to an input array (``x.attach_grad()`` + ``autograd.record``),
multi-term losses over intermediate feature maps, and an optimizer
stepped manually on a non-parameter array — the reference drove the
same loop through executor ``backward`` to the input slot.

Adaptations for this environment (zero egress): the feature extractor
is a small fixed random conv pyramid (random CNN features carry enough
texture statistics for a demonstrable style loss), and content/style
images are built from sklearn's digits.  The optimization itself — the
thing the example is about — is unchanged.

Run:  python examples/neural_style/nstyle.py [--iters 60]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from cpu_pin import pin_if_cpu  # noqa: E402
pin_if_cpu(None)  # JAX_PLATFORMS=cpu must never touch the tunnel

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, nd  # noqa: E402


def make_feature_params(channels=(8, 16, 32), seed=3):
    """Fixed random conv stack: 3x3 convs, stride 2 between scales."""
    rng = np.random.RandomState(seed)
    params = []
    cin = 1
    for cout in channels:
        w = rng.randn(cout, cin, 3, 3).astype(np.float32)
        w *= np.sqrt(2.0 / (cin * 9))
        params.append(nd.array(w))
        cin = cout
    return params


def features(x, params):
    """Forward through the fixed pyramid; returns per-scale activations."""
    feats = []
    h = x
    for k, w in enumerate(params):
        h = nd.Convolution(h, w, kernel=(3, 3), pad=(1, 1),
                           stride=(2, 2) if k else (1, 1),
                           num_filter=w.shape[0], no_bias=True)
        h = nd.Activation(h, act_type='relu')
        feats.append(h)
    return feats


def gram(feat):
    """Style statistic (reference nstyle.py style_gram): channel
    co-occurrence of a (1, C, H, W) feature map."""
    c = feat.shape[1]
    flat = feat.reshape((c, -1))
    n = flat.shape[1]
    return nd.dot(flat, flat.T) / n


def digits_image(index, size=32):
    from sklearn.datasets import load_digits
    d = load_digits()
    img = (d.images[index] / 16.0).astype(np.float32)
    img = img.repeat(size // 8, axis=0).repeat(size // 8, axis=1)
    return img[None, None, :, :]  # (1, 1, H, W)


def transfer(content_idx=0, style_idx=7, iters=60, lr=0.05,
             content_weight=1.0, style_weight=30.0, seed=0, log=print):
    params = make_feature_params()
    content = nd.array(digits_image(content_idx))
    style = nd.array(digits_image(style_idx))

    # fixed targets (no grads): deep layer for content, Grams for style
    content_target = features(content, params)[-1]
    style_targets = [gram(f) for f in features(style, params)]

    rng = np.random.RandomState(seed)
    x = nd.array(content.asnumpy()
                 + 0.1 * rng.randn(*content.shape).astype(np.float32))
    x.attach_grad()
    opt = mx.optimizer.Adam(learning_rate=lr)
    state = opt.create_state(0, x)

    losses = []
    for it in range(iters):
        with autograd.record():
            feats = features(x, params)
            c_loss = ((feats[-1] - content_target) ** 2).mean()
            s_loss = sum(((gram(f) - t) ** 2).mean()
                         for f, t in zip(feats, style_targets))
            loss = content_weight * c_loss + style_weight * s_loss
        loss.backward()
        opt.update(0, x, x.grad, state)
        losses.append(float(loss.asscalar()))
        if it % 20 == 0:
            log("iter %d loss %.5f (content %.5f style %.5f)"
                % (it, losses[-1], float(c_loss.asscalar()),
                   float(s_loss.asscalar())))
    return x, losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--iters', type=int, default=60)
    ap.add_argument('--lr', type=float, default=0.05)
    a = ap.parse_args()
    x, losses = transfer(iters=a.iters, lr=a.lr)
    print("loss %.5f -> %.5f over %d iters"
          % (losses[0], losses[-1], len(losses)))


if __name__ == '__main__':
    main()
