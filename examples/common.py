"""Shared training-script plumbing (reference:
example/image-classification/common/fit.py).

Arg parsing + kvstore creation + lr schedule + checkpoint callbacks +
Module.fit — the reference's `fit.fit(args, network, data_loader)` shape.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..'))

from cpu_pin import pin_if_cpu
pin_if_cpu()  # strip the axon factory before any jax touch when the
# caller selected CPU — otherwise a dead tunnel hangs backend init

import mxnet_tpu as mx


def add_fit_args(parser):
    """reference: common/fit.py add_fit_args."""
    parser.add_argument('--network', type=str, default=None)
    parser.add_argument('--num-epochs', type=int, default=10)
    parser.add_argument('--batch-size', type=int, default=128)
    parser.add_argument('--lr', type=float, default=0.05)
    parser.add_argument('--lr-factor', type=float, default=0.1)
    parser.add_argument('--lr-step-epochs', type=str, default='')
    parser.add_argument('--optimizer', type=str, default='sgd')
    parser.add_argument('--mom', type=float, default=0.9)
    parser.add_argument('--wd', type=float, default=1e-4)
    parser.add_argument('--kv-store', type=str, default='device')
    parser.add_argument('--dtype', type=str, default='float32',
                        help="compute dtype: float32 | bfloat16 | float16")
    parser.add_argument('--model-prefix', type=str, default=None)
    parser.add_argument('--load-epoch', type=int, default=None)
    parser.add_argument('--disp-batches', type=int, default=20)
    parser.add_argument('--num-examples', type=int, default=60000)
    return parser


def fit(args, network, train, val=None, **kwargs):
    """reference: common/fit.py fit — the universal training entry."""
    logging.basicConfig(level=logging.INFO)
    kv = mx.kv.create(args.kv_store)

    lr_sched = None
    if args.lr_step_epochs:
        epoch_size = max(args.num_examples // args.batch_size
                         // max(kv.num_workers, 1), 1)
        steps = [epoch_size * int(e)
                 for e in args.lr_step_epochs.split(',') if e]
        if steps:
            lr_sched = mx.lr_scheduler.MultiFactorScheduler(
                step=steps, factor=args.lr_factor)

    compute_dtype = None
    if args.dtype in ('bfloat16', 'float16'):
        import jax.numpy as jnp
        compute_dtype = jnp.dtype(args.dtype)

    mod = mx.mod.Module(network, context=mx.tpu(0),
                        compute_dtype=compute_dtype,
                        **{k: v for k, v in kwargs.items()
                           if k in ('data_names', 'label_names', 'mesh',
                                    'sharding_rules')})
    arg_params = aux_params = None
    begin_epoch = 0
    if args.model_prefix and args.load_epoch is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        begin_epoch = args.load_epoch

    cbs = [mx.callback.Speedometer(args.batch_size, args.disp_batches)]
    epoch_cbs = []
    if args.model_prefix:
        epoch_cbs.append(mx.callback.do_checkpoint(args.model_prefix))

    opt_params = {'learning_rate': args.lr, 'wd': args.wd}
    if args.optimizer in ('sgd', 'nag', 'signum'):
        opt_params['momentum'] = args.mom
    if lr_sched is not None:
        opt_params['lr_scheduler'] = lr_sched

    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            begin_epoch=begin_epoch,
            arg_params=arg_params, aux_params=aux_params,
            kvstore=kv, optimizer=args.optimizer,
            optimizer_params=opt_params,
            initializer=mx.initializer.Xavier(rnd_type='gaussian',
                                              factor_type='in',
                                              magnitude=2),
            batch_end_callback=cbs, epoch_end_callback=epoch_cbs,
            eval_metric='acc')
    return mod
