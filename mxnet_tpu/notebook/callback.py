"""Notebook training-progress callbacks
(reference: python/mxnet/notebook/callback.py).

``PandasLogger`` collects train/eval metrics into pandas DataFrames for
notebook analysis.  The reference's live-plot layer (LiveBokehChart /
LiveLearningCurve) depends on bokeh, which isn't shipped here;
``LiveLearningCurve`` keeps the callback contract and metric
accumulation but does NOT render (with or without bokeh installed) —
plot the accumulated ``.train_data`` / ``.eval_data`` with any library.
"""
from __future__ import annotations

import time


class PandasLogger:
    """Accumulate per-batch train metrics, per-epoch eval metrics and
    timings into pandas DataFrames (reference: notebook/callback.py:71).
    """

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self._train = []
        self._eval = []
        self._epoch = []
        self.last_time = time.time()
        self.start_time = time.time()

    @property
    def train_df(self):
        import pandas as pd
        return pd.DataFrame(self._train)

    @property
    def eval_df(self):
        import pandas as pd
        return pd.DataFrame(self._eval)

    @property
    def epoch_df(self):
        import pandas as pd
        return pd.DataFrame(self._epoch)

    @property
    def all_dataframes(self):
        return {'train': self.train_df, 'eval': self.eval_df,
                'epoch': self.epoch_df}

    def elapsed(self):
        return time.time() - self.start_time

    def _process_batch(self, param, rows):
        now = time.time()
        if param.eval_metric is not None:
            metrics = dict(param.eval_metric.get_name_value())
        else:
            metrics = {}
        speed = self.frequent * self.batch_size / (now - self.last_time) \
            if now > self.last_time else float('inf')
        metrics['batches_per_sec'] = speed / self.batch_size
        metrics['records_per_sec'] = speed
        metrics['elapsed'] = self.elapsed()
        metrics['minibatch_count'] = param.nbatch
        metrics['epoch'] = param.epoch
        rows.append(metrics)
        self.last_time = now

    def train_cb(self, param):
        if param.nbatch % self.frequent == 0:
            self._process_batch(param, self._train)

    def eval_cb(self, param):
        self._process_batch(param, self._eval)

    def epoch_cb(self):
        self._epoch.append({'elapsed': self.elapsed()})

    def callback_args(self):
        """kwargs for Module.fit wiring all callbacks
        (reference: notebook/callback.py:188)."""
        return {'batch_end_callback': self.train_cb,
                'eval_end_callback': self.eval_cb,
                'epoch_end_callback': lambda *a, **kw: self.epoch_cb()}


class LiveLearningCurve:
    """Callback-compatible metric accumulator with the reference
    LiveLearningCurve signature (reference: notebook/callback.py).

    Live bokeh rendering is NOT implemented (bokeh isn't shipped here);
    the callback contract and accumulated series (``.train_data`` /
    ``.eval_data`` as (epoch, [batch,] value) tuples) are, so notebooks
    plot with whatever is available.  ``display_freq`` is accepted for
    signature parity and unused."""

    def __init__(self, metric_name, display_freq=10, frequent=50):
        self.metric_name = metric_name
        self.display_freq = display_freq
        self.frequent = frequent
        self.train_data = []
        self.eval_data = []

    def train_cb(self, param):
        if param.nbatch % self.frequent == 0 \
                and param.eval_metric is not None:
            metrics = dict(param.eval_metric.get_name_value())
            if self.metric_name in metrics:
                self.train_data.append(
                    (param.epoch, param.nbatch,
                     metrics[self.metric_name]))

    def eval_cb(self, param):
        if param.eval_metric is not None:
            metrics = dict(param.eval_metric.get_name_value())
            if self.metric_name in metrics:
                self.eval_data.append(
                    (param.epoch, metrics[self.metric_name]))

    def callback_args(self):
        return {'batch_end_callback': self.train_cb,
                'eval_end_callback': self.eval_cb}
