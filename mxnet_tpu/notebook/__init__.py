"""Notebook utilities (reference: python/mxnet/notebook/)."""
from . import callback  # noqa: F401
