"""mx.sym.linalg — symbolic linear-algebra namespace
(reference: python/mxnet/symbol/linalg.py, the symbol mirror of
ndarray/linalg.py over la_op.cc)."""
from __future__ import annotations


def _make(name, op):
    def f(*args, **kw):
        from .. import symbol as _sym
        g = getattr(_sym, op, None)
        if g is None:
            raise AttributeError(f"symbol op {op!r} not registered")
        return g(*args, **kw)
    f.__name__ = name
    f.__doc__ = f"Symbolic {op} (same registered op as mx.nd.linalg.{name})."
    return f


gemm = _make("gemm", "linalg_gemm")
gemm2 = _make("gemm2", "linalg_gemm2")
potrf = _make("potrf", "linalg_potrf")
potri = _make("potri", "linalg_potri")
trmm = _make("trmm", "linalg_trmm")
trsm = _make("trsm", "linalg_trsm")
syrk = _make("syrk", "linalg_syrk")
gelqf = _make("gelqf", "linalg_gelqf")
sumlogdiag = _make("sumlogdiag", "linalg_sumlogdiag")
syevd = _make("syevd", "linalg_syevd")
inverse = _make("inverse", "linalg_inverse")
det = _make("det", "linalg_det")
slogdet = _make("slogdet", "linalg_slogdet")
