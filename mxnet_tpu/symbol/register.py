"""Autogenerate the ``mx.sym.*`` namespace from the op registry.

Mirror of the reference's symbol wrapper codegen
(python/mxnet/symbol/register.py; C side MXSymbolCreateAtomicSymbol +
Compose, src/c_api/c_api_symbolic.cc).  Shares the single OpDef registry
with the NDArray frontend — one registration path serves both (SURVEY.md §7
design stance).
"""
from __future__ import annotations

from ..ops import registry as _reg
from .symbol import Symbol, _compose, _skip_args


def make_sym_func(opdef: _reg.OpDef, name: str):
    def sym_func(*args, **kwargs):
        sym_name = kwargs.pop("name", None)
        user_attr = kwargs.pop("attr", None)
        if len(args) == 1 and isinstance(args[0], (list, tuple)) and opdef.variadic:
            args = tuple(args[0])
        if opdef.variadic:
            inputs = [a for a in args if isinstance(a, Symbol)]
            attrs = {k: v for k, v in kwargs.items()
                     if not isinstance(v, Symbol)}
            inputs += [v for v in kwargs.values() if isinstance(v, Symbol)]
            return _compose(opdef.name, inputs, attrs, sym_name,
                            user_attr=user_attr)
        arg_names = list(opdef.arg_names or [])
        aux_names = list(opdef.aux_names or [])
        attrs = {}
        supplied = {}
        for k in list(kwargs):
            if isinstance(kwargs[k], Symbol):
                supplied[k] = kwargs.pop(k)
            else:
                attrs[k] = kwargs[k]
        skip = _skip_args(opdef.name, attrs)
        wanted = [a for a in arg_names + aux_names if a not in skip]
        pos = list(args)
        inputs = []
        for nm in wanted:
            if nm in supplied:
                inputs.append(supplied.pop(nm))
            elif pos:
                inputs.append(pos.pop(0))
            else:
                break  # remaining become auto-created variables in _compose
        inputs.extend(pos)
        return _compose(opdef.name, inputs, attrs, sym_name,
                        user_attr=user_attr)

    sym_func.__name__ = name
    sym_func.__doc__ = _reg.build_op_doc(opdef, name, flavor="sym")
    return sym_func


def init_symbol_module(namespace: dict):
    for name in _reg.list_ops():
        opdef = _reg.get(name)
        namespace.setdefault(name, make_sym_func(opdef, name))
