"""Symbolic API (``mx.sym`` / ``mx.symbol``)."""
from .symbol import (Symbol, Node, Variable, var, Group, load, load_json,
                     zeros, ones, arange)
from .register import init_symbol_module

init_symbol_module(globals())


from ..base import ContribNamespace as _ContribNS
contrib = _ContribNS(globals())

from . import random    # noqa: E402  mx.sym.random.*
from . import linalg    # noqa: E402  mx.sym.linalg.*
