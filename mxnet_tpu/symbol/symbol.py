"""Symbol: the declarative graph frontend.

TPU-native equivalent of the reference's nnvm ``Symbol``/``Graph``
(python/mxnet/symbol/symbol.py; nnvm op graph built by
src/c_api/c_api_symbolic.cc).  A Symbol is a list of (node, output-index)
heads over a DAG of ``Node`` objects.  Unlike the reference there is no
C++ graph IR — the graph *is* the trace program: binding a symbol builds a
pure jax function that an :class:`~mxnet_tpu.executor.Executor` jit-compiles
(the XLA-native replacement for GraphExecutor's memory planning / op bulking,
src/executor/graph_executor.cc:507-1456 — XLA buffer assignment and fusion
subsume both).

Graph JSON save/load mirrors the nnvm JSON layout (nodes / arg_nodes /
heads — nnvm SaveJSON as used by mx.model.save_checkpoint, model.py:340) so
checkpoints remain structurally familiar.
"""
from __future__ import annotations

import json
import numbers
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from .. import name as _name
from .. import attribute as _attribute
from ..ops import registry as _reg


class Node:
    """One graph node: an op application or (op=None) a variable."""
    __slots__ = ("op", "name", "attrs", "inputs", "_user_attrs")

    def __init__(self, op: Optional[str], name: str, attrs: dict,
                 inputs: List[Tuple["Node", int]], user_attrs=None):
        self.op = op
        self.name = name
        self.attrs = attrs
        self.inputs = inputs
        self._user_attrs = dict(user_attrs or {})

    @property
    def is_variable(self):
        return self.op is None

    def opdef(self) -> Optional[_reg.OpDef]:
        return _reg.get(self.op) if self.op else None

    def num_outputs(self) -> int:
        return node_num_outputs(self)


def node_num_outputs(node: Node) -> int:
    if node.op is None:
        return 1
    opdef = _reg.get(node.op)
    n = opdef.num_visible if opdef.num_visible is not None else opdef.num_outputs
    if callable(n):  # attr-dependent (reference NumVisibleOutputs)
        n = n(node.attrs)
    if n == -1:
        # attr-dependent output count (reference: SliceChannel num_outputs)
        if node.op in ("SliceChannel", "split"):
            return int(node.attrs.get("num_outputs", 1))
        if node.op == "topk":
            return 2 if node.attrs.get("ret_typ", "indices") == "both" else 1
        if node.op == "RNN":
            return 3 if node.attrs.get("state_outputs") else 1
        if node.op == "Custom":
            from .. import operator as _custom_mod
            return _custom_mod.num_outputs_for(node.attrs)
        return 1
    return n


def _topo_sort(heads: Sequence[Tuple[Node, int]]) -> List[Node]:
    order: List[Node] = []
    visited = set()

    def visit(node):
        stack = [(node, False)]
        while stack:
            n, processed = stack.pop()
            if processed:
                order.append(n)
                continue
            if id(n) in visited:
                continue
            visited.add(id(n))
            stack.append((n, True))
            for inp, _ in reversed(n.inputs):
                if id(inp) not in visited:
                    stack.append((inp, False))

    for node, _ in heads:
        visit(node)
    return order


# ---------------------------------------------------------------------------
# parameter-shape inference hooks (reference: per-op InferShape filling
# unknown arg shapes, src/executor/infer_graph_attr_pass.cc:368; e.g.
# FullyConnectedProp::InferShape derives weight from data × num_hidden)
# ---------------------------------------------------------------------------
def _fc_param_shapes(attrs, in_shapes):
    data = in_shapes.get("data")
    if data is None:
        return {}
    nh = int(attrs.get("num_hidden", 0))
    flatten = attrs.get("flatten", True)
    in_dim = int(np.prod(data[1:])) if flatten else data[-1]
    out = {"weight": (nh, in_dim)}
    if not attrs.get("no_bias", False):
        out["bias"] = (nh,)
    return out


def _conv_param_shapes(attrs, in_shapes):
    data = in_shapes.get("data")
    if data is None:
        return {}
    kernel = tuple(int(k) for k in attrs.get("kernel", ()))
    nf = int(attrs.get("num_filter", 0))
    ng = int(attrs.get("num_group", 1))
    # NHWC activations keep channels last; the weight stays OIHW either way
    cin = data[-1] if attrs.get("layout") == "NHWC" else data[1]
    out = {"weight": (nf, cin // ng) + kernel}
    if not attrs.get("no_bias", False):
        out["bias"] = (nf,)
    return out


def _deconv_param_shapes(attrs, in_shapes):
    data = in_shapes.get("data")
    if data is None:
        return {}
    kernel = tuple(int(k) for k in attrs.get("kernel", ()))
    nf = int(attrs.get("num_filter", 0))
    ng = int(attrs.get("num_group", 1))
    cin = data[1]
    out = {"weight": (cin, nf // ng) + kernel}
    if not attrs.get("no_bias", True):
        out["bias"] = (nf,)
    return out


def _bn_param_shapes(attrs, in_shapes):
    data = in_shapes.get("data")
    if data is None:
        return {}
    ax = int(attrs.get("axis", 1)) % len(data)
    c = data[ax]
    return {"gamma": (c,), "beta": (c,),
            "moving_mean": (c,), "moving_var": (c,)}


def _in_param_shapes(attrs, in_shapes):
    data = in_shapes.get("data")
    if data is None:
        return {}
    return {"gamma": (data[1],), "beta": (data[1],)}


def _ln_param_shapes(attrs, in_shapes):
    data = in_shapes.get("data")
    if data is None:
        return {}
    ax = int(attrs.get("axis", -1)) % len(data)
    return {"gamma": (data[ax],), "beta": (data[ax],)}


def _embedding_param_shapes(attrs, in_shapes):
    return {"weight": (int(attrs["input_dim"]), int(attrs["output_dim"]))}


def _prelu_param_shapes(attrs, in_shapes):
    data = in_shapes.get("data")
    if data is None or attrs.get("act_type", "leaky") != "prelu":
        return {}
    return {"gamma": (data[1] if len(data) > 1 else 1,)}


def _rnn_param_shapes(attrs, in_shapes):
    data = in_shapes.get("data")  # (seq, batch, input)
    if data is None:
        return {}
    from ..ops.rnn import rnn_param_size
    mode = attrs.get("mode", "lstm")
    sh = int(attrs["state_size"])
    nl = int(attrs.get("num_layers", 1))
    bidir = bool(attrs.get("bidirectional", False))
    d = 2 if bidir else 1
    psize = rnn_param_size(nl, data[2], sh, bidir, mode)
    shapes = {"parameters": (psize,),
              "state": (nl * d, data[1], sh)}
    if mode == "lstm":
        shapes["state_cell"] = (nl * d, data[1], sh)
    return shapes


PARAM_SHAPE_INFER = {
    "FullyConnected": _fc_param_shapes,
    "Convolution": _conv_param_shapes,
    "Deconvolution": _deconv_param_shapes,
    "BatchNorm": _bn_param_shapes,
    "InstanceNorm": _in_param_shapes,
    "LayerNorm": _ln_param_shapes,
    "L2Normalization": lambda a, s: {},
    "Embedding": _embedding_param_shapes,
    "LeakyReLU": _prelu_param_shapes,
    "RNN": _rnn_param_shapes,
}

# args skipped at composition time depending on attrs (reference: each op's
# ListArguments respects flags like no_bias)
def _skip_args(op: str, attrs: dict) -> set:
    skip = set()
    opdef = _reg.find(op)
    no_bias_default = (opdef.attr_defaults.get("no_bias", False)
                       if opdef else False)
    if attrs.get("no_bias", no_bias_default) in (True, "True", "true", 1):
        skip.add("bias")
    if op == "LeakyReLU" and attrs.get("act_type", "leaky") != "prelu":
        skip.add("gamma")
    if op == "RNN" and attrs.get("mode", "lstm") != "lstm":
        skip.add("state_cell")
    if op == "CTCLoss":
        if attrs.get("use_data_lengths", False) not in (True, "True",
                                                        "true", 1):
            skip.add("data_lengths")
        if attrs.get("use_label_lengths", False) not in (True, "True",
                                                         "true", 1):
            skip.add("label_lengths")
    if op in ("SequenceReverse", "SequenceMask", "SequenceLast"):
        # the optional length input EXISTS only under
        # use_sequence_length=True (reference: sequence_reverse-inl.h) —
        # otherwise it must not auto-materialize as a learnable arg
        if attrs.get("use_sequence_length", False) not in (True, "True",
                                                           "true", 1):
            skip.add("sequence_length")
    return skip


class Symbol:
    """A list of output heads over the op DAG (reference Symbol semantics)."""
    __slots__ = ("_heads",)

    def __init__(self, heads: List[Tuple[Node, int]]):
        self._heads = list(heads)

    # -- identity -----------------------------------------------------------
    @property
    def name(self):
        if len(self._heads) == 1:
            return self._heads[0][0].name
        return None

    def __repr__(self):
        names = ", ".join(n.name for n, _ in self._heads)
        return f"<Symbol {names}>"

    def __iter__(self):
        return (self[i] for i in range(len(self)))

    def __len__(self):
        return sum(node_num_outputs(n) if i is None else 1
                   for n, i in self._heads)

    # -- composition helpers ------------------------------------------------
    def _single_head(self) -> Tuple[Node, int]:
        if len(self._heads) != 1:
            raise MXNetError("operation requires a single-output symbol")
        return self._heads[0]

    def __getitem__(self, index):
        outputs = self._expanded_heads()
        if isinstance(index, str):
            names = self.list_outputs()
            matches = [i for i, n in enumerate(names)
                       if n == index or n == index + "_output"]
            if not matches:
                raise ValueError(f"no output named {index!r}")
            return Symbol([outputs[matches[0]]])
        if isinstance(index, slice):
            return Symbol(outputs[index])
        return Symbol([outputs[index]])

    def _expanded_heads(self) -> List[Tuple[Node, int]]:
        out = []
        for node, idx in self._heads:
            if idx is None:
                for i in range(node_num_outputs(node)):
                    out.append((node, i))
            else:
                out.append((node, idx))
        return out

    @property
    def heads(self):
        return self._expanded_heads()

    # -- graph introspection ------------------------------------------------
    def nodes(self) -> List[Node]:
        return _topo_sort(self._expanded_heads())

    def list_arguments(self) -> List[str]:
        return [n.name for n in self.nodes()
                if n.is_variable and not n._user_attrs.get("__is_aux__")]

    def list_outputs(self) -> List[str]:
        names = []
        for node, idx in self._expanded_heads():
            if node.is_variable:
                names.append(node.name)
            elif node_num_outputs(node) == 1:
                names.append(node.name + "_output")
            else:
                names.append(f"{node.name}_output{idx}")
        return names

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in self.nodes()
                if n.is_variable and n._user_attrs.get("__is_aux__")]

    def list_inputs(self):
        return [n.name for n in self.nodes() if n.is_variable]

    def get_internals(self) -> "Symbol":
        heads = []
        for n in self.nodes():
            for i in range(node_num_outputs(n)):
                heads.append((n, i))
        return Symbol(heads)

    def get_children(self) -> Optional["Symbol"]:
        """Inputs of every head, in head order (reference Symbol
        semantics: on a grouped/multi-output symbol the children of all
        heads concatenate; leaf variables contribute none).  None when
        no head has inputs."""
        heads = []
        seen = set()
        for node, _ in self._heads:
            # reference nnvm GetChildren visits each head NODE once:
            # three expanded outputs of one SliceChannel contribute its
            # inputs a single time
            if id(node) in seen:
                continue
            seen.add(id(node))
            heads.extend(node.inputs)
        if not heads:
            return None
        return Symbol(heads)

    # -- attributes ---------------------------------------------------------
    def attr(self, key):
        node, _ = self._single_head()
        return node._user_attrs.get(key)

    def list_attr(self):
        node, _ = self._single_head()
        return {k: v for k, v in node._user_attrs.items()
                if not k.startswith("__is_aux")}

    def attr_dict(self):
        out = {}
        for n in self.nodes():
            attrs = {k: v for k, v in n._user_attrs.items()
                     if not k.startswith("__is_aux")}
            attrs.update({k: str(v) for k, v in n.attrs.items()})
            if attrs:
                out[n.name] = attrs
        return out

    def _set_attr(self, **kwargs):
        node, _ = self._single_head()
        node._user_attrs.update(kwargs)

    # -- shape/type inference ----------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise
        except Exception as e:
            raise MXNetError(f"infer_shape error: {e}")

    def infer_shape_partial(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(True, *args, **kwargs)
        except Exception:
            n_args = len(self.list_arguments())
            return ([None] * n_args, None, None)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        arg_names = self.list_arguments()
        known: Dict[str, tuple] = {}
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    known[n] = tuple(s)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})
        shapes, dtypes = _infer_graph_shapes(self, known, {})
        aux_names = self.list_auxiliary_states()
        out_shapes = shapes["__outputs__"]
        arg_shapes = [shapes.get(n) for n in arg_names]
        aux_shapes = [shapes.get(n) for n in aux_names]
        if not partial and any(s is None for s in arg_shapes):
            missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
            raise MXNetError(f"infer_shape: cannot infer shapes for {missing}")
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        known: Dict[str, np.dtype] = {}
        if args:
            for n, t in zip(arg_names, args):
                if t is not None:
                    known[n] = np.dtype(t)
        known.update({k: np.dtype(v) for k, v in kwargs.items()
                      if v is not None})
        # Variable(dtype=...) attrs pin that variable; FLOAT attr dtypes
        # also join the default election below (a float16 data input
        # retypes the whole homogeneous graph, the reference InferType
        # behavior) — INTEGER pins do not (an int32 index input must not
        # retype every untyped parameter)
        float_attr_dtypes = []
        for node in self.nodes():
            if node.is_variable and "__dtype__" in node._user_attrs:
                dt = np.dtype(node._user_attrs["__dtype__"])
                known.setdefault(node.name, dt)
                if np.issubdtype(dt, np.floating):
                    float_attr_dtypes.append(dt)
        # propagate: any explicitly-passed dtype becomes the default for
        # all unspecified inputs (the reference's InferType propagation
        # collapses to this for homogeneous-dtype graphs)
        explicit = [v for k, v in known.items()]
        float_explicit = [v for v in explicit
                          if np.issubdtype(v, np.floating)]
        default = next(iter(float_explicit + float_attr_dtypes),
                       np.dtype("float32"))
        all_known = dict(known)
        for n in arg_names + self.list_auxiliary_states():
            all_known.setdefault(n, default)
        _, dtypes = _infer_graph_shapes(self, {}, all_known,
                                        shapes_optional=True,
                                        dummy_shapes=True)
        arg_types = [dtypes.get(n, default) for n in arg_names]
        aux_types = [dtypes.get(n, default)
                     for n in self.list_auxiliary_states()]
        out_types = dtypes.get("__outputs__",
                               [default] * len(self.list_outputs()))
        return arg_types, out_types, aux_types

    # -- save/load ----------------------------------------------------------
    def tojson(self):
        nodes = self.nodes()
        node_index = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jn = {
                "op": n.op if n.op else "null",
                "name": n.name,
                "inputs": [[node_index[id(src)], idx, 0]
                           for src, idx in n.inputs],
            }
            attrs = {k: _attr_to_str(v) for k, v in n.attrs.items()}
            attrs.update({k: str(v) for k, v in n._user_attrs.items()})
            if attrs:
                jn["attrs"] = attrs
            jnodes.append(jn)
        heads = [[node_index[id(n)], i, 0] for n, i in self._expanded_heads()]
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_variable]
        return json.dumps({
            "nodes": jnodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 1200]},
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- arithmetic ---------------------------------------------------------
    def __abs__(self):
        return _compose("abs", [self], {}, None)

    def _binop(self, other, op, scalar_op, rop=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if rop else (self, other)
            return _compose(op, [a, b], {}, None)
        if isinstance(other, numbers.Number):
            return _compose(scalar_op, [self], {"scalar": float(other)}, None)
        return NotImplemented

    def __add__(self, o): return self._binop(o, "broadcast_add", "_plus_scalar")
    __radd__ = __add__
    def __sub__(self, o): return self._binop(o, "broadcast_sub", "_minus_scalar")
    def __rsub__(self, o): return self._binop(o, "broadcast_sub", "_rminus_scalar", rop=True)
    def __mul__(self, o): return self._binop(o, "broadcast_mul", "_mul_scalar")
    __rmul__ = __mul__
    def __truediv__(self, o): return self._binop(o, "broadcast_div", "_div_scalar")
    def __rtruediv__(self, o): return self._binop(o, "broadcast_div", "_rdiv_scalar", rop=True)
    __div__ = __truediv__
    __rdiv__ = __rtruediv__
    def __pow__(self, o): return self._binop(o, "broadcast_power", "_power_scalar")
    def __mod__(self, o): return self._binop(o, "broadcast_mod", "_mod_scalar")
    def __neg__(self): return _compose("negative", [self], {}, None)
    def __eq__(self, o): return self._binop(o, "broadcast_equal", "_equal_scalar")
    def __ne__(self, o): return self._binop(o, "broadcast_not_equal", "_not_equal_scalar")
    def __gt__(self, o): return self._binop(o, "broadcast_greater", "_greater_scalar")
    def __ge__(self, o): return self._binop(o, "broadcast_greater_equal", "_greater_equal_scalar")
    def __lt__(self, o): return self._binop(o, "broadcast_lesser", "_lesser_scalar")
    def __le__(self, o): return self._binop(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __copy__(self):
        return Symbol(list(self._heads))

    def __deepcopy__(self, memo):
        # graph nodes are immutable-by-convention; sharing is safe
        return Symbol(list(self._heads))

    # -- convenience method mirrors (subset used by models/tests) -----------
    def reshape(self, *shape, **kw):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return _compose("Reshape", [self], {"shape": shape, **kw}, None)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _compose("transpose", [self], {"axes": axes}, None)

    def astype(self, dtype):
        return _compose("Cast", [self], {"dtype": np.dtype(dtype).name}, None)

    def sum(self, axis=None, keepdims=False):
        return _compose("sum", [self], {"axis": axis, "keepdims": keepdims}, None)

    def mean(self, axis=None, keepdims=False):
        return _compose("mean", [self], {"axis": axis, "keepdims": keepdims}, None)

    def flatten(self):
        return _compose("Flatten", [self], {}, None)

    def slice_axis(self, axis, begin, end):
        return _compose("slice_axis", [self],
                        {"axis": axis, "begin": begin, "end": end}, None)

    def expand_dims(self, axis):
        return _compose("expand_dims", [self], {"axis": axis}, None)

    def softmax(self, axis=-1):
        return _compose("softmax", [self], {"axis": axis}, None)

    # -- evaluation / binding ----------------------------------------------
    def eval(self, ctx=None, **kwargs):
        from ..executor import Executor
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor
        return Executor(self, ctx, args=args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states,
                        group2ctx=group2ctx, shared_exec=shared_exec)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, shared_arg_names=None, shared_exec=None,
                    shared_buffer=None, **kwargs):
        from ..executor import Executor
        return Executor.simple_bind(self, ctx, grad_req=grad_req,
                                    type_dict=type_dict,
                                    shared_exec=shared_exec,
                                    shapes=kwargs)

    # gradient symbol (reference: nnvm Gradient pass exposed as Symbol.grad
    # in old API) — not needed: Executor differentiates via jax.vjp.
    def grad(self, wrt):
        raise MXNetError("Symbol.grad is not supported; bind and use "
                         "backward (jax.vjp differentiates the whole graph)")


def _attr_to_str(v):
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, (tuple, list)):
        return "(" + ", ".join(str(x) for x in v) + ")"
    return str(v)


# ---------------------------------------------------------------------------
# composition (reference: MXSymbolCreateAtomicSymbol + Compose,
# c_api_symbolic.cc)
# ---------------------------------------------------------------------------
def _compose(op_name: str, inputs: List[Symbol], attrs: dict,
             name: Optional[str], user_attr: Optional[dict] = None) -> Symbol:
    opdef = _reg.get(op_name)
    attrs = {k: v for k, v in attrs.items() if v is not None}
    hint = op_name.lower().lstrip("_")
    name = _name.current().get(name, hint)
    # explicit attr= dict merges over the ambient AttrScope (reference:
    # atomic-symbol attrs, test_attr.py test_list_attr/test_attr_dict)
    user_attrs = _attribute.current().get(user_attr)

    heads: List[Tuple[Node, int]] = []
    for s in inputs:
        hs = s._expanded_heads()
        heads.extend(hs)

    if not opdef.variadic:
        # auto-create missing parameter/aux variables; they inherit the
        # op's attr dict like the reference's Compose does
        arg_names = list(opdef.arg_names or [])
        aux_names = list(opdef.aux_names or [])
        skip = _skip_args(op_name, attrs)
        wanted = [a for a in arg_names + aux_names if a not in skip]
        n_missing = len(wanted) - len(heads)
        if n_missing > 0:
            for extra in wanted[len(heads):]:
                is_aux = extra in aux_names
                v = Variable(f"{name}_{extra}", attr=user_attr,
                             __is_aux__="1" if is_aux else None)
                heads.extend(v._expanded_heads())

    node = Node(op_name, name, attrs, heads, user_attrs)
    return Symbol([(node, None)])


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs) -> Symbol:
    """Create a variable symbol (reference: symbol.py var/Variable)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    user_attrs = _attribute.current().get(attr)
    if shape is not None:
        user_attrs["__shape__"] = str(tuple(shape))
    if lr_mult is not None:
        user_attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        user_attrs["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        user_attrs["__dtype__"] = np.dtype(dtype).name
    if init is not None:
        if not isinstance(init, str):
            init = init.dumps()
        user_attrs["__init__"] = init
    for k, v in kwargs.items():
        if v is None:
            continue
        if k.startswith("__") and k.endswith("__"):
            user_attrs[k] = str(v)
        else:
            user_attrs[k] = str(v)
    user_attrs = {k: v for k, v in user_attrs.items() if v is not None}
    node = Node(None, name, {}, [], user_attrs)
    return Symbol([(node, None)])


Variable = var


def Group(symbols) -> Symbol:
    heads = []
    for s in symbols:
        if not isinstance(s, Symbol):
            raise TypeError("Group expects Symbols")
        heads.extend(s._expanded_heads())
    return Symbol(heads)


def _entry(e):
    """Graph entry → (node_id, output_idx).  nnvm-era JSON writes
    [node, idx, version] triplets; the reference's pre-nnvm v0.8 format
    (the checked-in save_000800.json fixture, upgraded there by
    src/nnvm/legacy_json_util.cc) writes [node, idx] pairs — accept
    both so reference-written symbol files load unchanged."""
    return e[0], e[1]


def load_json(json_str: str) -> Symbol:
    g = json.loads(json_str)
    nodes: List[Node] = []
    for jn in g["nodes"]:
        attrs = dict(jn.get("attrs", jn.get("param", {})) or {})
        user_attrs = {k: v for k, v in attrs.items()
                      if k.startswith("__") or k in ("ctx_group",)}
        # ONLY the pre-nnvm v0.8 format (identified by its sibling
        # "param" dict) keeps USER attrs (lr_mult, ctx_group, ...) in a
        # separate "attr" dict; nnvm-era files spell op params "attr",
        # and merging those here would silently strip them from the op
        if "param" in jn:
            user_attrs.update(jn.get("attr", {}) or {})
        op = jn["op"]
        if op == "null":
            node = Node(None, jn["name"], {}, [], user_attrs)
        else:
            opdef = _reg.find(op)
            if opdef is None:
                raise MXNetError(f"cannot load graph: unknown op {op!r}")
            op_attrs = {k: _parse_attr(v, opdef.attr_defaults.get(k))
                        for k, v in attrs.items() if not k.startswith("__")}
            inputs = [(nodes[i], idx)
                      for i, idx in map(_entry, jn["inputs"])]
            # pre-nnvm JSON omits implicit inputs (BatchNorm's
            # moving_mean/var aux states, SoftmaxOutput's label);
            # synthesize the missing TRAILING ones with composition's
            # standard names — the reference's legacy upgrade pass
            # (legacy_json_util.cc) re-ran composition to the same effect
            # same conditional-arg filter as composition (no_bias drops
            # bias, non-prelu LeakyReLU drops gamma, ...): without it a
            # tojson/load round trip would fabricate phantom arguments
            skip = _skip_args(op, op_attrs)
            args_w = [a for a in (opdef.arg_names or [])
                      if a not in skip]
            aux_w = [a for a in (opdef.aux_names or []) if a not in skip]
            want = args_w + aux_w
            if not opdef.variadic and args_w and len(inputs) < len(want):
                for pos, missing in enumerate(want[len(inputs):],
                                              start=len(inputs)):
                    # NOTE: synthesized variables must NOT enter `nodes`
                    # — the JSON's input indices refer to the original
                    # node list, and shifting it corrupts later edges
                    var = Node(None, f"{jn['name']}_{missing}", {}, [],
                               {"__is_aux__": True}
                               if pos >= len(args_w) else {})
                    inputs.append((var, 0))
            node = Node(op, jn["name"], op_attrs, inputs, user_attrs)
        nodes.append(node)
    heads = [(nodes[i], idx) for i, idx in map(_entry, g["heads"])]
    return Symbol(heads)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def _parse_attr(v, default=None):
    """Parse a stringified attr back to python (tuples, bools, numbers)."""
    if not isinstance(v, str):
        return v
    s = v.strip()
    if s in ("True", "true"):
        return True
    if s in ("False", "false"):
        return False
    if s in ("None", ""):
        return None
    if s.startswith("(") or s.startswith("["):
        inner = s[1:-1].strip()
        if not inner:
            return ()
        parts = [p.strip() for p in inner.split(",") if p.strip()]
        return tuple(_parse_attr(p) for p in parts)
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return v


# ---------------------------------------------------------------------------
# partial (bidirectional) shape inference
# ---------------------------------------------------------------------------
# The reference's InferShape pass (src/executor/infer_graph_attr_pass.cc:368)
# iterates forward AND backward so a 0 ("unknown") dim anywhere can be pinned
# by constraints elsewhere (tests/python/unittest/test_infer_shape.py).  The
# main engine below is forward abstract interpretation; this fixpoint
# pre-pass resolves unknown dims for the structural ops where backward
# propagation matters (elementwise/broadcast binaries, FullyConnected,
# Convolution, Concat, SliceChannel, shape-preserving unaries), then hands
# fully-resolved variable shapes to the forward engine.

_SHAPE_PRESERVING_OPS = frozenset({
    "Activation", "relu", "sigmoid", "tanh", "softsign", "exp", "log",
    "negative", "abs", "square", "sqrt", "BlockGrad", "stop_gradient",
    "_copy", "identity", "make_loss", "zeros_like", "ones_like",
    "LeakyReLU", "softmax", "log_softmax", "Dropout", "BatchNorm",
    "InstanceNorm", "L2Normalization", "Cast", "cast",
})
# strict same-shape binaries: inputs and output all unify dim-wise
_ELEMWISE_BINARY_OPS = frozenset({
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "elemwise_mod", "_identity_with_attr_like_rhs", "_grad_add",
})
# numpy-broadcast binaries: right-aligned, 1s broadcast; unknown input
# dims fill OPTIMISTICALLY from the output (assume no broadcast), the
# same call the reference's BinaryBroadcastShape makes
_BROADCAST_BINARY_OPS = frozenset({
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_mod", "broadcast_power", "broadcast_maximum",
    "broadcast_minimum", "broadcast_hypot",
})


def _unify_dims(a, b, where=""):
    """Dim-wise merge of two patterns (None/0 = unknown)."""
    if a is None:
        return list(b) if b is not None else None
    if b is None:
        return list(a)
    if len(a) != len(b):
        raise MXNetError(f"infer_shape: rank mismatch {a} vs {b} {where}")
    out = []
    for x, y in zip(a, b):
        x = None if not x else x
        y = None if not y else y
        if x is not None and y is not None and x != y:
            raise MXNetError(
                f"infer_shape: inconsistent dims {a} vs {b} {where}")
        out.append(x if x is not None else y)
    return out


def _partial_prepass(nodes, var_pat, generic_eval=True):
    """Fixpoint bidirectional dim propagation.  ``var_pat``: id(node) ->
    list pattern (None = unknown) for variables; mutated in place.
    ``generic_eval=False`` skips abstract-eval of unhandled ops (used on
    the fully-specified path, where the main engine traces them anyway —
    the special-cased rules still run for constraint VALIDATION)."""
    pat: Dict[Tuple[int, int], list] = {}
    for n in nodes:
        if n.is_variable and var_pat.get(id(n)) is not None:
            pat[(id(n), 0)] = list(var_pat[id(n)])

    def get(src, idx):
        return pat.get((id(src), idx))

    def put(src, idx, p, where):
        if p is None:
            return False
        merged = _unify_dims(get(src, idx), p, where)
        if merged != get(src, idx):
            pat[(id(src), idx)] = merged
            if src.is_variable:
                var_pat[id(src)] = merged
            return True
        return False

    def complete(p):
        return p is not None and all(d for d in p)

    for _ in range(3 * len(nodes) + 8):
        changed = False
        for n in nodes:
            if n.is_variable:
                continue
            ins = [get(s, i) for s, i in n.inputs]
            out0 = get(n, 0)
            op = n.op
            w = f"at {n.name!r} ({op})"
            try:
                if op in _ELEMWISE_BINARY_OPS and len(n.inputs) == 2:
                    m = _unify_dims(_unify_dims(ins[0], ins[1], w), out0, w)
                    changed |= put(*n.inputs[0], m, w)
                    changed |= put(*n.inputs[1], m, w)
                    changed |= put(n, 0, m, w)
                elif op in _BROADCAST_BINARY_OPS and len(n.inputs) == 2:
                    # output rank = max input rank — only deducible when
                    # both input ranks are known, or pinned by the output
                    if out0 is not None:
                        r = len(out0)
                    elif ins[0] is not None and ins[1] is not None:
                        r = max(len(ins[0]), len(ins[1]))
                    else:
                        continue

                    def aligned(p):
                        # right-align; absent leading dims behave as 1
                        if p is None:
                            return [None] * r
                        return [1] * (r - len(p)) + list(p)

                    a, b, o = aligned(ins[0]), aligned(ins[1]), \
                        aligned(out0)
                    new_a, new_b, new_o = list(a), list(b), list(o)
                    for d in range(r):
                        cand = {v for v in (a[d], b[d]) if v and v != 1}
                        if len(cand) > 1:
                            raise MXNetError(
                                f"infer_shape: broadcast mismatch "
                                f"{ins[0]} vs {ins[1]} {w}")
                        if cand:
                            new_o[d] = _unify_dims([o[d]],
                                                   [cand.pop()], w)[0]
                        elif a[d] == 1 and b[d] == 1:
                            new_o[d] = _unify_dims([o[d]], [1], w)[0]
                        # optimistic backward fill: unknown input dim
                        # takes the output dim (assume non-broadcast)
                        if new_o[d]:
                            if a[d] is None:
                                new_a[d] = new_o[d]
                            if b[d] is None:
                                new_b[d] = new_o[d]
                    if ins[0] is not None:
                        changed |= put(*n.inputs[0],
                                       new_a[r - len(ins[0]):], w)
                    if ins[1] is not None:
                        changed |= put(*n.inputs[1],
                                       new_b[r - len(ins[1]):], w)
                    changed |= put(n, 0, new_o, w)
                elif op in _SHAPE_PRESERVING_OPS and n.inputs:
                    m = _unify_dims(ins[0], out0, w)
                    changed |= put(*n.inputs[0], m, w)
                    changed |= put(n, 0, m, w)
                elif op == "FullyConnected" and \
                        n.attrs.get("flatten", True) in (True, "True", 1):
                    # flatten=False keeps leading dims — rank unknown
                    # here, so that variant stays with the forward engine
                    nh = int(n.attrs.get("num_hidden", 0))
                    data = ins[0]
                    o = _unify_dims(out0, [None, nh], w)
                    if data is not None and len(data) == 2:
                        o = _unify_dims(o, [data[0], nh], w)
                        changed |= put(*n.inputs[0], [o[0], data[1]], w)
                        if len(n.inputs) > 1 and data[1]:
                            changed |= put(*n.inputs[1], [nh, data[1]], w)
                    changed |= put(n, 0, o, w)
                elif op == "Convolution":
                    kern = tuple(n.attrs.get("kernel", ()) or ())
                    rank = len(kern)
                    if rank and ins[0] is not None \
                            and len(ins[0]) == rank + 2:
                        stride = tuple(n.attrs.get("stride", ()) or
                                       (1,) * rank)
                        pad = tuple(n.attrs.get("pad", ()) or (0,) * rank)
                        dil = tuple(n.attrs.get("dilate", ()) or
                                    (1,) * rank)
                        nf = int(n.attrs.get("num_filter", 0))
                        # channel/spatial axis positions flip for NHWC
                        nhwc = n.attrs.get("layout") == "NHWC" and rank == 2
                        sp0, c_ax = (1, rank + 1) if nhwc else (2, 1)
                        data = list(ins[0])
                        o = out0 or [None] * (rank + 2)
                        hint = [None] * (rank + 2)
                        hint[0], hint[c_ax] = data[0], nf
                        o = _unify_dims(o, hint, w)
                        for d in range(rank):
                            ke = dil[d] * (kern[d] - 1) + 1
                            if data[sp0 + d]:
                                o[sp0 + d] = (data[sp0 + d] + 2 * pad[d]
                                              - ke) // stride[d] + 1
                            elif o[sp0 + d]:
                                data[sp0 + d] = ((o[sp0 + d] - 1) * stride[d]
                                                 - 2 * pad[d] + ke)
                        data[0] = o[0]
                        changed |= put(*n.inputs[0], data, w)
                        changed |= put(n, 0, o, w)
                elif op in ("Concat", "concat"):
                    dim = int(n.attrs.get("dim", 1))
                    parts = [get(s, i) for s, i in n.inputs]
                    rank = next((len(p) for p in parts + [out0]
                                 if p is not None), None)
                    if rank is not None:
                        dim %= rank
                        # unify non-concat dims across all parts + output
                        base = [None] * rank
                        for p in parts + [out0]:
                            if p is None:
                                continue
                            for d in range(rank):
                                if d != dim and p[d]:
                                    base[d] = _unify_dims(
                                        [base[d]], [p[d]], w)[0]
                        tot = 0
                        missing = []
                        for j, p in enumerate(parts):
                            if p is not None and p[dim]:
                                tot += p[dim]
                            else:
                                missing.append(j)
                        o = list(base)
                        o[dim] = tot if not missing else (
                            out0[dim] if out0 and out0[dim] else None)
                        changed |= put(n, 0, o, w)
                        if out0 and out0[dim] and len(missing) == 1:
                            j = missing[0]
                            rem = out0[dim] - tot
                            if rem <= 0:
                                raise MXNetError(
                                    f"infer_shape: concat parts sum to "
                                    f"{tot} but output dim is "
                                    f"{out0[dim]} {w}")
                            fill = list(base)
                            fill[dim] = rem
                            changed |= put(*n.inputs[j], fill, w)
                        for j, p in enumerate(parts):
                            fill = list(base)
                            fill[dim] = p[dim] if p and p[dim] else None
                            changed |= put(*n.inputs[j], fill, w)
                elif op in ("SliceChannel", "split"):
                    num = int(n.attrs.get("num_outputs", 1))
                    axis = int(n.attrs.get("axis", 1))
                    squeeze = bool(n.attrs.get("squeeze_axis", False))
                    data = ins[0]
                    nouts = node_num_outputs(n)
                    if axis < 0:
                        # normalize against the INPUT rank (outputs are one
                        # dim shorter when squeezing)
                        in_rank = len(data) if data is not None else next(
                            (len(get(n, i)) + (1 if squeeze else 0)
                             for i in range(nouts)
                             if get(n, i) is not None), None)
                        if in_rank is None:
                            continue
                        axis %= in_rank
                    for i in range(nouts):
                        oi = get(n, i)
                        if oi is None and data is None:
                            continue
                        if data is not None:
                            exp = list(data)
                            exp[axis] = (data[axis] // num
                                         if data[axis] else None)
                            if squeeze:
                                exp = exp[:axis] + exp[axis + 1:]
                            changed |= put(n, i, exp, w)
                        if oi is not None:
                            if squeeze:
                                back = (list(oi[:axis]) + [num]
                                        + list(oi[axis:]))
                            else:
                                back = list(oi)
                                back[axis] = (oi[axis] * num
                                              if oi[axis] else None)
                            changed |= put(*n.inputs[0], back, w)
                else:
                    # generic forward: all inputs complete -> exact eval
                    if generic_eval and ins and not complete(out0) and \
                            all(complete(p) for p in ins):
                        opdef = _reg.get(op)
                        specs = [jax.ShapeDtypeStruct(tuple(p),
                                                      np.float32)
                                 for p in ins]
                        outs = _eval_node_shape(n, opdef, specs)
                        for i, sds in enumerate(outs):
                            changed |= put(n, i, list(sds.shape), w)
            except MXNetError:
                raise
            except Exception:
                continue
        if not changed:
            break


def _infer_graph_shapes(sym: Symbol, known_shapes: Dict[str, tuple],
                        known_dtypes: Dict[str, np.dtype],
                        shapes_optional=False, dummy_shapes=False):
    """Forward abstract interpretation with parameter-shape back-fill.

    Returns (shapes, dtypes) dicts keyed by variable name, plus
    ``"__outputs__"`` entries listing per-head results.
    """
    nodes = _topo_sort(sym._expanded_heads())
    default_dtype = np.dtype("float32")
    var_shape: Dict[int, Optional[tuple]] = {}
    var_dtype: Dict[int, np.dtype] = {}
    val: Dict[Tuple[int, int], jax.ShapeDtypeStruct] = {}

    partial_pat: Dict[int, list] = {}
    has_partial = False
    for n in nodes:
        if n.is_variable:
            shp = known_shapes.get(n.name)
            if shp is None and "__shape__" in n._user_attrs:
                shp = _parse_attr(n._user_attrs["__shape__"])
            if shp is None and dummy_shapes:
                shp = (1,)  # dtype-only inference: shapes are throwaway
            if shp is not None and any(not d for d in shp):
                # 0 = unknown dim (MXNet convention): resolve via the
                # bidirectional pre-pass below, not as a literal 0-size
                partial_pat[id(n)] = [d if d else None for d in shp]
                shp = None
                has_partial = True
            elif shp is not None:
                partial_pat[id(n)] = list(shp)
            var_shape[id(n)] = tuple(shp) if shp else None
            dt = known_dtypes.get(n.name)
            if dt is None and "__dtype__" in n._user_attrs:
                dt = np.dtype(n._user_attrs["__dtype__"])
            var_dtype[id(n)] = dt or default_dtype

    if not dummy_shapes:
        # always: resolves 0-dim unknowns bidirectionally AND validates
        # caller-supplied shapes against op constraints (the reference's
        # InferShape CHECKs, e.g. FC weight vs num_hidden)
        _partial_prepass(nodes, partial_pat, generic_eval=has_partial)
        # adopt anything the bidirectional pass fully resolved — including
        # variables that had NO shape hint at all (e.g. an FC weight pinned
        # purely by backward constraints)
        for n in nodes:
            if n.is_variable and var_shape.get(id(n)) is None:
                p = partial_pat.get(id(n))
                if p is not None and all(d for d in p):
                    var_shape[id(n)] = tuple(p)

    for n in nodes:
        if n.is_variable:
            if var_shape[id(n)] is not None:
                val[(id(n), 0)] = jax.ShapeDtypeStruct(
                    var_shape[id(n)], var_dtype[id(n)])
            continue
        opdef = _reg.get(n.op)
        # back-fill parameter shapes from data shapes
        infer_hook = PARAM_SHAPE_INFER.get(n.op)
        argmap = {}
        names = (opdef.arg_names or []) + (opdef.aux_names or [])
        skip = _skip_args(n.op, n.attrs)
        names = [a for a in names if a not in skip]
        for an, (src, idx) in zip(names, n.inputs):
            argmap[an] = (src, idx)
        if infer_hook:
            in_shapes = {an: val[(id(src), idx)].shape
                         for an, (src, idx) in argmap.items()
                         if (id(src), idx) in val}
            try:
                fills = infer_hook(n.attrs, in_shapes)
            except Exception:
                fills = {}
            for an, shp in fills.items():
                if an in argmap:
                    src, idx = argmap[an]
                    if src.is_variable and var_shape.get(id(src)) is None:
                        var_shape[id(src)] = tuple(shp)
                        val[(id(src), 0)] = jax.ShapeDtypeStruct(
                            tuple(shp), var_dtype.get(id(src), default_dtype))
        # elementwise mirroring: same-shape binary ops
        in_specs = []
        missing = []
        for src, idx in n.inputs:
            sds = val.get((id(src), idx))
            if sds is None:
                missing.append((src, idx))
            in_specs.append(sds)
        if missing:
            knowns = [s for s in in_specs if s is not None]
            if knowns and all(m[0].is_variable for m in missing):
                for src, idx in missing:
                    val[(id(src), idx)] = knowns[0]
                    var_shape[id(src)] = knowns[0].shape
                in_specs = [val[(id(src), idx)] for src, idx in n.inputs]
            elif shapes_optional:
                continue
            else:
                raise MXNetError(
                    f"infer_shape: insufficient information at node "
                    f"{n.name!r} ({n.op})")
        try:
            out_specs = _eval_node_shape(n, opdef, in_specs)
        except Exception:
            if shapes_optional:
                continue  # dtype-only mode with throwaway shapes
            raise
        for i, sds in enumerate(out_specs):
            val[(id(n), i)] = sds

    shapes = {"__outputs__": []}
    dtypes = {"__outputs__": []}
    for node in nodes:
        if node.is_variable:
            shapes[node.name] = var_shape.get(id(node))
            dtypes[node.name] = var_dtype.get(id(node), default_dtype)
    for hn, hi in sym._expanded_heads():
        sds = val.get((id(hn), hi))
        shapes["__outputs__"].append(tuple(sds.shape)
                                     if sds is not None else None)
        dtypes["__outputs__"].append(np.dtype(str(sds.dtype))
                                     if sds is not None else default_dtype)
    return shapes, dtypes


def _eval_node_shape(n: Node, opdef: _reg.OpDef, in_specs):
    import jax.random as jrandom
    attrs = dict(n.attrs)
    kwargs = dict(attrs)
    if opdef.takes_is_train:
        kwargs["is_train"] = True

    def f(*vals):
        if opdef.needs_rng:
            out = opdef.fn(jrandom.PRNGKey(0), *vals, **kwargs)
        else:
            out = opdef.fn(*vals, **kwargs)
        return out if isinstance(out, (tuple, list)) else (out,)

    out = jax.eval_shape(f, *in_specs)
    return list(out)[:node_num_outputs(n)]


def zeros(shape, dtype="float32", **kw):
    if isinstance(shape, numbers.Integral):
        shape = (shape,)
    return _compose("_zeros", [], {"shape": tuple(shape),
                                   "dtype": np.dtype(dtype).name},
                    kw.get("name"))


def ones(shape, dtype="float32", **kw):
    if isinstance(shape, numbers.Integral):
        shape = (shape,)
    return _compose("_ones", [], {"shape": tuple(shape),
                                  "dtype": np.dtype(dtype).name},
                    kw.get("name"))


def arange(start, stop=None, step=1.0, repeat=1, name=None, dtype="float32"):
    return _compose("_arange", [], {"start": start, "stop": stop,
                                    "step": step, "repeat": repeat,
                                    "dtype": np.dtype(dtype).name}, name)
