"""mx.sym.random — symbolic sampling namespace
(reference: python/mxnet/symbol/random.py, the symbol mirror of
ndarray/random.py over random/sample_op.cc).

Each helper composes the SAME registered op as its ``mx.nd.random``
twin (scalar-parameter ``_random_*`` or tensor-parameter ``_sample_*``),
so a graph built here and an imperative call see identical numerics.
"""
from __future__ import annotations

from .symbol import Symbol
from . import register as _register  # noqa: F401  (ops injected at pkg init)


def _op(name):
    from .. import symbol as _sym
    f = getattr(_sym, name, None)
    if f is None:
        raise AttributeError(f"symbol op {name!r} not registered")
    return f


def _both_symbol(a, b, fname):
    """Tensor-parameter path requires BOTH params symbolic — a mixed
    scalar/Symbol call would silently drop the Symbol into an unused
    kwarg of the scalar op (the reference's _random_helper raises the
    same way, symbol/random.py)."""
    sa, sb = isinstance(a, Symbol), isinstance(b, Symbol)
    if sa != sb:
        raise ValueError(
            f"mx.sym.random.{fname}: distribution parameters must be "
            "both Symbols or both numbers; wrap the scalar, e.g. "
            "mx.sym.zeros(shape) + value")
    return sa


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", **kw):
    if _both_symbol(low, high, "uniform"):
        return _op("_sample_uniform")(low, high, shape=shape or (),
                                      dtype=dtype, **kw)
    return _op("_random_uniform")(low=low, high=high, shape=shape or (),
                                  dtype=dtype, **kw)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", **kw):
    if _both_symbol(loc, scale, "normal"):
        return _op("_sample_normal")(loc, scale, shape=shape or (),
                                     dtype=dtype, **kw)
    return _op("_random_normal")(loc=loc, scale=scale, shape=shape or (),
                                 dtype=dtype, **kw)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", **kw):
    if _both_symbol(alpha, beta, "gamma"):
        return _op("_sample_gamma")(alpha, beta, shape=shape or (),
                                    dtype=dtype, **kw)
    return _op("_random_gamma")(alpha=alpha, beta=beta, shape=shape or (),
                                dtype=dtype, **kw)


def exponential(scale=1.0, shape=None, dtype="float32", **kw):
    if isinstance(scale, Symbol):   # single-parameter family: no mix risk
        return _op("_sample_exponential")(1.0 / scale, shape=shape or (),
                                          dtype=dtype, **kw)
    return _op("_random_exponential")(lam=1.0 / scale, shape=shape or (),
                                      dtype=dtype, **kw)


def poisson(lam=1.0, shape=None, dtype="float32", **kw):
    if isinstance(lam, Symbol):
        return _op("_sample_poisson")(lam, shape=shape or (),
                                      dtype=dtype, **kw)
    return _op("_random_poisson")(lam=lam, shape=shape or (),
                                  dtype=dtype, **kw)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", **kw):
    # same surface as mx.nd.random.negative_binomial (scalar params only)
    return _op("_random_negative_binomial")(k=k, p=p, shape=shape or (),
                                            dtype=dtype, **kw)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None,
                                  dtype="float32", **kw):
    return _op("_random_generalized_negative_binomial")(
        mu=mu, alpha=alpha, shape=shape or (), dtype=dtype, **kw)


def randint(low, high, shape=None, dtype="int32", **kw):
    return _op("_random_randint")(low=low, high=high, shape=shape or (),
                                  dtype=dtype, **kw)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kw):
    return _op("_sample_multinomial")(data, shape=shape or (),
                                      get_prob=get_prob, dtype=dtype, **kw)


def shuffle(data, **kw):
    return _op("_shuffle")(data, **kw)
