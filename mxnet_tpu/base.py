"""Core plumbing shared by every layer of mxnet_tpu.

TPU-native re-imagination of the reference's dmlc-core utilities
(reference: include/mxnet/base.h, dmlc GetEnv / logging / registry).  There is
no C ABI boundary here — the "C API" layer of the reference
(include/mxnet/c_api.h) is subsumed by Python-native classes; a thin stable
ABI can be added later for non-Python bindings.
"""
from __future__ import annotations

import ast
import os
import threading
from typing import Any, Callable, Dict, Optional

__version__ = "0.12.0.tpu0"

# The reference's dtype zoo includes float64 (mshadow DType switch); JAX
# disables 64-bit types by default.  Enable x64 so mx.nd arrays honor
# requested dtypes — defaults stay float32 because every creation path in
# this package passes an explicit dtype.
import jax as _jax  # noqa: E402
_jax.config.update("jax_enable_x64", True)


class MXNetError(RuntimeError):
    """Default error raised by mxnet_tpu (mirrors mxnet.base.MXNetError)."""


# ---------------------------------------------------------------------------
# Runtime flag registry (reference: dmlc::GetEnv call sites, SURVEY.md §5.6).
# Every env flag the framework consults is declared here with a type and a
# default so `mxnet_tpu.base.list_env_flags()` is self-documenting.
# ---------------------------------------------------------------------------
_ENV_FLAGS: Dict[str, tuple] = {}

# Tune metadata sidecar (mxnet_tpu.autotune): knobs that additionally
# carry a search-space description.  Kept out of the _ENV_FLAGS tuple so
# every existing (typ, default, doc) unpacker stays valid.
_ENV_TUNE: Dict[str, dict] = {}


def _validate_tune(name: str, typ: type, tune: dict) -> dict:
    """Normalize/validate declare_env tune metadata.  Two shapes:
    ``{"choices": [...]}`` (ordered candidate values, any typ) or
    ``{"min": lo, "max": hi[, "log": True]}`` (numeric range)."""
    if not isinstance(tune, dict):
        raise MXNetError("declare_env(%s): tune metadata must be a dict, "
                         "got %r" % (name, type(tune).__name__))
    unknown = set(tune) - {"choices", "min", "max", "log"}
    if unknown:
        raise MXNetError("declare_env(%s): unknown tune keys %s"
                         % (name, sorted(unknown)))
    if "choices" in tune:
        choices = list(tune["choices"])
        if not choices:
            raise MXNetError("declare_env(%s): empty tune choices" % name)
        if "min" in tune or "max" in tune:
            raise MXNetError("declare_env(%s): tune metadata is choices "
                             "OR a min/max range, not both" % name)
        return {"kind": "choice", "choices": choices}
    if "min" not in tune or "max" not in tune:
        raise MXNetError("declare_env(%s): tune metadata needs either "
                         "'choices' or both 'min' and 'max'" % name)
    if typ not in (int, float):
        raise MXNetError("declare_env(%s): min/max tune ranges require "
                         "an int or float knob, got %s"
                         % (name, typ.__name__))
    lo, hi = typ(tune["min"]), typ(tune["max"])
    if not lo < hi:
        raise MXNetError("declare_env(%s): tune range needs min < max, "
                         "got [%r, %r]" % (name, lo, hi))
    log = bool(tune.get("log", False))
    if log and lo <= 0:
        raise MXNetError("declare_env(%s): log-scale tune range needs "
                         "min > 0" % name)
    return {"kind": typ.__name__, "min": lo, "max": hi, "log": log}


def declare_env(name: str, typ: type, default, doc: str = "",
                tune: Optional[dict] = None) -> None:
    if tune is not None:
        _ENV_TUNE[name] = _validate_tune(name, typ, tune)
    _ENV_FLAGS[name] = (typ, default, doc)


def env(name: str, default=None):
    """Typed environment-variable lookup (reference: dmlc::GetEnv)."""
    if name in _ENV_FLAGS:
        typ, declared_default, _ = _ENV_FLAGS[name]
        if default is None:
            default = declared_default
    else:
        typ = type(default) if default is not None else str
    raw = os.environ.get(name)
    if raw is None:
        return default
    if typ is bool:
        return raw.lower() not in ("0", "false", "off", "")
    try:
        return typ(raw)
    except (TypeError, ValueError):
        return default


def list_env_flags() -> Dict[str, tuple]:
    return dict(_ENV_FLAGS)


def list_env_tunables() -> Dict[str, dict]:
    """Knobs that declared a search space (``declare_env(..., tune=)``).
    The ONLY source mxnet_tpu.autotune derives axes from — an undeclared
    knob can never be tuned."""
    return {name: dict(meta) for name, meta in _ENV_TUNE.items()}


# The runtime flags carried over from the reference that still make sense on
# TPU (SURVEY.md §5.6); CUDA/cuDNN-specific knobs intentionally dropped.
declare_env("MXNET_ENGINE_TYPE", str, "Async",
            "Async (default, jit-dispatch) or Naive (block after every op)")
declare_env("MXNET_EXEC_BULK_EXEC_TRAIN", bool, True,
            "fuse fwd+bwd(+update) into one XLA program in Module")
declare_env("MXNET_EXEC_BULK_EXEC_INFERENCE", bool, True,
            "jit whole forward graphs for inference")
declare_env("MXNET_BACKWARD_DO_MIRROR", bool, False,
            "rematerialise activations in backward (jax.checkpoint)")
declare_env("MXNET_REMAT_POLICY", str, "full",
            "what remat keeps: 'full' recomputes everything; "
            "'save_matmuls' keeps conv/FC/dot/MoE outputs and recomputes "
            "only the elementwise chains between them")


def tag_for_remat(x, name):
    """checkpoint_name, applied ONLY when the save_matmuls remat policy is
    active (trace-time env check, same read point as executor.maybe_mirror).
    The name primitive is semantically an identity, but it measurably
    hinders XLA/GSPMD optimization when present for no reason — a
    multi-process dp x tp transformer step ran ~50% slower with
    unconditional tags."""
    if not env("MXNET_BACKWARD_DO_MIRROR", False) \
            or os.environ.get("MXNET_REMAT_POLICY") != "save_matmuls":
        return x
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(x, name)
declare_env("MXNET_PROFILER_MODE", str, "symbolic_only",
            "initial profiler mode: symbolic_only (dispatch events) or "
            "all (every category); profiler_set_config overrides")
declare_env("MXNET_PROFILER_AUTOSTART", bool, False,
            "begin profiling at import (reference: engine profiler "
            "autostart)")
declare_env("MXNET_PROFILER_XLA_LOGDIR", str, "",
            "directory for the XLA (xplane) device trace profiler "
            "start()/stop() also drives; empty = host events only")
# -- cluster tracing (mxnet_tpu.tracing; docs/OBSERVABILITY.md) --------------
declare_env("MXNET_TRACE", bool, False,
            "master switch for Dapper-style span tracing: kvstore "
            "request envelopes carry (trace_id, parent span) so "
            "server-side handling becomes child spans of the worker-"
            "side call; off (default) adds ZERO envelope bytes and "
            "near-zero cost at every instrumentation site")
declare_env("MXNET_TRACE_DIR", str, "",
            "tracing: directory each process appends its span journal "
            "to (<role>-<rank>.trace.jsonl, fsync'd, torn-line "
            "tolerant); merge with tools/trace_merge.py --spans; "
            "empty = in-memory ring only")
declare_env("MXNET_TRACE_RING", int, 4096,
            "tracing: bounded in-memory span ring per process (the "
            "stats op and in-process tests read it; older spans fall "
            "off — the file journal is the durable record)")
declare_env("MXNET_TRACE_FLUSH_N", int, 32,
            "tracing: spans buffered between flush+fsync of the trace "
            "journal (a SIGKILL loses at most this many spans plus "
            "one torn line, which the reader skips)")
# -- cluster health (mxnet_tpu.health; docs/OBSERVABILITY.md) ----------------
declare_env("MXNET_HEALTH", bool, True,
            "master switch for the health layer: flight-recorder event "
            "ring, stall watchdogs and SLO status evaluation; 0 makes "
            "every entry point a no-op (status always OK, no monitor "
            "thread, no crash bundles)")
declare_env("MXNET_HEALTH_DIR", str, "",
            "health: directory the flight recorder dumps its fsync'd "
            "<role>-<rank>.crash.json bundle into on crashes, channel "
            "poison, watchdog trips, SIGTERM and exit — the postmortem "
            "evidence a SIGKILLed peer's survivors leave behind "
            "(tools/postmortem.py merges them); empty = in-memory ring "
            "only")
declare_env("MXNET_HEALTH_INTERVAL_S", float, 1.0,
            "health: watchdog monitor-thread poll interval (the thread "
            "starts lazily with the first registered wait or probe)")
declare_env("MXNET_HEALTH_EVENTS", int, 256,
            "health: bounded size of the flight recorder's typed-event "
            "ring (older events fall off; the crash bundle carries the "
            "whole ring)")
declare_env("MXNET_HEALTH_BARRIER_STALL_S", float, 30.0,
            "health: a barrier wait (worker rendezvous or server park) "
            "parked past this many seconds trips the barrier_stall "
            "watchdog; 0 disables the check")
declare_env("MXNET_HEALTH_WIRE_STALL_S", float, 30.0,
            "health: a kvstore wire wait (pull_async resolution) stuck "
            "past this many seconds with its round never completing "
            "trips the wire_stall watchdog; 0 disables the check")
declare_env("MXNET_HEALTH_RECOVERY_S", float, 5.0,
            "health: recovery hysteresis — after every bad condition "
            "clears, the status keeps reporting DEGRADED for this many "
            "seconds before returning to OK, so a flapping condition "
            "reads as one continuous degradation")
declare_env("MXNET_HEALTH_P99_MS", float, 0.0,
            "health SLO rule: serving.request p99 latency ceiling in "
            "ms — p99 above it degrades the node; 0 disables the rule")
declare_env("MXNET_HEALTH_OVERLAP_FLOOR", float, 0.0,
            "health SLO rule: wire overlap_pct floor for the fused "
            "dist driver — overlap below it (once >= 4 rounds have "
            "completed) degrades the node; 0 disables the rule")
declare_env("MXNET_HEALTH_FAILOVER_BUDGET_S", float, 0.0,
            "health SLO rule: coordinator failover_rebuild_s budget — "
            "a rebuild gauge above it degrades the node; 0 disables "
            "the rule")
declare_env("MXNET_HEALTH_QUEUE_SAT", float, 1.0,
            "health: serving queue-depth saturation fraction — a "
            "registered queue probe at or past this fraction of its "
            "limit trips the queue_saturated watchdog")
declare_env("MXNET_HEALTH_BUSY_STORM", int, 8,
            "health: BUSY-shed storm threshold — this many busy_shed "
            "events within MXNET_HEALTH_BUSY_WINDOW_S flip the replica "
            "to DEGRADED (recovering with hysteresis); 0 disables")
declare_env("MXNET_HEALTH_BUSY_WINDOW_S", float, 1.0,
            "health: sliding window (seconds) the BUSY-shed storm rule "
            "counts busy_shed events over")
declare_env("MXNET_HEALTH_STALE_S", float, 30.0,
            "health: staleness horizon for REMOTE health verdicts — a "
            "banked/beat-piggybacked health block whose wall-clock ts "
            "stamp is older than this many seconds no longer earns an "
            "OK (cluster_health and the serving fleet router floor it "
            "at DEGRADED: the last word of a corpse is forensics, not "
            "a live verdict); 0 disables the discount")
declare_env("MXNET_CPU_WORKER_NTHREADS", int, 4,
            "host worker threads for the data pipeline")
declare_env("MXNET_KVSTORE_BIGARRAY_BOUND", int, 1 << 19,
            "dist kvstore: arrays above this many elements stripe "
            "row-wise across all servers (per-stripe keys; parallel "
            "serialize/apply)")
declare_env("MXNET_KVSTORE_WINDOW", int, 8,
            "dist_async channel: max envelopes in flight per server "
            "connection (sliding-window pipeline; 1 = the old "
            "stop-and-wait loop bit for bit)",
            tune={"choices": [1, 2, 4, 8, 16, 32]})
declare_env("MXNET_KVSTORE_COMPRESSION", str, "",
            "gradient compression for dist pushes: ''/none, 2bit or "
            "fp16 (job-wide form of set_gradient_compression)",
            tune={"choices": ["", "fp16", "2bit"]})
declare_env("MXNET_KVSTORE_COMPRESSION_THRESHOLD", float, 0.5,
            "2bit quantization threshold t: gradient values quantize "
            "to {-t, 0, +t} with worker-side error feedback",
            tune={"min": 0.05, "max": 2.0, "log": True})
declare_env("MXNET_KVSTORE_COALESCE_BYTES", int, 16384,
            "LIST pushes coalesce same-server keys at or below this "
            "many payload bytes into one multi-key envelope",
            tune={"choices": [0, 4096, 16384, 65536, 262144]})
declare_env("MXNET_KVSTORE_CODEC", str, "auto",
            "dist kvstore wire codec: 'auto'/'binary' negotiate the "
            "registry-generated binary frame codec per connection at "
            "hello time (hot push/pull/predict envelopes serialize "
            "zero pickled bytes; old peers keep pickle), 'pickle' "
            "pins the legacy pickle framing — the mixed-version "
            "escape hatch",
            tune={"choices": ["auto", "binary", "pickle"]})
declare_env("MXNET_KVSTORE_SENDMSG", int, 1,
            "dist kvstore transport: 1 sends each frame with vectored "
            "socket.sendmsg scatter-gather (one syscall per frame, "
            "chunked at IOV_MAX); 0 falls back to per-buffer sendall",
            tune={"choices": [0, 1]})
declare_env("MXNET_KVSTORE_PICKLE_ALLOWLIST", str, "",
            "extra 'module' or 'module:name' entries (comma-separated) "
            "the wire unpickler admits — the custom-optimizer escape "
            "hatch (kvstore_server allowlist)")
declare_env("MXNET_KVSTORE_RETRY_MAX", int, 8,
            "dist_async channel: reconnect attempts per failure episode "
            "before the channel fails hard")
declare_env("MXNET_KVSTORE_RETRY_INITIAL_MS", int, 50,
            "dist_async channel: first reconnect backoff delay")
declare_env("MXNET_KVSTORE_RETRY_MAX_MS", int, 2000,
            "dist_async channel: backoff delay cap")
declare_env("MXNET_KVSTORE_RETRY_BACKOFF", float, 2.0,
            "dist_async channel: backoff multiplier per attempt")
declare_env("MXNET_KVSTORE_HEARTBEAT_INTERVAL", float, 5.0,
            "dist_async channel: seconds between liveness pings "
            "(0 disables the heartbeat)")
declare_env("MXNET_KVSTORE_HEARTBEAT_TIMEOUT", float, 15.0,
            "dist_async: silence past this marks a node dead "
            "(num_dead_nodes; server barrier failure naming the rank)")
declare_env("MXNET_KVSTORE_DEDUP_WINDOW", int, 8,
            "server: cached replies per client channel for idempotent "
            "replay acks after a reconnect (keep >= 2: a zombie "
            "connection can serve its last request late)")
declare_env("MXNET_KVSTORE_ELASTIC", bool, False,
            "dist_async elastic membership: servers/workers may join or "
            "leave mid-job — versioned roster on the slot-0 coordinator "
            "(with deterministic successor election when the "
            "coordinator itself dies), stripe-plan re-derivation + "
            "striped-state handoff on a roster bump, barriers "
            "renegotiate instead of failing (mxnet_tpu.membership; "
            "docs/ROBUSTNESS.md)")
declare_env("MXNET_KVSTORE_SNAPSHOT_S", float, 0.0,
            "elastic: seconds between each server's state-snapshot "
            "beats, fanned out to EVERY peer so the bank outlives any "
            "single server incl. the coordinator (the killed-server "
            "optimizer-state recovery source; 0 disables snapshots — "
            "weights still recover from the workers' quorum re-push)",
            tune={"choices": [0.0, 0.25, 1.0, 5.0]})
declare_env("MXNET_KVSTORE_ELASTIC_PUSH_LOG", int, 256,
            "elastic: per-worker cap on pushes remembered since each "
            "key's last pull, re-applied under the new layout when a "
            "server dies with them (older entries fall off: "
            "best-effort for barrier-free async jobs)")
declare_env("MXNET_KVSTORE_FUSED", bool, True,
            "dist_async: let run_steps/step_k drive update-on-kvstore "
            "training through the chunked K-step scan with the push/"
            "pull wire overlapped behind the next chunk's compute "
            "(docs/PERF_NOTES.md round 10); 0 restores the eager "
            "per-step dist loop.  Elastic jobs "
            "(MXNET_KVSTORE_ELASTIC) ride it too: an in-flight "
            "pull_async handle replans against the post-bump stripe "
            "layout (docs/ROBUSTNESS.md replan contract)")
declare_env("MXNET_KVSTORE_FUSED_CHUNK", int, 8,
            "fused-dist driver: scanned steps per chunk — one host "
            "dispatch and one push/pull wire round per chunk; larger "
            "chunks amortize dispatch further but widen the window of "
            "local (worker-replica) weight evolution between server "
            "sync points.  A K not divisible by the chunk compiles the "
            "tail chunk as its own XLA program — size K in multiples "
            "to pay exactly one compile",
            tune={"choices": [1, 2, 4, 8, 16, 32]})
declare_env("MXNET_KVSTORE_FUSED_STALENESS", int, 1,
            "fused-dist driver: exactly how many chunk boundaries the "
            "adopted server weights lag — chunk j always starts from "
            "the pull issued after chunk j-1-S's pushes (deterministic, "
            "so goldens are simulable).  0 degrades to a barrier'd "
            "chunk boundary (no overlap) that single-worker matches the "
            "eager dist loop bit-for-bit; 1 (default) hides the wire "
            "behind one chunk of compute — async-SGD-grade staleness, "
            "same class as the elastic handoff contract",
            tune={"choices": [0, 1, 2]})
declare_env("MXNET_KVSTORE_HIERARCHY", bool, False,
            "dist_async: hierarchical reduction tier — workers sharing "
            "a host (membership.host_groups over the launch topology) "
            "allreduce gradients in-mesh "
            "(parallel.mesh.local_allreduce_sum: ICI when the devices "
            "allow) and only the per-host leader ships the reduced "
            "gradient over the TCP wire, fanning pulled weights back "
            "in-mesh; wire bytes per step drop by ~the workers-per-"
            "host factor (docs/PERF_NOTES.md round 11).  Needs "
            "MXNET_KVSTORE_WORKERS_PER_HOST and MXT_MESH_URIS (both "
            "set by tools/launch.py --workers-per-host); static "
            "rosters only",
            tune={"choices": [0, 1]})
declare_env("MXNET_KVSTORE_WORKERS_PER_HOST", int, 0,
            "hierarchical kvstore tier: worker ranks per host — "
            "consecutive ranks group (launchers fill host slots in "
            "order), lowest rank leads.  0 means no topology is "
            "known: MXNET_KVSTORE_HIERARCHY=1 then refuses loudly "
            "instead of guessing a mesh that crosses hosts")
declare_env("MXNET_KVSTORE_MESH_FANIN_S", float, 120.0,
            "hierarchical kvstore tier: seconds the host-group leader "
            "waits for every follower's contribution to a push round "
            "(and a follower's collect waits for the leader's wire "
            "round) before failing loudly — the fan-in watchdog that "
            "turns a dead group member into a NAMED error (missing "
            "ranks + last-heard ages, plus a flight-recorder event) "
            "instead of a silent hang (the wait is also "
            "health-registered)")
declare_env("MXNET_KVSTORE_MESH_ACCEPTORS", int, 8,
            "hierarchical kvstore tier: serve threads in the leader's "
            "mesh fan-in pool — follower connections spread across "
            "them so W followers' push frames decode CONCURRENTLY "
            "instead of serializing through one recv loop (reduction "
            "itself stays single-threaded at the local_allreduce_sum "
            "barrier); 1 restores the serialized single-acceptor "
            "drain, values past the follower count change nothing",
            tune={"choices": [1, 2, 4, 8, 16]})
declare_env("MXNET_KVSTORE_SHM", str, "auto",
            "hierarchical kvstore tier: same-host shared-memory lane "
            "for follower<->leader mesh frames (mxnet_tpu/shmlane.py; "
            "negotiated per connection by the shm_hello wire op) — "
            "'auto' tries it when the mesh endpoint is a local "
            "address, 'on'/'1' always tries, 'off'/'0' never; segment "
            "creation or cross-host attach failures fall back to the "
            "TCP loopback path per connection.  Lane bytes land in "
            "the shm_* counter family (profiler.shm_bytes_total) with "
            "ZERO socket syscalls behind them; the socket's ici_* "
            "drops to control traffic",
            tune={"choices": ["auto", "on", "off"]})
declare_env("MXNET_KVSTORE_SHM_RING_KB", int, 4096,
            "shm lane: ring capacity per direction in KiB — a frame "
            "larger than the ring rides the TCP path for that round "
            "(safe: mesh channels run a one-envelope window, so no "
            "reordering is possible)",
            tune={"choices": [256, 1024, 4096, 16384]})
declare_env("MXNET_KVSTORE_SHM_STALL_S", float, 5.0,
            "shm lane: seconds a pushed request may sit unconsumed in "
            "the ring before the follower declares the lane wedged, "
            "marks it dead and fails over to TCP via the channel's "
            "ordinary reconnect-and-replay (exactly-once via the "
            "leader's dedup window)")
declare_env("MXNET_KVSTORE_SPARSE", bool, True,
            "dist_async: ship row-sparse gradients (RowSparseNDArray "
            "pushes, e.g. embedding tables under sparse_grad) as "
            "RowSparsePayload wire values — only the touched rows plus "
            "8 bytes per row id travel, cutting push bytes by roughly "
            "the touch density (docs/PERF_NOTES.md round 14); 0 "
            "densifies at the push boundary (the pre-PR-19 wire "
            "format, every byte dense)",
            tune={"choices": [0, 1]})
declare_env("MXNET_KVSTORE_SPARSE_DENSITY_CUTOVER", float, 0.5,
            "dist_async sparse wire: touch-density threshold above "
            "which a row-sparse push goes DENSE instead — past ~50% "
            "touched rows the 8-bytes-per-id index overhead plus the "
            "gather outweighs the skipped rows, and the dense path's "
            "2-bit quantization packs tighter per element; 1.0 keeps "
            "every sparse push sparse, 0.0 densifies all",
            tune={"min": 0.05, "max": 1.0, "log": True})
# -- serving tier (mxnet_tpu.serving) ---------------------------------------
declare_env("MXNET_SERVING_BUCKETS", str, "1,2,4,8,16,32",
            "serving: comma-separated batch-size buckets the replica "
            "pre-compiles predict executables for (requests pad to the "
            "smallest covering bucket — N requests never mean N compiles)",
            tune={"choices": ["1,2,4,8,16,32", "1,4,16,64",
                              "8,16,32,64", "1,8,64"]})
declare_env("MXNET_SERVING_MAX_WAIT_MS", float, 2.0,
            "serving: dynamic batcher max wait for more requests before "
            "dispatching a partially-filled bucket (the latency half of "
            "the batching SLO dial; 0 dispatches immediately)",
            tune={"choices": [0.0, 0.5, 2.0, 5.0]})
declare_env("MXNET_SERVING_QUEUE_DEPTH", int, 256,
            "serving: admission control — requests queued past this "
            "depth are shed with a typed BUSY reply instead of growing "
            "an unbounded queue",
            tune={"choices": [64, 256, 1024]})
declare_env("MXNET_SERVING_REFRESH_S", float, 0.0,
            "serving: seconds between weight-version polls against the "
            "live dist_async parameter servers (0 disables polling; the "
            "serving_refresh envelope forces a check either way)")
declare_env("MXNET_SERVING_CLIENT_WINDOW", int, 64,
            "serving: max in-flight predict envelopes per client "
            "connection (the serving override of MXNET_KVSTORE_WINDOW — "
            "the replica's pipelined loop batches across the window)",
            tune={"choices": [16, 64, 256]})
declare_env("MXNET_SERVING_LATENCY_WINDOW", int, 2048,
            "serving: ring size of the profiler's per-kind latency "
            "sample window (p50/p99/QPS are computed over this window; "
            "count/total stay lifetime)")
# -- serving fleet (mxnet_tpu.serving.fleet; docs/SERVING.md) ----------------
declare_env("MXNET_SERVING_FLEET_RETRIES", int, 3,
            "serving fleet: per-request retry budget — after the first "
            "attempt, at most this many more replicas are tried on "
            "BusyError / connection failure / reply timeout (predict is "
            "pure, so a cross-replica retry can never double-apply)",
            tune={"choices": [1, 3, 6]})
declare_env("MXNET_SERVING_FLEET_DEADLINE_S", float, 30.0,
            "serving fleet: per-request wall deadline — routing, "
            "backoff sleeps and retries all stop here and the LAST "
            "error surfaces, naming every attempted replica")
declare_env("MXNET_SERVING_FLEET_ATTEMPT_S", float, 5.0,
            "serving fleet: per-attempt reply timeout — a replica that "
            "accepted the request but never answers (gray failure / "
            "blackhole) is abandoned after this many seconds and the "
            "request retries on a different replica")
declare_env("MXNET_SERVING_FLEET_BACKOFF_MS", float, 10.0,
            "serving fleet: initial retry backoff; doubles per retry "
            "up to MXNET_SERVING_FLEET_BACKOFF_MAX_MS")
declare_env("MXNET_SERVING_FLEET_BACKOFF_MAX_MS", float, 500.0,
            "serving fleet: retry backoff cap")
declare_env("MXNET_SERVING_FLEET_JITTER", float, 0.5,
            "serving fleet: jitter fraction on each backoff sleep "
            "(delay * (1 +/- jitter*U) — decorrelates a thundering "
            "retry herd); 0 = the pinned deterministic schedule the "
            "backoff tests assert")
declare_env("MXNET_SERVING_FLEET_STATS_S", float, 1.0,
            "serving fleet: scoreboard poll interval — each tick asks "
            "every replica for serving_stats (health verdict, queue "
            "depth, draining flag) and re-probes quarantined replicas; "
            "0 = no background thread, poll_once() only")
declare_env("MXNET_SERVING_FLEET_DEGRADED_PENALTY", float, 4.0,
            "serving fleet: load multiplier applied to a DEGRADED "
            "replica in weighted-least-loaded routing (it still "
            "serves, just proportionally less; CRITICAL/dead/draining "
            "replicas are excluded outright)",
            tune={"choices": [2.0, 4.0, 8.0]})
declare_env("MXNET_SERVING_FLEET_CANARY_FRACTION", float, 0.1,
            "serving fleet: fraction of requests routed to the canary "
            "cohort while a canary is active")
declare_env("MXNET_SERVING_FLEET_CANARY_MIN_N", int, 32,
            "serving fleet: minimum completed requests in BOTH cohorts "
            "before the canary SLO comparison may trigger a rollback")
declare_env("MXNET_SERVING_FLEET_CANARY_P99_X", float, 2.0,
            "serving fleet: canary p99 regression factor — canary p99 "
            "above baseline p99 times this rolls the canary back")
declare_env("MXNET_SERVING_FLEET_CANARY_ERR_X", float, 2.0,
            "serving fleet: canary error-rate regression factor — "
            "canary error rate above baseline rate times this (plus a "
            "1% absolute floor) rolls the canary back")
declare_env("MXNET_CKPT_RENDEZVOUS_TIMEOUT", float, 600.0,
            "async checkpoint: seconds rank 0 waits for every rank's "
            "shard (and ranks wait for the index) before failing")
declare_env("MXNET_DEFAULT_DTYPE", str, "float32",
            "default real dtype; set bfloat16 for TPU-preferred training")
declare_env("MXNET_ZERO_STAGE", int, 0,
            "ZeRO optimizer-state sharding over the dp mesh axis: 0 off, "
            "1 = shard optimizer states + fp32 master weights (Module "
            "zero_stage kwarg overrides)")
declare_env("MXNET_DEVICE_METRICS", bool, True,
            "device-resident metric accumulation in the training/eval "
            "loops (EvalMetric.device_update + lazy sync); 0 restores "
            "the classic one-host-readback-per-batch metric path")
declare_env("MXNET_SCAN_CACHE_MAX", int, 32,
            "max compiled K-step scan programs retained per "
            "Module/Trainer (LRU; executor.scan_cache_store)")
declare_env("MXNET_PREDICT_READBACK_BATCHES", int, 64,
            "predict readback chunk: batches fetched per stacked "
            "device_get (bounds device memory held by the stacked "
            "readback; module.base_module.chunked_device_get)")
declare_env("MXNET_FUSED_DONATE", bool, True,
            "donate param/aux/opt-state buffers to the fused training "
            "step so XLA updates them in place in HBM")
declare_env("MXNET_ATTENTION_IMPL", str, "auto",
            "attention kernel dispatch: flash (Pallas), xla (fused "
            "jnp) or auto (the measured winner table decides)")
# Deterministic fault injection (mxnet_tpu.faultinject) — the env forms
# of configure(), for reaching into launcher-spawned worker processes.
declare_env("MXNET_FI_KILL_POINT", str, "before_send",
            "fault injection: where the one-shot connection kill fires "
            "(before_send / after_send / on_recv)")
declare_env("MXNET_FI_KILL_AFTER", int, None,
            "fault injection: sever the client connection at exactly "
            "this 1-based data-channel message count (unset = off)")
declare_env("MXNET_FI_KILL_UNACKED", int, None,
            "fault injection: sever the connection the moment this "
            "many pipelined envelopes are unacked (unset = off)")
declare_env("MXNET_FI_REFUSE_CONNECTS", int, 0,
            "fault injection: refuse the next N client connect "
            "attempts")
declare_env("MXNET_FI_REFUSE_ACCEPTS", int, 0,
            "fault injection: close the next N accepted server "
            "connections immediately")
declare_env("MXNET_FI_DELAY_ACK_MS", float, 0.0,
            "fault injection: delay every server data-channel reply "
            "by this many ms (heartbeats exempt)")
declare_env("MXNET_FI_ONLY_RANK", int, None,
            "fault injection: restrict the armed plan to this "
            "DMLC_WORKER_ID (unset = all ranks)")
declare_env("MXNET_FI_KILL_PROCESS_AFTER", int, None,
            "fault injection: SIGKILL this process after serving "
            "exactly this many enveloped data-channel replies — real "
            "process death for elastic-membership tests (unset = off)")
declare_env("MXNET_FI_ONLY_SERVER", int, None,
            "fault injection: restrict the process-kill plan to this "
            "DMLC_SERVER_ID (unset = all servers)")
declare_env("MXNET_FI_ONLY_COORDINATOR", bool, False,
            "fault injection: restrict the process-kill plans to the "
            "process CURRENTLY holding the elastic roster coordinator "
            "role (kvstore_server keeps the flag current across "
            "failovers; composes with MXNET_FI_ONLY_SERVER and the "
            "KILL_PROCESS_AFTER / KILL_ON_BEAT_SEQ kill points)")
declare_env("MXNET_FI_STALL_BARRIER_MS", float, 0.0,
            "fault injection: delay the server's handling of the NEXT "
            "barrier arrival by this many ms before it registers — a "
            "deterministic one-shot barrier wedge (every other rank's "
            "park and the delayed rank's reply stretch by exactly this "
            "long), the CPU-testable stall the mxnet_tpu.health "
            "watchdog gates trip on (unset/0 = off)")
declare_env("MXNET_FI_KILL_ON_BEAT_SEQ", int, None,
            "fault injection: SIGKILL this process when its elastic "
            "beat loop sends beat number N — the deterministic beat-"
            "boundary kill point for coordinator-failover tests, where "
            "the enveloped-ack count is timing-dependent (unset = off)")
declare_env("MXNET_FI_BLACKHOLE_AFTER", int, None,
            "fault injection: serve exactly N enveloped data-channel "
            "replies normally, then SWALLOW every later one — the "
            "socket stays open, requests are still accepted and "
            "heartbeats still ack, but no reply ever arrives.  The "
            "gray-failure shape (a stalled-not-dead server) the "
            "serving fleet's reply timeouts must route around, where "
            "liveness alone says everything is fine (unset = off)")
declare_env("MXNET_FI_SHM_WEDGE_AFTER", int, None,
            "fault injection: the mesh leader drains exactly N shm-"
            "lane ring frames normally, then stops popping — requests "
            "pile up unconsumed, the wedged-drain shape the "
            "follower's MXNET_KVSTORE_SHM_STALL_S watchdog must turn "
            "into a clean TCP fallback with zero lost envelopes "
            "(composes with MXNET_FI_ONLY_RANK; unset = off)")
# -- bench-script knobs (bench.py / benchmark/*) -----------------------------
# Read by the repo-level bench scripts, which sit OUTSIDE the linted
# package — declared here anyway because registration is what makes a
# knob tunable: mxnet_tpu.autotune derives its search space exclusively
# from this registry (docs/AUTOTUNE.md), so an undeclared bench axis
# could never be swept.
declare_env("BENCH_BATCH", int, 256,
            "bench.py: training batch size (halved automatically on "
            "OOM; per-topology BENCH_DEFAULTS.json overrides the "
            "built-in default, env overrides both)",
            tune={"choices": [64, 128, 256, 512, 1024]})
declare_env("BENCH_DTYPE", str, "bfloat16",
            "bench.py: compute dtype for the fused step (bfloat16 = "
            "mixed precision with fp32 masters; float32 = full "
            "precision)",
            tune={"choices": ["bfloat16", "float32"]})
declare_env("BENCH_OPT", str, "sgd",
            "bench.py: optimizer driven through init_optimizer (lars "
            "exercises the large-batch trust-ratio recipe)",
            tune={"choices": ["sgd", "lars"]})
declare_env("BENCH_STEPS_PER_CALL", int, 1,
            "bench.py: training steps fused into ONE run_steps dispatch "
            "(lax.scan); K>1 amortizes the host dispatch through the "
            "tunnel to 1/K per step, 1 = classic per-step dispatch",
            tune={"choices": [1, 2, 4, 8, 16]})
declare_env("BENCH_STEM", str, "conv7",
            "bench.py: ResNet stem variant — conv7 (reference 7x7) or "
            "s2d (TPU-native space-to-depth, mathematically equivalent)",
            tune={"choices": ["conv7", "s2d"]})
declare_env("BENCH_LAYOUT", str, "nchw",
            "bench.py: activation layout — nchw (MXNet default) or "
            "nhwc (channels-last, the MLPerf-TPU ResNet convention)",
            tune={"choices": ["nchw", "nhwc"]})
declare_env("BENCH_REMAT", str, "0",
            "bench.py: rematerialization — 0 off, 1/full whole-step "
            "recompute, save_matmuls keeps conv/FC outputs and "
            "recomputes elementwise chains",
            tune={"choices": ["0", "1", "save_matmuls"]})
# -- autotune harness (mxnet_tpu.autotune) -----------------------------------
declare_env("MXNET_AUTOTUNE_TRIALS", int, 16,
            "autotune: measured trials per sweep invocation (the CLI "
            "--trials default; resume counts prior journaled trials "
            "toward nothing — this is trials THIS run)")
declare_env("MXNET_AUTOTUNE_SEED", int, 0,
            "autotune: RNG seed for proposal sampling — same journal + "
            "same seed reproduces the same proposal sequence exactly")
declare_env("MXNET_AUTOTUNE_EPSILON", float, 0.25,
            "autotune: epsilon-greedy exploration rate for the model "
            "searcher — fraction of proposals drawn uniformly from the "
            "space instead of argmax over the fitted cost model")
declare_env("MXNET_AUTOTUNE_STRATEGY", str, "model",
            "autotune: proposal strategy — model (fit-on-the-fly "
            "regressor + epsilon-greedy), random, or grid")
declare_env("MXNET_AUTOTUNE_TRIAL_TIMEOUT_S", float, 900.0,
            "autotune: hard deadline per measured trial — the "
            "subprocess executor SIGKILLs the config's whole process "
            "group at the deadline and records status=timeout "
            "(fresh_process_probe discipline: a hung trial can never "
            "serialize the sweep)")
declare_env("MXNET_AUTOTUNE_CANDIDATES", int, 64,
            "autotune: candidate pool size the model searcher scores "
            "per proposal (random samples + neighbors of the measured "
            "best)")
# -- interleaving explorer (mxnet_tpu.analysis.sched) ------------------------
declare_env("MXNET_SCHED_SCHEDULES", int, 20,
            "interleaving explorer: controlled schedules per "
            "--explore run (each is a fresh seeded PCT priority "
            "assignment run under the hb sanitizer)")
declare_env("MXNET_SCHED_SEED", int, 0,
            "interleaving explorer: schedule seed — (seed, scenario, "
            "schedule index) names a bit-identical schedule for pure "
            "thread scenarios, so a finding reported for one seed "
            "reproduces from the seed alone even without its journal")
declare_env("MXNET_SCHED_DEPTH", int, 3,
            "interleaving explorer: PCT bug depth d — each schedule "
            "plants d-1 seeded priority-change points, enough for "
            "every ordering bug reachable by d-1 preemptions "
            "(Burckhardt et al.'s probabilistic guarantee)")
declare_env("MXNET_SCHED_STARVE_OPS", int, 20000,
            "interleaving explorer: starvation budget — a thread "
            "RUNNABLE for this many consecutive scheduling decisions "
            "without ever being picked is a finding (0 disables; the "
            "counter resets whenever the thread runs or blocks, so "
            "PCT's legitimate long demotions don't trip it)")
declare_env("MXNET_SCHED_JOURNAL_DIR", str, "_sched_journals",
            "interleaving explorer: where fsync'd JSONL schedule "
            "journals land — failing schedules keep theirs (the "
            "--replay input), clean schedules delete theirs")


# ---------------------------------------------------------------------------
# Generic name registry (reference: dmlc registry pattern used for optimizers,
# initializers, metrics, iterators...).
# ---------------------------------------------------------------------------
class Registry:
    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, Any] = {}

    def register(self, obj=None, name: Optional[str] = None):
        def _do(o):
            key = (name or getattr(o, "__name__", None) or str(o)).lower()
            self._entries[key] = o
            return o
        return _do(obj) if obj is not None else _do

    def alias(self, name: str, target: str):
        self._entries[name.lower()] = self._entries[target.lower()]

    def get(self, name: str):
        try:
            return self._entries[name.lower()]
        except KeyError:
            raise MXNetError(
                f"unknown {self.kind} {name!r}; registered: "
                f"{sorted(self._entries)}")

    def find(self, name: str):
        return self._entries.get(name.lower())

    def keys(self):
        return sorted(self._entries)


# ---------------------------------------------------------------------------
# Attr (de)serialization for symbol JSON round trips.  The reference stores op
# hyper-params as strings in graph JSON (nnvm); we keep that convention so
# saved graphs stay human-readable and diffable.
# ---------------------------------------------------------------------------
def attr_to_str(v) -> str:
    import numpy as _np
    if isinstance(v, (list, tuple)):
        return "(" + ", ".join(attr_to_str(x) for x in v) + ("," if len(v) == 1 else "") + ")"
    if isinstance(v, bool):
        return "True" if v else "False"
    if isinstance(v, _np.dtype):
        return v.name
    if isinstance(v, type):
        return _np.dtype(v).name
    return str(v)


def str_to_attr(s: str):
    if not isinstance(s, str):
        return s
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


# thread-local scoping helper used by Context / autograd / name managers
class _ScopeStack(threading.local):
    def __init__(self, default=None):
        super().__init__()
        self.stack = [default] if default is not None else []

    @property
    def current(self):
        return self.stack[-1] if self.stack else None

    def push(self, v):
        self.stack.append(v)

    def pop(self):
        return self.stack.pop()


_numeric_types = (int, float)


def string_types():
    return (str,)


class ContribNamespace:
    """``mx.nd.contrib.X`` / ``mx.sym.contrib.X`` → registered
    ``_contrib_X`` op (reference: python/mxnet/{ndarray,symbol}/contrib.py
    namespaces)."""

    def __init__(self, ns):
        self._ns = ns

    def __getattr__(self, name):
        fn = self._ns.get("_contrib_" + name) or self._ns.get(name)
        if fn is None:
            raise AttributeError(f"contrib op {name!r} not registered")
        return fn
