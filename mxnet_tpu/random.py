"""Global PRNG state.

The reference seeds one PRNG per device via the resource manager
(src/resource.cc kRandom; python/mxnet/random.py mx.random.seed).  Here the
global state is a counter over a root jax.random key: every random op draw
folds the counter in, so eager results are reproducible after
``mx.random.seed(n)`` while traced graphs receive keys as explicit arguments
(purity under jit).
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()


def _get():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
        _state.counter = 0
    return _state


def seed(seed_state: int) -> None:
    """mx.random.seed — reseed the global generator."""
    s = _get()
    s.key = jax.random.PRNGKey(int(seed_state))
    s.counter = 0


def next_key():
    """Fresh key for one op invocation."""
    s = _get()
    s.counter += 1
    return jax.random.fold_in(s.key, s.counter)


def split_key(n: int):
    return jax.random.split(next_key(), n)


_dummy_key = None


def key_for(run):
    """Key for one interpreter invocation.

    next_key() is an eager fold_in — a real device dispatch (a per-step
    round-trip on a remote-attached chip).  Interpreters from
    build_interpreter carry ``needs_rng``; RNG-free programs (most CNN
    training steps) share one constant key instead, which also keeps jit
    cache signatures stable."""
    global _dummy_key
    if getattr(run, "needs_rng", True):
        return next_key()
    if _dummy_key is None:
        _dummy_key = jax.random.PRNGKey(0)
    return _dummy_key
