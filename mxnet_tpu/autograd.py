"""Imperative autograd.

TPU-native equivalent of the reference's tape (src/imperative/imperative.cc
RecordOp/Backward, include/mxnet/imperative.h AGInfo; python API
python/mxnet/autograd.py).  Where the reference builds an nnvm graph node per
invoked op and runs a Gradient pass, here the tape records each dispatched
op's pure-jax closure + input snapshots; ``backward`` replays the tape as one
pure function of the marked variables and differentiates it with ``jax.vjp``
— so the gradient graph is *compiled by XLA as a whole* rather than executed
op-by-op.

Handle identity provides the reference's var-versioning: every NDArray owns a
``_handle`` token; in-place mutation swaps the token, so tape entries always
refer to the value they observed (the analog of ThreadedVar versions,
threaded_engine.h:112-214).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .base import MXNetError


class _TapeEntry:
    __slots__ = ("fn", "attrs", "in_handles", "in_values", "in_arrays",
                 "out_handles", "out_arrays", "rng_key", "n_keep",
                 "op_name")

    def __init__(self, fn, attrs, in_handles, in_values, in_arrays,
                 out_handles, out_arrays, rng_key, n_keep, op_name=None):
        self.fn = fn                # pure: fn(*in_values, **attrs) -> tuple
        self.attrs = attrs
        self.in_handles = in_handles
        self.in_values = in_values  # jax value snapshot at record time
        self.in_arrays = in_arrays  # NDArray refs (keeps AGInfo alive)
        self.out_handles = out_handles
        self.out_arrays = out_arrays
        self.rng_key = rng_key
        self.n_keep = n_keep        # how many leading fn outputs are visible
        self.op_name = op_name      # canonical registry name (None: custom)


class _State(threading.local):
    def __init__(self):
        super().__init__()
        self.recording = False
        self.training = False
        self.tape: List[_TapeEntry] = []


_state = _State()


def is_recording() -> bool:
    return _state.recording


def is_training() -> bool:
    return _state.training


def set_recording(is_recording: bool) -> bool:
    prev, _state.recording = _state.recording, is_recording
    return prev


def set_training(train_mode: bool) -> bool:
    prev, _state.training = _state.training, train_mode
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train_mode):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode
        self._prev_is_record = None
        self._prev_train_mode = None

    def __enter__(self):
        if self._enter_is_record is not None:
            self._prev_is_record = set_recording(self._enter_is_record)
        if self._enter_train_mode is not None:
            self._prev_train_mode = set_training(self._enter_train_mode)
        return self

    def __exit__(self, *a):
        if self._enter_is_record is not None:
            set_recording(self._prev_is_record)
        if self._enter_train_mode is not None:
            set_training(self._prev_train_mode)


def record(train_mode: bool = True):
    """Scope: record imperative ops for backward (autograd.py:34-100)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode: bool = False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach grad buffers (reference: MXAutogradMarkVariables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g if req != "null" else None
        v._grad_req = req


def _record(fn, attrs, in_arrays, in_values, out_arrays, rng_key=None,
            n_keep=None, op_name=None):
    """Called by the dispatcher for every op executed under record()."""
    entry = _TapeEntry(
        fn=fn, attrs=attrs,
        in_handles=[a._handle for a in in_arrays],
        in_values=list(in_values),
        in_arrays=list(in_arrays),
        out_handles=[a._handle for a in out_arrays],
        out_arrays=list(out_arrays),
        rng_key=rng_key,
        n_keep=n_keep if n_keep is not None else len(out_arrays),
        op_name=op_name)
    _state.tape.append(entry)


def _clear_tape():
    _state.tape = []


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Differentiate tape-recorded graph wrt marked variables.

    Replays the tape as one pure jax function of the leaf values and calls
    ``jax.vjp`` — XLA compiles the whole backward as a single program
    (reference equivalent: Imperative::Backward, imperative.cc:357-575).
    """
    from .ndarray import NDArray
    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    tape = _state.tape
    if not tape:
        raise MXNetError("backward called outside of autograd.record scope "
                         "or tape is empty")

    # leaves: marked arrays, keyed by the handle *recorded on the tape* (the
    # version actually used in the graph — an in-place mutation after record
    # must not orphan the gradient; reference analog: engine var versions).
    #
    # Leaves whose grad buffer is row_sparse (attach_grad(stype=
    # 'row_sparse')) are handled on a separate path: they are NOT vjp
    # leaves (that would materialize the dense (vocab, d) cotangent the
    # sparse request exists to avoid).  Instead, for each gather that
    # consumes them (Embedding/take — the reference's sparse-grad ops,
    # indexing_op.cc FInferStorageType), an auxiliary zero leaf is added
    # to the gather's output; its cotangent IS the touched-row values,
    # and the gather's index input supplies the row indices.
    from .ndarray.sparse import RowSparseNDArray

    def _is_sparse_leaf(a):
        return (getattr(a, "_grad_req", "null") != "null"
                and isinstance(getattr(a, "_grad", None), RowSparseNDArray))

    leaf_handles: List[object] = []
    leaf_arrays: List["NDArray"] = []
    leaf_values: List[object] = []
    seen = set()
    sparse_leaf_of: Dict[object, "NDArray"] = {}
    for e in tape:
        for h, a, v in zip(e.in_handles, e.in_arrays, e.in_values):
            if h in seen:
                continue
            if _is_sparse_leaf(a):
                seen.add(h)
                sparse_leaf_of[h] = a
                continue
            if (getattr(a, "_grad_req", "null") != "null"
                    and a._grad is not None):
                seen.add(h)
                leaf_handles.append(h)
                leaf_arrays.append(a)
                leaf_values.append(v)

    # locate the gathers consuming sparse leaves and build their aux leaves
    aux_handles: List[object] = []
    aux_values: List[object] = []
    aux_entries = {}       # id(entry) -> aux handle
    sparse_contrib = []    # (leaf_array, aux_handle, indices_values, mode)
    for e in tape:
        for pos, (h, a) in enumerate(zip(e.in_handles, e.in_arrays)):
            if h not in sparse_leaf_of:
                continue
            if e.op_name == "Embedding" and pos == 1:
                idx_vals, w_vals = e.in_values[0], e.in_values[1]
            elif e.op_name == "take" and pos == 0 \
                    and e.attrs.get("axis", 0) == 0:
                idx_vals, w_vals = e.in_values[1], e.in_values[0]
            elif e.op_name is None:
                raise MXNetError(
                    "row_sparse gradients are not supported through a "
                    "hybridized/cached graph (the fused program hides the "
                    "gather); un-hybridize the block consuming this "
                    "parameter, or use a dense gradient "
                    "(grad_stype='default')")
            else:
                raise MXNetError(
                    "row_sparse gradient requested for an array consumed "
                    f"by op {e.op_name!r}; only Embedding/take(axis=0) "
                    "produce sparse gradients (reference: indexing_op.cc "
                    "sparse-grad storage inference)")
            aux_h = object()
            out_shape = tuple(idx_vals.shape) + tuple(w_vals.shape[1:])
            aux_handles.append(aux_h)
            aux_values.append(jnp.zeros(out_shape, w_vals.dtype))
            aux_entries[id(e)] = aux_h
            sparse_contrib.append((sparse_leaf_of[h], aux_h, idx_vals,
                                   e.attrs.get("mode", "clip")))
    for h in heads:
        if (getattr(h, "_grad_req", "null") != "null" and h._grad is not None
                and h._handle not in seen):
            seen.add(h._handle)
            leaf_handles.append(h._handle)
            leaf_arrays.append(h)
            leaf_values.append(h._data)
    if not leaf_handles and not sparse_contrib:
        raise MXNetError("no marked (attach_grad) variables found in graph")

    head_handles = [h._handle for h in heads]
    all_handles = leaf_handles + aux_handles
    all_values = leaf_values + aux_values

    def replay(leaf_vals):
        env = dict(zip(all_handles, leaf_vals))
        for e in tape:
            ins = [env.get(h, v) for h, v in zip(e.in_handles, e.in_values)]
            if e.rng_key is not None:
                outs = e.fn(e.rng_key, *ins, **e.attrs)
            else:
                outs = e.fn(*ins, **e.attrs)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            aux_h = aux_entries.get(id(e))
            if aux_h is not None:
                outs = (outs[0] + env[aux_h],) + tuple(outs[1:])
            for h, o in zip(e.out_handles, outs[:e.n_keep]):
                env[h] = o
        missing = [i for i, h in enumerate(head_handles) if h not in env]
        if missing:
            raise MXNetError("head output was not produced by recorded graph")
        return tuple(env[h] for h in head_handles)

    outs, vjp_fn = jax.vjp(lambda *ls: replay(ls), *all_values)
    if head_grads is None:
        cts = tuple(jnp.ones_like(o) for o in outs)
    else:
        cts = tuple(jnp.ones_like(o) if g is None else
                    (g._data if isinstance(g, NDArray) else jnp.asarray(g))
                    for o, g in zip(outs, head_grads))
    grads = vjp_fn(cts)
    # sparse leaves: aux cotangents are the touched-row values; the gather
    # indices are the row ids.  O(touched rows) end to end.
    aux_grads = dict(zip(aux_handles, grads[len(leaf_values):]))
    sp_per_array: Dict[int, list] = {}
    sp_order: List["NDArray"] = []
    for a, aux_h, idx_vals, mode in sparse_contrib:
        if id(a) not in sp_per_array:
            sp_per_array[id(a)] = []
            sp_order.append(a)
        g = aux_grads[aux_h]
        row_shape = tuple(a.shape[1:])
        # normalize indices exactly like the forward gather did (take's
        # mode attr: clip/wrap — ops/indexing.py _take) so out-of-range
        # ids credit the same row the forward read
        idx = jnp.asarray(idx_vals).reshape(-1).astype(jnp.int64)
        if mode == "wrap":
            idx = jnp.mod(idx, a.shape[0])
        else:
            idx = jnp.clip(idx, 0, a.shape[0] - 1)
        sp_per_array[id(a)].append((g.reshape((-1,) + row_shape), idx))
    for a in sp_order:
        vals = jnp.concatenate([v for v, _ in sp_per_array[id(a)]], axis=0)
        idxs = jnp.concatenate([i for _, i in sp_per_array[id(a)]], axis=0)
        if a._grad_req == "add" and isinstance(a._grad, RowSparseNDArray) \
                and a._grad.indices.shape[0] > 0:
            vals = jnp.concatenate([a._grad.data._data, vals], axis=0)
            idxs = jnp.concatenate(
                [a._grad.indices._data.astype(jnp.int64), idxs], axis=0)
        # re-arm the existing grad buffer in place: Parameter/Module hold
        # a reference to it, exactly like the dense in-place write below
        RowSparseNDArray.__init__(a._grad, NDArray(vals), NDArray(idxs),
                                  tuple(a.shape))

    grads = grads[:len(leaf_values)]
    # accumulate per array (the same array may appear under several recorded
    # versions); honor grad_req write/add
    per_array: Dict[int, list] = {}
    order: List["NDArray"] = []
    for a, g in zip(leaf_arrays, grads):
        if id(a) not in per_array:
            per_array[id(a)] = []
            order.append(a)
        per_array[id(a)].append(g)
    for a in order:
        total = per_array[id(a)][0]
        for g in per_array[id(a)][1:]:
            total = total + g
        if a._grad_req == "add":
            a._grad._data = a._grad._data + total
        else:
            a._grad._data = total
    if not retain_graph:
        _clear_tape()


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return grads of heads wrt variables (autograd.py:274).

    create_graph=True records the gradient computation itself for
    higher-order gradients.
    """
    from .ndarray import NDArray
    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    if isinstance(heads, NDArray):
        heads = [heads]
    tape = list(_state.tape)
    if retain_graph is None:
        retain_graph = create_graph

    var_handles = [v._handle for v in variables]
    head_handles = [h._handle for h in heads]

    def replay(leaf_vals):
        env = dict(zip(var_handles, leaf_vals))
        for e in tape:
            ins = [env.get(h, v) for h, v in zip(e.in_handles, e.in_values)]
            outs = (e.fn(e.rng_key, *ins, **e.attrs) if e.rng_key is not None
                    else e.fn(*ins, **e.attrs))
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            for h, o in zip(e.out_handles, outs[:e.n_keep]):
                env[h] = o
        return tuple(env[h] for h in head_handles)

    leaf_vals = [v._data for v in variables]
    if create_graph:
        # differentiate symbolically so the result is itself recordable:
        # run jax.grad-of-replay eagerly and record it as one tape op
        def gradfun(*ls):
            outs, vjp_fn = jax.vjp(lambda *xs: replay(xs), *ls)
            cts = tuple(jnp.ones_like(o) for o in outs) if head_grads is None \
                else tuple(g._data for g in head_grads)
            return vjp_fn(cts)
        gvals = gradfun(*leaf_vals)
        out_arrays = [NDArray(g) for g in gvals]
        if is_recording():
            _record(lambda *ls: gradfun(*ls), {}, list(variables), leaf_vals,
                    out_arrays)
        result = out_arrays
    else:
        outs, vjp_fn = jax.vjp(lambda *ls: replay(ls), *leaf_vals)
        cts = tuple(jnp.ones_like(o) for o in outs) if head_grads is None \
            else tuple(g._data if isinstance(g, NDArray) else jnp.asarray(g)
                       for g in head_grads)
        gvals = vjp_fn(cts)
        result = [NDArray(g) for g in gvals]
    if not retain_graph:
        _clear_tape()
    return result[0] if single else result


def get_symbol(x):
    raise MXNetError("autograd.get_symbol is not supported in mxnet_tpu; "
                     "use gluon HybridBlock tracing instead")


class Function:
    """Custom differentiable function (reference: autograd.py:369 Function,
    src/c_api/c_api_function.cc).

    Subclass and override ``forward``/``backward`` operating on NDArrays.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray
        import numpy as _np
        func = self

        raw_in = [x._data for x in inputs]

        def _fwd_raw(*vals):
            with pause():
                nds = [NDArray(v) for v in vals]
                outs = func.forward(*nds)
            if isinstance(outs, NDArray):
                outs = (outs,)
            return tuple(o._data for o in outs)

        @jax.custom_vjp
        def core(*vals):
            return _fwd_raw(*vals)

        def core_fwd(*vals):
            return _fwd_raw(*vals), vals

        def core_bwd(res, gs):
            with pause():
                nd_gs = [NDArray(g) for g in gs]
                igrads = func.backward(*nd_gs)
            if isinstance(igrads, NDArray):
                igrads = (igrads,)
            return tuple(g._data for g in igrads)

        core.defvjp(core_fwd, core_bwd)

        out_vals = core(*raw_in)
        out_arrays = [NDArray(v) for v in out_vals]
        if is_recording():
            _record(lambda *vals: core(*vals), {}, list(inputs), raw_in,
                    out_arrays)
        return out_arrays[0] if len(out_arrays) == 1 else tuple(out_arrays)
