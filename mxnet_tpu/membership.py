"""Elastic membership: a versioned roster for the dist_async cluster.

The transport already had every primitive needed to survive roster
churn — heartbeat liveness (``num_dead_nodes()``), deterministic
row-striping (``KVStoreDistAsync._stripe_plan``), exactly-once
envelopes with full-window replay — without ever ACTING on them: a dead
server was only *named* in a barrier failure (reference: the fixed
ps-lite roster, arXiv:1512.01274).  This module is the acting-on-them
layer, the dynamic-membership trait TensorFlow's production experience
(arXiv:1605.08695) showed separates a lab parameter server from one
that rides preemptible capacity:

* **Roster** — an epoch-numbered generation, the ordered server URI
  tuple (order IS the stripe-slot mapping) and the live worker-rank
  tuple.  Negotiated over the existing control channel; the
  COORDINATOR is slot 0 of the current generation
  (:func:`coordinator_uri` — the single source of truth both the
  server's and the worker's address derivation route through).
  Coordinator death is itself a survivable membership event: on
  coordinator silence every observer independently elects
  :func:`elect_successor` — pure arithmetic over the ordered roster,
  no votes, the same determinism trick ``stripe_plan`` uses — and the
  elected survivor rebuilds the ledger with :func:`rebuild_ledger`
  from the reports and snapshot bank it already holds.
* **Pure roster arithmetic** (this module, no sockets): stripe-plan
  derivation, wire-key layouts per server set, handoff planning
  between generations, per-stripe optimizer-state restriping.  Every
  worker computes the identical layout from the same roster with no
  coordination — determinism is the correctness argument, and
  ``tests/test_membership.py`` pins it as pure units.
* **Coordinator state machine** (:class:`MembershipCoordinator`):
  join/leave/evict with generation bumps only on actual change, so
  duplicate reports (every surviving worker races to report the same
  dead server) are idempotent.

The kvstore client (``kvstore.KVStoreDistAsync``) and server
(``kvstore_server.KVStoreServer``) own the socket halves: roster ops
ride the ordinary exactly-once envelopes, handoff values ride the same
zero-copy frames as pushes, and the server dedups handoffs
per-(wire key, generation) so duplicate delivery — the quorum re-push
by ALL workers, or a replay through a connection kill — applies once.
See docs/ROBUSTNESS.md (elastic membership) for the full protocol.
"""
from __future__ import annotations

import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .analysis import hb as _hb

#: stripe-suffix separator, shared with the kvstore wire protocol
STRIPE_SEP = "@s"


def bank_newest(bank: Dict[str, tuple], uri: str, seq, snapshot) -> None:
    """THE snapshot-banking rule, in one place: keep the newest-seq
    snapshot per uri (ties re-bank — a re-sent equal seq is the same
    beat).  Used by the ledger bank (``note_server_beat`` /
    ``preload_snapshot``) and every server's local peer bank
    (``kvstore_server._bank_peer_snapshot``) so the three banks can
    never diverge on the tie-break or seq coercion.  Caller holds
    whatever lock guards ``bank``; a None snapshot banks nothing."""
    if snapshot is None or seq is None:
        return
    have = bank.get(uri)
    if have is None or int(seq) >= have[0]:
        bank[uri] = (int(seq), snapshot)


# ---------------------------------------------------------------------------
# Pure roster arithmetic — no sockets, no state.  Every function is
# deterministic from its arguments so every observer of the same roster
# generation derives the identical layout.
# ---------------------------------------------------------------------------
def stripe_plan(key: str, shape, num_servers: int,
                bigarray_bound: int) -> Optional[List[int]]:
    """Row boundaries for a striped key, or None for an unstriped one.
    Deterministic from (key, shape, num_servers, bound) — the single
    source of truth behind ``KVStoreDistAsync._stripe_plan`` and every
    handoff computation; two generations with the same server COUNT
    always yield the same plan."""
    if num_servers <= 1 or not shape or len(shape) == 0 \
            or int(np.prod(shape)) <= bigarray_bound or shape[0] < 2:
        return None
    parts = min(num_servers, shape[0])
    return [shape[0] * i // parts for i in range(parts + 1)]


def coordinator_uri(servers: Optional[Sequence[str]]) -> Optional[str]:
    """The coordinator of a roster: slot 0 of the CURRENT generation's
    server order (removal preserves survivor order, so succession walks
    the roster deterministically).  The single source of truth behind
    ``kvstore_server._coordinator_addr`` and the worker's
    ``_coordinator_conn`` — both used to hardcode bootstrap slot 0,
    which goes stale the moment a failover re-seats the roster."""
    if not servers:
        return None
    for u in servers:
        if u:
            return u
    return None


def elect_successor(servers: Optional[Sequence[str]],
                    dead) -> Optional[str]:
    """Deterministic coordinator succession: the first roster slot not
    known dead.  Pure arithmetic over the same ordered roster every
    observer already holds — no votes, no extra protocol (the
    ``stripe_plan`` determinism trick applied to leadership): any two
    observers of the same (roster, dead set) elect the SAME successor,
    and observers with momentarily different dead sets converge through
    the ``roster_dead`` / :func:`rebuild_ledger` path.  None when every
    server is dead (nothing left to elect)."""
    dead = set(dead or ())
    for u in servers or ():
        if u and u not in dead:
            return u
    return None


def roster_diff(old: Optional[Sequence[str]],
                new: Optional[Sequence[str]]) -> Tuple[List[str],
                                                       List[str]]:
    """``(added, removed)`` between two ordered server rosters, order-
    preserving and duplicate-free — the pure arithmetic behind roster
    OBSERVATION: the serving fleet reconciles its replica set against
    each observed generation (a removed uri gets drained, an added one
    becomes routable) without ever joining the roster itself."""
    old_set = {u for u in (old or ()) if u}
    new_set = {u for u in (new or ()) if u}
    added = [u for u in (new or ()) if u and u not in old_set]
    removed = [u for u in (old or ()) if u and u not in new_set]
    return added, removed


def host_groups(workers: Sequence[int],
                workers_per_host: int) -> List[Tuple[int, ...]]:
    """Partition worker ranks into per-host mesh groups — the pure
    arithmetic behind the hierarchical kvstore tier
    (``MXNET_KVSTORE_HIERARCHY``).  Launchers place consecutive ranks
    on one host (tools/launch.py fills each host's slots in order), so
    rank ``r`` lives in group ``r // workers_per_host``; groups come
    back sorted by their leader (lowest) rank.  Deterministic from
    (workers, per_host) with no coordination — the same trick
    :func:`stripe_plan` and :func:`elect_successor` use, applied to
    host topology."""
    per = max(1, int(workers_per_host))
    groups: Dict[int, List[int]] = {}
    for r in sorted(int(w) for w in workers):
        groups.setdefault(r // per, []).append(r)
    return [tuple(groups[g]) for g in sorted(groups)]


def mesh_group(rank: int, workers: Sequence[int],
               workers_per_host: int) -> Tuple[int, Tuple[int, ...], int]:
    """``(leader_rank, members, group_index)`` of ``rank``'s host group
    (:func:`host_groups`).  The leader — the lowest rank on the host —
    is the ONLY member that ships gradients over the TCP wire; the
    rest reduce into it in-mesh.  Raises when ``rank`` is not in
    ``workers`` (a roster that does not know this rank cannot place
    it)."""
    for gi, members in enumerate(host_groups(workers, workers_per_host)):
        if int(rank) in members:
            return members[0], members, gi
    raise ValueError(
        f"mesh_group: rank {rank} not in worker set {tuple(workers)}")


def server_index(key: str, num_servers: int) -> int:
    """crc32 routing of an unstriped key to a server slot."""
    return zlib.crc32(key.encode()) % num_servers


def stripe_server_index(key: str, i: int, num_servers: int) -> int:
    """Server slot owning stripe ``i`` of ``key``: consecutive stripes
    land on consecutive servers, offset by the key hash."""
    return (zlib.crc32(key.encode()) + i) % num_servers


def wire_layout(key: str, shape, servers: Sequence[str],
                bigarray_bound: int) -> Dict[str, Tuple[str, int, int]]:
    """The full wire placement of one logical key against one server
    set: ``{wire_key: (server_uri, row_start, row_stop)}``.  For an
    unstriped key the row span is the whole leading axis (or (0, 0)
    for scalars)."""
    n = len(servers)
    plan = stripe_plan(key, shape, n, bigarray_bound)
    if plan is None:
        rows = int(shape[0]) if shape else 0
        return {key: (servers[server_index(key, n)], 0, rows)}
    out = {}
    for i in range(len(plan) - 1):
        out[f"{key}{STRIPE_SEP}{i}"] = (
            servers[stripe_server_index(key, i, n)], plan[i], plan[i + 1])
    return out


def base_key(wire_key: str) -> str:
    """The logical key behind a wire key (stripe suffix stripped)."""
    if STRIPE_SEP in wire_key:
        base, _, idx = wire_key.rpartition(STRIPE_SEP)
        if idx.isdigit():
            return base
    return wire_key


def sparse_route(plan: List[int], indices: np.ndarray
                 ) -> List[Tuple[int, np.ndarray, np.ndarray]]:
    """Route sorted row ids onto a stripe plan: ``[(stripe_index,
    local_ids, positions)]`` for the NON-empty stripes only — a sparse
    push/pull skips every stripe its batch never touched, which is the
    whole wire win.  ``local_ids`` are the ids rebased to the stripe's
    row 0; ``positions`` index back into ``indices`` (and the caller's
    row block).  Deterministic from (plan, indices) under the same
    contract as :func:`stripe_plan`."""
    idx = np.asarray(indices, dtype=np.int64)
    stripe_of = np.searchsorted(plan, idx, side="right") - 1
    out = []
    for i in range(len(plan) - 1):
        pos = np.nonzero(stripe_of == i)[0]
        if pos.size:
            out.append((i, idx[pos] - plan[i], pos))
    return out


def moved_row_spans(key: str, shape, old_servers: Sequence[str],
                    new_servers: Sequence[str],
                    bigarray_bound: int) -> List[Tuple[int, int]]:
    """The row spans of ``key`` whose OWNING server changes between two
    rosters: merged, sorted, half-open ``[(lo, hi)]``.  The pure
    arithmetic behind per-row residual invalidation — a restripe must
    drop exactly the error-feedback rows that moved to a different
    server (their un-drained error belongs to the OLD owner's applied
    history) and keep every row that stayed put."""
    old = wire_layout(key, shape, old_servers, bigarray_bound)
    new = wire_layout(key, shape, new_servers, bigarray_bound)
    rows = int(shape[0]) if shape else 0
    cuts = {0, rows}
    for _, lo, hi in list(old.values()) + list(new.values()):
        cuts.add(min(max(lo, 0), rows))
        cuts.add(min(max(hi, 0), rows))
    cuts = sorted(cuts)

    def owner_at(layout, row):
        for uri, lo, hi in layout.values():
            if lo <= row < hi:
                return uri
        return None

    moved: List[Tuple[int, int]] = []
    for lo, hi in zip(cuts, cuts[1:]):
        if lo >= hi:
            continue
        if owner_at(old, lo) != owner_at(new, lo):
            if moved and moved[-1][1] == lo:
                moved[-1] = (moved[-1][0], hi)
            else:
                moved.append((lo, hi))
    return moved


def plan_handoff(key_shapes: Dict[str, tuple], old_servers: Sequence[str],
                 new_servers: Sequence[str],
                 bigarray_bound: int) -> List[str]:
    """The logical keys whose wire layout CHANGES between two server
    sets — the keys that need a state handoff on this roster bump.  A
    key whose every wire key maps to the same URI with the same row
    span needs nothing (its owning server survived in the same slot
    role); everything else is re-pushed under the new layout."""
    moved = []
    for key, shape in key_shapes.items():
        old = wire_layout(key, shape, old_servers, bigarray_bound)
        new = wire_layout(key, shape, new_servers, bigarray_bound)
        if old != new:
            moved.append(key)
    return moved


def restripe_value(key: str, value: np.ndarray, servers: Sequence[str],
                   bigarray_bound: int) -> List[Tuple[str, str, np.ndarray]]:
    """Slice one full key value into its new-layout handoff pushes:
    ``[(wire_key, server_uri, row_slice)]`` in stripe order."""
    out = []
    layout = wire_layout(key, value.shape, servers, bigarray_bound)
    for wk, (uri, lo, hi) in layout.items():
        out.append((wk, uri,
                    value if wk == key else value[lo:hi]))
    return out


def _concat_states(parts):
    """Concatenate per-stripe optimizer states along axis 0.  States are
    the shapes ``optimizer.create_state`` produces: an ndarray shaped
    like the weight stripe, a tuple/list of those (momentum pairs), or
    None (stateless).  Anything else is not row-decomposable and maps
    to None (the optimizer re-creates fresh state — the documented
    restriping caveat for non-elementwise state)."""
    if all(p is None for p in parts):
        return None
    if all(isinstance(p, np.ndarray) for p in parts):
        return np.concatenate(parts, axis=0)
    if all(isinstance(p, (tuple, list)) for p in parts) \
            and len({len(p) for p in parts}) == 1:
        cols = []
        for items in zip(*parts):
            cols.append(_concat_states(list(items)))
        return tuple(cols)
    return None


def _slice_state(state, lo, hi):
    if state is None:
        return None
    if isinstance(state, np.ndarray):
        return state[lo:hi]
    if isinstance(state, (tuple, list)):
        return tuple(_slice_state(s, lo, hi) for s in state)
    return None


def restripe_states(key: str, per_wire_states: Dict[str, object],
                    old_plan: Optional[List[int]],
                    new_plan: Optional[List[int]]):
    """Re-key per-stripe optimizer state from one plan to another:
    merge the old stripes' states (concatenating leading-axis arrays),
    then re-slice along the NEW plan.  Returns ``{wire_key: state}``
    under the new plan, or {} when the old stripes don't cover the full
    key (a partial snapshot cannot be restriped soundly — the optimizer
    re-creates state for the missing rows instead of training on a
    silently misaligned merge).

    Elementwise optimizers (SGD/Adam: state shaped like the weight)
    restripe EXACTLY; per-layer state (LARS/LAMB norms) is not
    row-decomposable and comes back None per stripe — the same caveat
    striping itself carries."""
    if old_plan is None:
        parts = [per_wire_states.get(key)]
        spans = [(0, None)]
    else:
        parts, spans = [], []
        for i in range(len(old_plan) - 1):
            wk = f"{key}{STRIPE_SEP}{i}"
            if wk not in per_wire_states:
                return {}
            parts.append(per_wire_states[wk])
            spans.append((old_plan[i], old_plan[i + 1]))
    merged = _concat_states(parts)
    if new_plan is None:
        return {key: merged}
    out = {}
    for i in range(len(new_plan) - 1):
        out[f"{key}{STRIPE_SEP}{i}"] = _slice_state(
            merged, new_plan[i], new_plan[i + 1])
    return out


# ---------------------------------------------------------------------------
# Coordinator state machine (lives inside server 0 of the roster)
# ---------------------------------------------------------------------------
class Roster:
    """One immutable roster generation."""

    __slots__ = ("generation", "servers", "workers")

    def __init__(self, generation: int, servers: Tuple[str, ...],
                 workers: Tuple[int, ...]):
        self.generation = int(generation)
        self.servers = tuple(servers)
        self.workers = tuple(sorted(workers))

    def as_wire(self):
        return (self.generation, list(self.servers), list(self.workers))

    def __repr__(self):
        return (f"Roster(gen={self.generation}, servers={self.servers}, "
                f"workers={self.workers})")


class MembershipCoordinator:
    """Epoch-numbered membership ledger (server 0 owns one instance).

    Every mutation bumps the generation ONLY on actual change, so the
    surviving workers' racing reports of the same dead server collapse
    into one bump; removal preserves the surviving servers' relative
    order, so every observer of generation G derives the identical
    stripe-slot mapping.  Thread-safe; the lock is a leaf (no calls out
    while held) so it can never join a lock cycle with the server's
    store lock or barrier condition."""

    def __init__(self, servers: Sequence[str], workers: Sequence[int]):
        self._lock = threading.Lock()
        self._generation = 0
        self._servers: List[str] = list(dict.fromkeys(servers))
        self._workers = set(int(w) for w in workers)
        self._server_seen: Dict[str, float] = _hb.track(
            {}, "MembershipCoordinator._server_seen")
        # uri -> (seq, blob); hb-tracked like the server-side banks
        self._snapshots: Dict[str, tuple] = _hb.track(
            {}, "MembershipCoordinator._snapshots")
        # last-known compact profiler counters per server, piggybacked
        # on beats (kvstore_server beat loop) — same newest-seq-wins
        # rule and same outlives-eviction contract as the state
        # snapshots: the counters of a SIGKILLed member stay readable
        # through the coordinator's "stats" envelope
        self._stats: Dict[str, tuple] = _hb.track(
            {}, "MembershipCoordinator._stats")   # uri -> (seq, counters)
        self.evictions = 0
        self.failovers = 0   # ledgers this one succeeded (rebuild_ledger)

    # -- views ---------------------------------------------------------------
    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def roster(self) -> Roster:
        with self._lock:
            return Roster(self._generation, tuple(self._servers),
                          tuple(self._workers))

    def workers_snapshot(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._workers))

    # -- mutations (generation bumps only on change) -------------------------
    def join_server(self, uri: str) -> int:
        with self._lock:
            if uri not in self._servers:
                self._servers.append(uri)
                self._generation += 1
            self._server_seen[uri] = time.monotonic()
            return self._generation

    def leave_server(self, uri: str) -> int:
        return self._remove_server(uri, evict=False)

    def report_dead_server(self, uri: str) -> int:
        return self._remove_server(uri, evict=True)

    def _remove_server(self, uri: str, evict: bool) -> int:
        with self._lock:
            if uri in self._servers:
                if len(self._servers) <= 1:
                    raise RuntimeError(
                        "cannot remove the last server (the coordinator "
                        "itself) from the roster")
                self._servers.remove(uri)
                self._server_seen.pop(uri, None)
                self._generation += 1
                if evict:
                    self.evictions += 1
            return self._generation

    def join_worker(self, rank: int) -> int:
        with self._lock:
            rank = int(rank)
            if rank not in self._workers:
                self._workers.add(rank)
                self._generation += 1
            return self._generation

    def leave_worker(self, rank: int) -> int:
        return self._remove_worker(rank, evict=False)

    def evict_worker(self, rank: int) -> int:
        return self._remove_worker(rank, evict=True)

    def _remove_worker(self, rank: int, evict: bool) -> int:
        with self._lock:
            rank = int(rank)
            if rank in self._workers:
                self._workers.discard(rank)
                self._generation += 1
                if evict:
                    self.evictions += 1
            return self._generation

    # -- server liveness + state snapshots -----------------------------------
    def note_server_beat(self, uri: str, seq: Optional[int] = None,
                         snapshot=None, stats=None) -> None:
        with self._lock:
            if uri in self._servers:
                self._server_seen[uri] = time.monotonic()
            bank_newest(self._snapshots, uri, seq, snapshot)
            bank_newest(self._stats, uri, seq, stats)

    def preload_snapshot(self, uri: str, seq: int, snapshot) -> None:
        """Seed the snapshot bank without touching liveness — the
        failover path: the elected successor promotes its LOCAL peer
        bank (grown from the beat fan-out) into the rebuilt ledger.
        Same newest-seq-wins rule as :meth:`note_server_beat`
        (:func:`bank_newest` is the one implementation both share)."""
        with self._lock:
            bank_newest(self._snapshots, uri, seq, snapshot)

    def snapshot_of(self, uri: str):
        """The last state snapshot a (possibly now-dead) server shipped,
        or None.  Snapshots OUTLIVE eviction on purpose — they are the
        killed-server recovery source."""
        with self._lock:
            have = self._snapshots.get(uri)
            return None if have is None else have[1]

    def stats_of(self, uri: str):
        """The last compact counter snapshot a (possibly now-dead)
        server piggybacked on a beat, or None.  Outlives eviction like
        :meth:`snapshot_of` — the forensic record of what a killed
        member was doing when it died."""
        with self._lock:
            have = self._stats.get(uri)
            return None if have is None else have[1]

    def stats_bank(self) -> Dict[str, tuple]:
        """The whole stats bank, ``{uri: (beat_seq, counters)}`` — what
        the coordinator folds into its ``("stats",)`` reply so a
        cluster sweep sees dead members' last-known counters too."""
        with self._lock:
            return dict(self._stats)

    def silent_servers(self, timeout: float) -> List[str]:
        """Non-coordinator servers heard from at least once and then
        silent past ``timeout`` (same never-heard-never-dead contract as
        worker liveness)."""
        if timeout <= 0:
            return []
        now = time.monotonic()
        with self._lock:
            return [u for u in self._servers[1:]
                    if u in self._server_seen
                    and now - self._server_seen[u] > timeout]


def rebuild_ledger(servers: Sequence[str], workers: Sequence[int],
                   reports: Sequence[dict],
                   snapshots: Optional[Dict[str, tuple]] = None
                   ) -> MembershipCoordinator:
    """Rebuild the coordinator ledger on the elected successor — pure
    merge over the three sources the successor already has or can
    demand: its own last-seen roster (``servers``/``workers``, carried
    on every beat reply and barrier exchange), the ``ledger_report``
    sweep of the survivors (each ships its last-known generation, beat
    seq and live key set), and the local peer snapshot bank (grown from
    the beat fan-out, so it outlives server 0).

    Merge rules (pinned socket-free by tests/test_membership.py) — the
    ONLY report field the merge consumes is ``generation``; beat seqs
    and key sets ride the full (non-slim) report for operator
    forensics, never as merge inputs:

    * the generation resumes at ``max(reported generations) + 1`` —
      every envelope a stale coordinator (or a worker still converged
      on its roster) stamped with an older generation is rejected by
      the EXISTING per-generation staleness checks (handoff dedup,
      barrier-reply bump discovery), no new wire checks needed;
    * duplicate reports are idempotent (the merge is a max over a set —
      every survivor racing to report changes nothing twice);
    * reports never ADD servers the successor's roster view lacks: an
      unknown reporter re-joins through the ordinary join path, it is
      not grandfathered into slot arithmetic mid-rebuild;
    * missing snapshots stay missing — the bank never invents state, so
      a later restripe of an unbanked dead server degrades to fresh
      state exactly like :func:`restripe_states`' partial-snapshot
      refusal, instead of training on fabricated momentum."""
    gen = 0
    for r in reports or ():
        try:
            gen = max(gen, int(r.get("generation", 0)))
        except (AttributeError, TypeError, ValueError):
            continue
    m = MembershipCoordinator(servers, workers)
    with m._lock:
        m._generation = gen + 1
    m.failovers = 1
    for uri, entry in (snapshots or {}).items():
        if entry is None:
            continue
        seq, snap = entry
        m.preload_snapshot(uri, seq, snap)
    return m
