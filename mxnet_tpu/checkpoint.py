"""Sharded checkpoint/resume.

Reference behavior (SURVEY.md §5.4): `mx.model.save_checkpoint` writes
`prefix-symbol.json` + `prefix-%04d.params` (NDArray::Save,
src/ndarray/ndarray.cc:826,939); `fit(..., begin_epoch=N)` resumes;
optimizer state rides `Module.save_optimizer_states`.

This module adds the TPU-native piece the reference never needed: params
that are jax.Arrays SHARDED over a device mesh.  Each process writes only
its addressable shards (`<prefix>-NNNN.params.shardR` + a JSON index), so
checkpointing scales with local HBM, not global model size — the
tensorstore/ocdbt pattern in a single dependency-free file format.
Loading reassembles the global arrays (any process can read all shard
files from shared storage) and `Module` re-applies mesh shardings on
bind, exactly as at first initialization.
"""
from __future__ import annotations

import json
import os
import struct
from typing import Dict

import numpy as np

from .base import MXNetError
from .ndarray import NDArray

_MAGIC = b"MXTPUSH1"


class _MetaOnly:
    """Shape/dtype stand-in for a value THIS rank will not serialize
    (fully-replicated params are written by rank 0 only) — lets the async
    snapshot skip the device copy for buffers it never reads."""

    def __init__(self, v):
        self.shape = tuple(np.shape(v))
        self.dtype = v.dtype


def _shard_entries(name, arr):
    """Yield (name, index_spec, numpy_block) for the shards THIS process
    is responsible for: exactly one replica (replica_id 0) of every
    distinct block, so checkpoint bytes scale with the global model size,
    not with replication factor or process count."""
    import jax
    v = arr._data if isinstance(arr, NDArray) else arr
    if isinstance(v, _MetaOnly):
        return
    if not isinstance(v, jax.Array) or v.is_fully_replicated:
        if jax.process_index() == 0:
            yield name, [[0, s] for s in np.shape(v)], np.asarray(v)
        return
    for sh in v.addressable_shards:
        if sh.replica_id != 0:
            continue
        spec = []
        for dim, sl in enumerate(sh.index):
            start = 0 if sl.start is None else int(sl.start)
            stop = v.shape[dim] if sl.stop is None else int(sl.stop)
            spec.append([start, stop])
        yield name, spec, np.asarray(sh.data)


def _write_local_shard(prefix: str, params: Dict[str, NDArray],
                       token=None) -> Dict:
    """Write THIS process's shard file atomically (tmp + rename); return
    the global-params index metadata.  ``token`` (async saves) rides the
    shard header so a rendezvous can tell THIS save's shard from a stale
    one left at the same path by an earlier save."""
    import jax
    rank = jax.process_index()
    entries = []
    bufs = []
    offset = 0
    index = {}
    for name, arr in params.items():
        v = arr._data if isinstance(arr, NDArray) else arr
        if isinstance(v, _MetaOnly):
            index[name] = {"shape": list(v.shape), "dtype": str(v.dtype)}
            continue
        index[name] = {"shape": list(np.shape(v)), "dtype": str(v.dtype)}
        for nm, spec, block in _shard_entries(name, arr):
            raw = np.ascontiguousarray(block).tobytes()
            entries.append({"name": nm, "index": spec,
                            "dtype": str(block.dtype),
                            "offset": offset, "nbytes": len(raw)})
            bufs.append(raw)
            offset += len(raw)
    # header stays a bare entry list for tokenless (sync) saves — the
    # on-disk format golden; async saves wrap it with the token
    header = entries if token is None else {"token": token,
                                            "entries": entries}
    hjson = json.dumps(header).encode()
    shard_path = f"{prefix}.shard{rank}"
    with open(shard_path + ".tmp", "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        for raw in bufs:
            f.write(raw)
    os.replace(shard_path + ".tmp", shard_path)
    return index


def _read_shard_header(path):
    """(header_entries, token, data_offset) from a shard file."""
    with open(path, "rb") as f:
        if f.read(8) != _MAGIC:
            raise MXNetError(f"{path}: bad shard magic")
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode())
    if isinstance(header, dict):
        return header["entries"], header.get("token"), 16 + hlen
    return header, None, 16 + hlen


def _write_index(prefix: str, index: Dict, token=None) -> None:
    import jax
    doc = {"nprocs": jax.process_count(), "params": index}
    if token is not None:
        doc["token"] = token
    with open(f"{prefix}.index.tmp", "w") as f:
        json.dump(doc, f)
    os.replace(f"{prefix}.index.tmp", f"{prefix}.index")


def save_params_sharded(prefix: str, params: Dict[str, NDArray]) -> None:
    """Write this process's shards + (rank 0) the global index.

    Atomic by construction: every file is tmp+rename, and the index is
    written LAST after a barrier confirms all shards landed — a kill
    mid-save never leaves a readable-looking broken checkpoint."""
    import jax
    index = _write_local_shard(prefix, params)
    if jax.process_count() > 1:
        from . import distributed as _dist
        _dist.barrier("mxnet_tpu_checkpoint_save")
    if jax.process_index() == 0:
        _write_index(prefix, index)
    if jax.process_count() > 1:
        # read-after-save: no rank returns before the index is visible
        from . import distributed as _dist
        _dist.barrier("mxnet_tpu_checkpoint_index")


def load_params_sharded(prefix: str) -> Dict[str, NDArray]:
    """Assemble global arrays from all shard files."""
    import ml_dtypes  # jax hard-dependency; gives numpy a bfloat16 dtype

    def _npdt(name):
        return np.dtype(ml_dtypes.bfloat16) if "bfloat16" in name \
            else np.dtype(name)

    with open(f"{prefix}.index") as f:
        index = json.load(f)
    out_np = {name: np.zeros(meta["shape"], _npdt(meta["dtype"]))
              for name, meta in index["params"].items()}
    for r in range(index["nprocs"]):
        path = f"{prefix}.shard{r}"
        if not os.path.exists(path):
            raise MXNetError(f"missing checkpoint shard file {path}")
        header, _tok, data_off = _read_shard_header(path)
        with open(path, "rb") as f:
            f.seek(data_off)
            blob = f.read()
        for ent in header:
            shape = [b - a for a, b in ent["index"]]
            count = int(np.prod(shape)) if shape else 1
            block = np.frombuffer(blob, _npdt(ent["dtype"]), count=count,
                                  offset=ent["offset"]).reshape(shape)
            sl = tuple(slice(a, b) for a, b in ent["index"])
            out_np[ent["name"]][sl] = block
    return {name: NDArray(a) for name, a in out_np.items()}


class AsyncCheckpointer:
    """Overlap checkpoint writes with training (the orbax async-save
    pattern; no reference analog — its PS snapshots were synchronous).

    ``save_params`` snapshots every value with a DEVICE-side copy (HBM to
    HBM, microseconds) and returns immediately; a background thread then
    fetches the snapshot to host and writes the shard file.  The copy
    makes the snapshot immune to the fused train step's buffer DONATION —
    step N+1 may overwrite the live param buffers while the write is
    still in flight.

    Multi-process protocol: the background threads must NOT use device
    collectives (a barrier issued from a side thread would interleave
    with training collectives in different orders per process and
    deadlock the mesh).  Rendezvous is on the shared filesystem instead:
    rank 0's writer polls for every ``<prefix>.shard{r}`` file, then
    writes the index — the same shards-before-index atomicity as the
    synchronous path.

    One save in flight at a time: a new ``save_params`` (or ``wait``)
    joins the previous write first and re-raises any background failure.
    ``wait()`` returns only after the INDEX is on disk (every rank polls
    for it), so ``wait()`` → ``load_params_sharded`` is safe on any rank.

    Per-save identity: shards and index carry a token
    ``<run_nonce>:<seq>`` — the nonce is a rank-0 uuid agreed once per
    run via a broadcast from the MAIN thread (save calls are collective,
    so this is collective-safe), and seq is a per-prefix counter.  Rank 0
    never indexes a shard from a different save (not an earlier save this
    run, not a previous run's leftover at the same path), and every
    rank's index poll requires the same full token — no wall-clock
    comparisons across hosts.

    Re-saving to the SAME prefix overwrites in place (like the sync
    path): the previous checkpoint stops being readable the moment any
    rank begins the next save, so multi-process readers must finish (and
    a barrier must confirm it) before the next save to that prefix — or
    use per-epoch prefixes (``save_checkpoint``), which never collide.
    """

    def __init__(self, poll_interval_s: float = 0.1,
                 timeout_s: float = None):
        from .base import env
        # rendezvous budget: slow shared filesystems (or a straggling
        # rank) legitimately need more than the default 10 minutes —
        # tune per deployment without touching code
        self._poll = poll_interval_s
        self._timeout = float(env("MXNET_CKPT_RENDEZVOUS_TIMEOUT", 600.0)
                              if timeout_s is None else timeout_s)
        self._thread = None
        self._err = None
        self._nonce = None  # run-unique, rank-agreed; set on first save
        self._seq = {}  # prefix -> saves issued

    def _run_nonce(self):
        """Rank-agreed uuid for this run (main-thread collective)."""
        if self._nonce is None:
            import uuid
            import jax
            nonce = np.frombuffer(uuid.uuid4().bytes, np.uint8)
            if jax.process_count() > 1:
                from . import distributed as _dist
                nonce = np.asarray(_dist.broadcast_from_root(nonce),
                                   np.uint8)
            self._nonce = bytes(nonce).hex()
        return self._nonce

    @staticmethod
    def _snapshot(params):
        import jax
        import jax.numpy as jnp
        rank0 = jax.process_index() == 0
        snap = {}
        for name, arr in params.items():
            v = arr._data if isinstance(arr, NDArray) else arr
            if isinstance(v, jax.Array):
                if not rank0 and v.is_fully_replicated:
                    # rank 0 alone writes replicated values — other
                    # ranks keep only shape/dtype (no transient HBM
                    # duplicate of buffers they never serialize)
                    snap[name] = _MetaOnly(v)
                else:
                    # device-side copy: a NEW buffer with the same
                    # sharding, outside any donation set
                    snap[name] = jnp.copy(v)
            else:
                snap[name] = np.array(v, copy=True)
        return snap

    @staticmethod
    def _fresh(path, token):
        """True when ``path`` is THIS save's shard (full-token match)."""
        try:
            _ents, tok, _off = _read_shard_header(path)
            return tok == token
        except (OSError, MXNetError, ValueError, KeyError):
            return False  # mid-rename / partial — keep polling

    def save_params(self, prefix: str, params: Dict[str, NDArray]) -> None:
        """Collective: every process must call with the same prefix."""
        import threading
        self.wait()
        self._seq[prefix] = self._seq.get(prefix, -1) + 1
        token = f"{self._run_nonce()}:{self._seq[prefix]}"
        snap = self._snapshot(params)

        def _write():
            try:
                import jax
                import time as _time
                index = _write_local_shard(prefix, snap, token=token)
                deadline = _time.monotonic() + self._timeout
                if jax.process_index() == 0:
                    missing = set(range(jax.process_count()))
                    while missing:
                        missing = {r for r in missing if not self._fresh(
                            f"{prefix}.shard{r}", token)}
                        if not missing:
                            break
                        if _time.monotonic() > deadline:
                            raise MXNetError(
                                f"async checkpoint {prefix}: shard "
                                f"markers from rank(s) {sorted(missing)} "
                                f"missing after {self._timeout:.0f}s — "
                                f"those ranks never wrote this save's "
                                f"shard (crashed rank or slow shared "
                                f"fs?); raise "
                                f"MXNET_CKPT_RENDEZVOUS_TIMEOUT if the "
                                f"fs is just slow")
                        _time.sleep(self._poll)
                    _write_index(prefix, index, token=token)
                else:
                    # completion for non-zero ranks = THIS save's index
                    # is visible (wait() must imply loadability)
                    while True:
                        try:
                            with open(f"{prefix}.index") as f:
                                if json.load(f).get("token") == token:
                                    break
                        except (OSError, ValueError):
                            pass
                        if _time.monotonic() > deadline:
                            raise MXNetError(
                                f"async checkpoint {prefix}: index not "
                                f"current after {self._timeout:.0f}s — "
                                f"rank 0 never published this save's "
                                f"index (its shard rendezvous names the "
                                f"ranks it is missing); raise "
                                f"MXNET_CKPT_RENDEZVOUS_TIMEOUT if the "
                                f"shared fs is just slow")
                        _time.sleep(self._poll)
            except BaseException as e:  # noqa: BLE001 — surfaced at wait()
                self._err = e

        self._thread = threading.Thread(target=_write, daemon=True)
        self._thread.start()

    def save_checkpoint(self, prefix: str, epoch: int, symbol, arg_params,
                        aux_params) -> None:
        """Async analog of save_checkpoint_sharded."""
        path = _checkpoint_prelude(prefix, epoch, symbol)
        self.save_params(path, _merge_arg_aux(arg_params, aux_params))

    def wait(self) -> None:
        """Join the in-flight save; re-raise any background failure."""
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        err, self._err = self._err, None
        if err is not None:
            raise err


def _merge_arg_aux(arg_params, aux_params):
    """One params dict with the load_checkpoint_sharded aux: contract."""
    merged = dict(arg_params)
    merged.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    return merged


def _checkpoint_prelude(prefix, epoch, symbol):
    """Symbol file (rank 0 — shared storage needs one writer) + the
    epoch-numbered params path, shared by the sync and async savers."""
    import jax
    if symbol is not None and jax.process_index() == 0:
        symbol.save(f"{prefix}-symbol.json")
    return f"{prefix}-{epoch:04d}.params"


def save_checkpoint_sharded(prefix: str, epoch: int, symbol, arg_params,
                            aux_params) -> None:
    """Sharded analog of mx.model.save_checkpoint (model.py:94)."""
    path = _checkpoint_prelude(prefix, epoch, symbol)
    save_params_sharded(path, _merge_arg_aux(arg_params, aux_params))


def load_serving_params(prefix: str, epoch: int):
    """Checkpoint loader for serving replicas: returns ``(symbol,
    arg_params, aux_params)`` from EITHER checkpoint flavor at
    ``prefix-%04d.params`` — the classic single-file format
    (``mx.model.save_checkpoint``) or the sharded multi-process format
    (``save_checkpoint_sharded`` / :class:`AsyncCheckpointer`, detected
    by its ``.index`` file).  A replica must be able to serve whatever
    the trainer wrote without knowing how many hosts wrote it."""
    path = f"{prefix}-{epoch:04d}.params"
    if os.path.exists(path + ".index"):
        return load_checkpoint_sharded(prefix, epoch)
    from .model import load_checkpoint
    return load_checkpoint(prefix, epoch)


def load_checkpoint_sharded(prefix: str, epoch: int):
    """Sharded analog of mx.model.load_checkpoint (model.py:105)."""
    from .symbol.symbol import load as sym_load
    sym = None
    if os.path.exists(f"{prefix}-symbol.json"):
        sym = sym_load(f"{prefix}-symbol.json")
    loaded = load_params_sharded(f"{prefix}-{epoch:04d}.params")
    arg_params = {k: v for k, v in loaded.items()
                  if not k.startswith("aux:")}
    aux_params = {k[4:]: v for k, v in loaded.items()
                  if k.startswith("aux:")}
    return sym, arg_params, aux_params
