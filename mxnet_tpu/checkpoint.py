"""Sharded checkpoint/resume.

Reference behavior (SURVEY.md §5.4): `mx.model.save_checkpoint` writes
`prefix-symbol.json` + `prefix-%04d.params` (NDArray::Save,
src/ndarray/ndarray.cc:826,939); `fit(..., begin_epoch=N)` resumes;
optimizer state rides `Module.save_optimizer_states`.

This module adds the TPU-native piece the reference never needed: params
that are jax.Arrays SHARDED over a device mesh.  Each process writes only
its addressable shards (`<prefix>-NNNN.params.shardR` + a JSON index), so
checkpointing scales with local HBM, not global model size — the
tensorstore/ocdbt pattern in a single dependency-free file format.
Loading reassembles the global arrays (any process can read all shard
files from shared storage) and `Module` re-applies mesh shardings on
bind, exactly as at first initialization.
"""
from __future__ import annotations

import json
import os
import struct
from typing import Dict

import numpy as np

from .base import MXNetError
from .ndarray import NDArray

_MAGIC = b"MXTPUSH1"


def _shard_entries(name, arr):
    """Yield (name, index_spec, numpy_block) for the shards THIS process
    is responsible for: exactly one replica (replica_id 0) of every
    distinct block, so checkpoint bytes scale with the global model size,
    not with replication factor or process count."""
    import jax
    v = arr._data if isinstance(arr, NDArray) else arr
    if not isinstance(v, jax.Array) or v.is_fully_replicated:
        if jax.process_index() == 0:
            yield name, [[0, s] for s in np.shape(v)], np.asarray(v)
        return
    for sh in v.addressable_shards:
        if sh.replica_id != 0:
            continue
        spec = []
        for dim, sl in enumerate(sh.index):
            start = 0 if sl.start is None else int(sl.start)
            stop = v.shape[dim] if sl.stop is None else int(sl.stop)
            spec.append([start, stop])
        yield name, spec, np.asarray(sh.data)


def save_params_sharded(prefix: str, params: Dict[str, NDArray]) -> None:
    """Write this process's shards + (rank 0) the global index."""
    import jax
    rank = jax.process_index()
    entries = []
    bufs = []
    offset = 0
    index = {}
    for name, arr in params.items():
        v = arr._data if isinstance(arr, NDArray) else arr
        index[name] = {"shape": list(np.shape(v)), "dtype": str(v.dtype)}
        for nm, spec, block in _shard_entries(name, arr):
            raw = np.ascontiguousarray(block).tobytes()
            entries.append({"name": nm, "index": spec,
                            "dtype": str(block.dtype),
                            "offset": offset, "nbytes": len(raw)})
            bufs.append(raw)
            offset += len(raw)
    # atomic writes (tmp + rename), index LAST after all shards land: a
    # kill mid-save never leaves a readable-looking broken checkpoint
    hjson = json.dumps(entries).encode()
    shard_path = f"{prefix}.shard{rank}"
    with open(shard_path + ".tmp", "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        for raw in bufs:
            f.write(raw)
    os.replace(shard_path + ".tmp", shard_path)
    if jax.process_count() > 1:
        from . import distributed as _dist
        _dist.barrier("mxnet_tpu_checkpoint_save")
    if rank == 0:
        with open(f"{prefix}.index.tmp", "w") as f:
            json.dump({"nprocs": jax.process_count(), "params": index}, f)
        os.replace(f"{prefix}.index.tmp", f"{prefix}.index")
    if jax.process_count() > 1:
        # read-after-save: no rank returns before the index is visible
        from . import distributed as _dist
        _dist.barrier("mxnet_tpu_checkpoint_index")


def load_params_sharded(prefix: str) -> Dict[str, NDArray]:
    """Assemble global arrays from all shard files."""
    import ml_dtypes  # jax hard-dependency; gives numpy a bfloat16 dtype

    def _npdt(name):
        return np.dtype(ml_dtypes.bfloat16) if "bfloat16" in name \
            else np.dtype(name)

    with open(f"{prefix}.index") as f:
        index = json.load(f)
    out_np = {name: np.zeros(meta["shape"], _npdt(meta["dtype"]))
              for name, meta in index["params"].items()}
    for r in range(index["nprocs"]):
        path = f"{prefix}.shard{r}"
        if not os.path.exists(path):
            raise MXNetError(f"missing checkpoint shard file {path}")
        with open(path, "rb") as f:
            if f.read(8) != _MAGIC:
                raise MXNetError(f"{path}: bad shard magic")
            (hlen,) = struct.unpack("<Q", f.read(8))
            header = json.loads(f.read(hlen).decode())
            blob = f.read()
        for ent in header:
            shape = [b - a for a, b in ent["index"]]
            count = int(np.prod(shape)) if shape else 1
            block = np.frombuffer(blob, _npdt(ent["dtype"]), count=count,
                                  offset=ent["offset"]).reshape(shape)
            sl = tuple(slice(a, b) for a, b in ent["index"])
            out_np[ent["name"]][sl] = block
    return {name: NDArray(a) for name, a in out_np.items()}


def save_checkpoint_sharded(prefix: str, epoch: int, symbol, arg_params,
                            aux_params) -> None:
    """Sharded analog of mx.model.save_checkpoint (model.py:94)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    merged = dict(arg_params)
    merged.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    save_params_sharded(f"{prefix}-{epoch:04d}.params", merged)


def load_checkpoint_sharded(prefix: str, epoch: int):
    """Sharded analog of mx.model.load_checkpoint (model.py:105)."""
    from .symbol.symbol import load as sym_load
    sym = None
    if os.path.exists(f"{prefix}-symbol.json"):
        sym = sym_load(f"{prefix}-symbol.json")
    loaded = load_params_sharded(f"{prefix}-{epoch:04d}.params")
    arg_params = {k: v for k, v in loaded.items()
                  if not k.startswith("aux:")}
    aux_params = {k[4:]: v for k, v in loaded.items()
                  if k.startswith("aux:")}
    return sym, arg_params, aux_params
