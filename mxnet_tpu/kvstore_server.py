"""Async parameter-server process: the backend of kvstore ``dist_async``.

TPU-native re-design of the reference's server stack
(src/kvstore/kvstore_dist_server.h; bootstrap in
python/mxnet/kvstore_server.py:28-75).  The reference runs ps-lite
``KVServer`` processes over ZMQ; async mode applies each worker's push to
the stored weight the moment it arrives (kvstore_dist_server.h:405-430 —
``DataHandleDefault``'s non-sync branch runs ``updater_(key, recved,
&stored)`` immediately, no cross-worker aggregation barrier).  That is
the one kvstore mode SPMD collectives cannot express — allreduce is
synchronous by construction — so here the servers come back as plain
host processes:

* transport: length-prefixed pickled messages over TCP (ps-lite/ZMQ's
  role; no new dependency).
* apply: one global store lock — the reference server is ALSO serialized
  (its single-thread ``Executor`` run loop, kvstore_dist_server.h:50-106),
  so per-push locking is the faithful concurrency model.
* placement: servers pin ``JAX_PLATFORMS=cpu`` (set by tools/launch.py);
  updates are tiny CPU math and a server must never touch a TPU — the
  accelerators belong to the workers, exactly as the reference gives
  servers no GPU context.

Process model mirrors the reference exactly: ``tools/launch.py -s S``
starts S copies of the *same user command* with ``DMLC_ROLE=server``;
importing :mod:`mxnet_tpu` in such a process enters the blocking server
loop and exits when the job is torn down, so user training scripts work
unmodified as server commands (reference kvstore_server.py:75
``_init_kvstore_server_module``).

Worker-side counterpart: :class:`mxnet_tpu.kvstore.KVStoreDistAsync`.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import sys
import threading
import time
from collections import OrderedDict

import numpy as np

from . import faultinject
from . import profiler as _prof
from .base import env as _env
from .compression import WirePayload, decompress as _decompress

# reference command codes (kvstore_dist_server.h:44-45): kStopServer=-1
# tears down, kSyncMode=-2 switches the reference server to sync
# aggregation (a documented no-op here — this server IS the async mode,
# and doubles as the channel-flush sync token), and any head >= 0 routes
# to the controller (CommandHandle :150-162), where head 0 carries the
# pickled optimizer (python/mxnet/kvstore.py set_optimizer).
K_CONTROLLER = 0
K_STOP_SERVER = -1
K_SYNC_MODE = -2


# -- wire frame ---------------------------------------------------------------
# A message is ONE frame:
#
#     >Q  total length of everything after this field
#     >I  skeleton length S
#     S bytes   pickled SKELETON: the message with every ndarray replaced
#               by a _Buf(index, dtype, shape) placeholder
#     ...       the raw tensor buffers, concatenated in index order
#
# Tensors therefore never pass through pickle: the sender writes each
# array's memoryview straight to the socket (no tobytes() copy) and the
# receiver maps np.frombuffer views over one contiguous read.  The
# skeleton — the only remaining pickled bytes from a peer — is decoded
# through a class-allowlisted Unpickler (below).


class _Buf:
    """Skeleton placeholder for a raw tensor buffer riding after it."""

    __slots__ = ("i", "dtype", "shape")

    def __init__(self, i, dtype, shape):
        self.i = i
        self.dtype = dtype
        self.shape = tuple(shape)

    def __reduce__(self):
        return (_Buf, (self.i, self.dtype, self.shape))

    @property
    def nbytes(self):
        return (int(np.prod(self.shape, dtype=np.int64))
                * np.dtype(self.dtype).itemsize)


def _pack(obj, bufs):
    """Replace every ndarray in ``obj`` with a _Buf placeholder,
    appending the (contiguous) array to ``bufs``.  Object-dtype arrays
    cannot ride a raw buffer and stay in the skeleton."""
    if isinstance(obj, np.ndarray) and not obj.dtype.hasobject:
        # NOTE: ascontiguousarray promotes 0-d to 1-d — keep the
        # logical shape from the original array
        arr = np.ascontiguousarray(obj)
        ref = _Buf(len(bufs), arr.dtype.str, obj.shape)
        bufs.append(arr)
        return ref
    if isinstance(obj, tuple):
        return tuple(_pack(x, bufs) for x in obj)
    if isinstance(obj, list):
        return [_pack(x, bufs) for x in obj]
    if isinstance(obj, dict):
        return {k: _pack(v, bufs) for k, v in obj.items()}
    if isinstance(obj, WirePayload):
        return WirePayload(obj.kind, obj.shape, obj.threshold,
                           _pack(obj.data, bufs))
    return obj


def _unpack(obj, body, offsets):
    if isinstance(obj, _Buf):
        return np.frombuffer(
            body, dtype=np.dtype(obj.dtype),
            count=int(np.prod(obj.shape, dtype=np.int64)),
            offset=offsets[obj.i]).reshape(obj.shape)
    if isinstance(obj, tuple):
        return tuple(_unpack(x, body, offsets) for x in obj)
    if isinstance(obj, list):
        return [_unpack(x, body, offsets) for x in obj]
    if isinstance(obj, dict):
        return {k: _unpack(v, body, offsets) for k, v in obj.items()}
    if isinstance(obj, WirePayload):
        return WirePayload(obj.kind, obj.shape, obj.threshold,
                           _unpack(obj.data, body, offsets))
    return obj


def _collect_bufs(obj, refs):
    if isinstance(obj, _Buf):
        refs.append(obj)
    elif isinstance(obj, (tuple, list)):
        for x in obj:
            _collect_bufs(x, refs)
    elif isinstance(obj, dict):
        for v in obj.values():
            _collect_bufs(v, refs)
    elif isinstance(obj, WirePayload):
        _collect_bufs(obj.data, refs)


# -- restricted deserialization ----------------------------------------------
# _recv_msg decodes bytes from ANY connected peer; a stock pickle.loads
# would let that peer name arbitrary importable callables (os.system,
# ...).  With tensors moved to raw-buffer frames, the remaining pickled
# skeletons/blobs only ever reference our own classes plus a handful of
# numpy/jax reconstruction helpers — so find_class admits mxnet_tpu
# classes (the reference semantics ship user optimizer/updater classes)
# plus an EXPLICIT (module, name) set.  Whole-root allowances for
# numpy/jax would re-open the door: numpy alone ships importable
# command/exec helpers (numpy.testing.runstring, distutils exec_command)
# that a REDUCE opcode could call with attacker arguments.
_SAFE_BUILTINS = frozenset({
    "complex", "frozenset", "set", "slice", "range", "bytearray",
    "object", "tuple", "list", "dict",
})
_SAFE_GLOBALS = frozenset({
    ("collections", "OrderedDict"),
    ("numpy", "dtype"),
    ("numpy", "ndarray"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "_reconstruct"),   # older numpy pickles
    ("numpy.core.multiarray", "scalar"),
    ("jax._src.array", "_reconstruct_array"),
    # the wire marker classes, by NAME: their home modules also hold
    # classes with side-effecting constructors (KVStoreServer binds a
    # listening socket) that must stay out of REDUCE reach
    ("mxnet_tpu.kvstore_server", "_Buf"),
    ("mxnet_tpu.compression", "WirePayload"),
})
# Only CLASSES from these modules — the pickle surface the reference
# semantics actually ship (optimizer/updater/scheduler objects, NDArray
# states).  A whole-package allowance would admit module-level
# callables and classes with side-effecting constructors (recordio/
# checkpoint file writers, server sockets) as REDUCE gadgets.
_SAFE_MXT_MODULES = (
    "mxnet_tpu.optimizer", "mxnet_tpu.lr_scheduler",
    "mxnet_tpu.ndarray", "mxnet_tpu.initializer",
    "mxnet_tpu.gluon.parameter",
    # Module.init_optimizer ships optimizers carrying sym/idx2name
    # context (reference: optimizer.py Optimizer attributes)
    "mxnet_tpu.symbol", "mxnet_tpu.attribute", "mxnet_tpu.name",
)


def _env_allowlist():
    """Operator-extensible trust: MXNET_KVSTORE_PICKLE_ALLOWLIST is a
    comma-separated list of ``module`` or ``module:name`` entries (a
    bare module admits every name in it).  This is the escape hatch for
    the reference's custom-optimizer flow — a user-defined optimizer
    class living in ``__main__``/their own package can be shipped to
    the servers by explicitly naming its module in the job env (the
    launcher propagates env to every role)."""
    raw = os.environ.get("MXNET_KVSTORE_PICKLE_ALLOWLIST", "")
    entries = []
    for item in raw.split(","):
        item = item.strip()
        if item:
            mod, _, name = item.partition(":")
            entries.append((mod, name or None))
    return entries


class _RestrictedUnpickler(pickle.Unpickler):  # analysis: allow(unsafe-pickle): this IS the allowlisted decoder — find_class below enforces the class allowlist every other site must route through
    def find_class(self, module, name):
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        if (module, name) in _SAFE_GLOBALS:
            return super().find_class(module, name)
        if any(module == m or module.startswith(m + ".")
               for m in _SAFE_MXT_MODULES):
            import inspect
            obj = super().find_class(module, name)
            if inspect.isclass(obj):
                return obj
        for mod, ename in _env_allowlist():
            if module == mod and (ename is None or name == ename):
                return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"kvstore wire: refusing to unpickle {module}.{name} "
            "(not in the transport allowlist; for custom optimizer/"
            "updater classes set MXNET_KVSTORE_PICKLE_ALLOWLIST="
            f"{module}:{name} on every job role)")


def _restricted_loads(data):
    """pickle.loads through the transport allowlist — for wire skeletons
    and peer-supplied control blobs (shipped optimizers, state blobs)."""
    import io
    return _RestrictedUnpickler(io.BytesIO(data)).load()


def _set_nodelay(sock):
    """Disable Nagle on a kvstore data socket.  A frame is two-plus
    ``sendall`` calls (header+skeleton, then each raw tensor buffer);
    with Nagle on, the small header write can sit in the kernel waiting
    on the peer's delayed ACK before the tensor bytes follow — a
    ~40 ms-class stall per frame on a real network (docs/PERF_NOTES.md
    round 9).  Loopback never shows it, which is exactly why it must be
    set unconditionally at connect/accept rather than found later on a
    chip."""
    import socket as _socket
    try:
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
    except (OSError, AttributeError):
        pass   # non-TCP socket (tests stub with socketpairs)


def _send_msg(sock, obj, fi_role=None):
    """Zero-copy framed send (skeleton pickle + raw tensor buffers).
    ``fi_role`` tags DATA-channel traffic for the deterministic fault-
    injection hooks ("client" may be severed at an exact message,
    "server" may delay acks); untagged sends (heartbeats) are exempt so
    a plan hits only what it targets."""
    if fi_role == "client":
        faultinject.client_send(sock)
    elif fi_role == "server":
        faultinject.server_reply_delay()
    bufs = []
    skel = pickle.dumps(_pack(obj, bufs),
                        protocol=pickle.HIGHEST_PROTOCOL)
    total = 4 + len(skel) + sum(a.nbytes for a in bufs)
    _prof.record_channel_bytes("sent", 8 + total)
    sock.sendall(struct.pack(">QI", total, len(skel)) + skel)
    for arr in bufs:
        sock.sendall(memoryview(arr).cast("B"))
    if fi_role == "client":
        faultinject.client_sent(sock)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock, fi_role=None):
    if fi_role == "client":
        faultinject.client_recv(sock)
    total, skel_len = struct.unpack(">QI", _recv_exact(sock, 12))
    skel = _restricted_loads(_recv_exact(sock, skel_len))
    body = _recv_exact(sock, total - 4 - skel_len)
    _prof.record_channel_bytes("recv", 8 + total)
    refs = []
    _collect_bufs(skel, refs)
    if not refs:
        return skel
    offsets, off = {}, 0
    for ref in sorted(refs, key=lambda r: r.i):
        offsets[ref.i] = off
        off += ref.nbytes
    return _unpack(skel, body, offsets)


class KVStoreServer:
    """One async parameter-server shard.

    Holds a slice of the key space (workers route each key to
    ``crc32(key) % num_servers``); applies the installed optimizer to
    every arriving gradient immediately (async SGD), or stores the pushed
    value verbatim when no optimizer is installed (the reference's
    assign-on-merge semantics, kvstore_local.h:173).
    """

    def __init__(self, server_id=0, num_workers=1,
                 host="127.0.0.1", port=0, hb_timeout=None):
        self.server_id = server_id
        self.num_workers = num_workers
        self._store = {}          # key -> NDArray (host CPU)
        self._updater = None
        self._lock = threading.Lock()
        self._barrier_cv = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_ranks = set()   # ranks currently arrived
        self._stop = threading.Event()
        self._listener = socket.create_server((host, port))
        self._listener.settimeout(0.5)
        self.port = self._listener.getsockname()[1]
        self._threads = []
        self._conns = []
        # exactly-once: per-client (rank, nonce) dedup window.  A client
        # that reconnects replays its unacked request with the SAME
        # (client_id, seq); the cached reply is returned without
        # re-applying — a replayed push that was already applied is
        # acked idempotently (reference analog: ps-lite resender).
        # The channel is serial, so the live replay set is ONE envelope —
        # but the window must stay >= 2: a zombie connection's handler
        # can process its final buffered request AFTER the replay (and
        # the client's next request) completed on the new connection,
        # and that late duplicate must still hit the cache.  Pull
        # replies embed whole arrays, so the window is deliberately
        # small; client windows are LRU-capped too (a relaunched client
        # arrives under a fresh nonce and must not pin the old one).
        # With the PIPELINED client (MXNET_KVSTORE_WINDOW envelopes in
        # flight) a reconnect replays the whole window, so the reply
        # cache must cover it: default 2x the client window (plus the
        # zombie-duplicate slack), read from the same env the launcher
        # exports to every role.
        self._dedup_window = int(_env(
            "MXNET_KVSTORE_DEDUP_WINDOW",
            max(8, 2 * int(_env("MXNET_KVSTORE_WINDOW", 8)))))
        self._dedup_clients = 256
        self._dedup = OrderedDict()   # client_id -> {inflight, replies}
        self._dedup_cv = threading.Condition()
        self.dedup_count = 0          # replays served from the window
        # liveness: last ping (or enveloped request) per worker rank.
        # Barrier waits stay UNBOUNDED by design — but a rank that was
        # alive and went silent past hb_timeout turns the wait into an
        # error naming the missing ranks instead of blocking forever.
        self._hb_timeout = float(
            hb_timeout if hb_timeout is not None
            else _env("MXNET_KVSTORE_HEARTBEAT_TIMEOUT", 15.0))
        self._hb_seen = {}            # rank -> last monotonic timestamp
        # extension ops: subsystems riding the kvstore wire (the serving
        # tier) register additional envelope types here instead of
        # forking the frame/allowlist/exactly-once stack.  Dispatch is
        # the LAST resort in _handle, so an extension can never shadow a
        # core op.
        self._ext_ops = {}

    def register_op(self, op: str, fn) -> None:
        """Register an extension envelope type: ``fn(msg, rank) ->
        reply payload``.  The handler runs under the same exactly-once
        envelope, allowlisted decode and error-reply contract as the
        built-in ops; core op names are reserved."""
        if op in ("ping", "init", "push", "push_multi", "pull",
                  "pull_rows", "assign", "get_states", "set_states",
                  "command", "barrier", "req"):
            raise ValueError(f"cannot override core kvstore op {op!r}")
        self._ext_ops[op] = fn

    # -- request handlers ----------------------------------------------------
    def _apply_push(self, key, arr):
        """reference kvstore_dist_server.h:405-430: async branch applies the
        updater right away; a pushed value with no updater replaces the
        stored one (assign, not add).  A compressed payload (2bit/fp16
        wire mode) is dequantized here — the stored weight stays fp32."""
        from .ndarray import NDArray
        import jax.numpy as jnp
        if isinstance(arr, WirePayload):
            arr = _decompress(arr)
        grad = NDArray(jnp.asarray(arr))
        with self._lock:
            stored = self._store.get(key)
            if stored is None:
                raise KeyError(f"push to uninitialized key {key!r}")
            if self._updater is not None:
                self._updater(_key_int(key), grad, stored)
            else:
                stored._set_data(grad._data)

    def _handle(self, msg, rank=None):
        op = msg[0]
        if op == "ping":
            # heartbeat: out-of-band liveness (its own connection — the
            # data channel may legitimately block in a barrier)
            if len(msg) > 1:
                self._note_ping(msg[1])
            return None
        if op == "init":
            # first init wins; later inits of the same key are ignored
            # (reference: the server keeps the first-arriving value,
            # kvstore_dist_server.h DataHandleDefault init path)
            _, key, arr = msg
            from .ndarray import NDArray
            import jax.numpy as jnp
            with self._lock:
                if key not in self._store:
                    self._store[key] = NDArray(jnp.asarray(arr))
            return None
        if op == "push":
            _, key, arr = msg
            self._apply_push(key, arr)
            return None
        if op == "push_multi":
            # coalesced small-key push: one envelope, applied in order
            # (the worker groups sub-threshold keys bound for this shard
            # into a single frame — one RTT instead of K)
            _, entries = msg
            for key, arr in entries:
                self._apply_push(key, arr)
            return None
        if op == "assign":
            # store the pushed value VERBATIM, bypassing any installed
            # updater, creating the key if absent.  Control-plane
            # metadata (the serving weight-version counter) must be a
            # plain register: routing it through "push" would hand it to
            # the SGD updater as a gradient.
            _, key, arr = msg
            from .ndarray import NDArray
            import jax.numpy as jnp
            if isinstance(arr, WirePayload):
                arr = _decompress(arr)
            with self._lock:
                stored = self._store.get(key)
                if stored is None:
                    self._store[key] = NDArray(jnp.asarray(arr))
                else:
                    stored._set_data(jnp.asarray(arr))
            return None
        if op == "pull":
            _, key = msg
            with self._lock:
                stored = self._store.get(key)
                if stored is None:
                    raise KeyError(f"pull of uninitialized key {key!r}")
                return np.asarray(stored.asnumpy())
        if op == "pull_rows":
            # O(requested rows) row-sparse pull (reference:
            # DataHandleRowSparse, kvstore_dist_server.h:211 — only the
            # requested rows travel)
            _, key, ids = msg
            with self._lock:
                stored = self._store.get(key)
                if stored is None:
                    raise KeyError(f"pull of uninitialized key {key!r}")
                full = np.asarray(stored.asnumpy())
                return full[ids], full.shape
        if op == "get_states":
            # optimizer-state checkpointing: this shard's {key: state}
            # dict, optionally with the optimizer itself (reference:
            # server-side optimizer states live in the server,
            # kvstore_dist_server.h:131).  Return only keys the shard
            # OWNS (is in _store): set_states broadcasts the full merged
            # union to every server, so after further training the
            # updater also holds stale loaded copies of OTHER shards'
            # keys — without this filter a save→load→train→save flow
            # with ≥2 servers lets a stale copy overwrite the owner's
            # fresh state in the client-side merge (ADVICE r5).
            dump = bool(msg[1]) if len(msg) > 1 else False
            with self._lock:
                if self._updater is None:
                    return None
                states = self._updater.states
                if self._store:
                    owned = {_key_int(k) for k in self._store}
                    states = {k: v for k, v in states.items()
                              if k in owned}
                # an EMPTY store means this shard never saw an init/push
                # (pure load→save relay, e.g. checkpoint migration):
                # return everything — the client-side merge prefers each
                # key's OWNER, so these can never shadow fresh state
                return pickle.dumps((states, self._updater.optimizer)
                                    if dump else states)
        if op == "set_states":
            _, blob = msg
            with self._lock:
                if self._updater is None:
                    raise RuntimeError(
                        "set_states before an optimizer was installed")
                # decode the peer-supplied blob through the transport
                # allowlist (Updater.set_states accepts the loaded dict)
                self._updater.set_states(_restricted_loads(blob))
            return None
        if op == "command":
            _, head, body = msg
            return self._command(head, body)
        if op == "barrier":
            self._barrier(rank)
            return None
        ext = self._ext_ops.get(op)
        if ext is not None:
            return ext(msg, rank)
        raise ValueError(f"unknown op {op!r}")

    # -- exactly-once delivery ----------------------------------------------
    def _exactly_once(self, client_id, seq, inner):
        """Serve one enveloped request with at-most-once application.

        A replayed (client_id, seq) that already completed returns the
        CACHED reply (``dedup_count`` ticks); one still in flight on
        another connection thread (e.g. the original connection died
        while its handler blocks in a barrier) is WAITED for, never
        double-entered — the replay then also gets the cached reply."""
        cid = tuple(client_id) if isinstance(client_id, list) else client_id
        if isinstance(cid, tuple) and cid:
            self._note_ping(cid[0])   # any request is liveness evidence
        with self._dedup_cv:
            st = self._dedup.get(cid)
            if st is None:
                st = self._dedup[cid] = {"inflight": set(),
                                         "replies": OrderedDict()}
            self._dedup.move_to_end(cid)
            while len(self._dedup) > self._dedup_clients:
                old_cid, old_st = next(iter(self._dedup.items()))
                if old_st["inflight"]:
                    break   # never drop a window with work in flight
                self._dedup.popitem(last=False)
            while seq in st["inflight"] and not self._stop.is_set():
                self._dedup_cv.wait(0.1)
            if seq in st["replies"]:
                self.dedup_count += 1
                return st["replies"][seq]
            st["inflight"].add(seq)
        rank = cid[0] if isinstance(cid, tuple) and cid else None
        reply = None
        try:
            try:
                reply = ("ok", self._handle(inner, rank=rank))
            except Exception as exc:  # noqa: BLE001 — to the client
                reply = ("err", f"{type(exc).__name__}: {exc}")
        finally:
            # cache + un-inflight atomically: a replay racing this exact
            # moment must see either "in flight" or the cached reply,
            # never a gap it could re-apply through
            with self._dedup_cv:
                st["inflight"].discard(seq)
                if reply is not None:
                    st["replies"][seq] = reply
                    while len(st["replies"]) > self._dedup_window:
                        st["replies"].popitem(last=False)
                self._dedup_cv.notify_all()
        return reply

    # -- liveness ------------------------------------------------------------
    def _note_ping(self, rank):
        try:
            rank = int(rank)
        except (TypeError, ValueError):
            return
        with self._barrier_cv:
            self._hb_seen[rank] = time.monotonic()

    def _silent_ranks(self):
        """Worker ranks that HAVE been heard from and then went silent
        past hb_timeout.  A rank that never pinged is indistinguishable
        from one that is still starting up — never declared dead.
        Caller holds _barrier_cv."""
        if self._hb_timeout <= 0:
            return set()
        now = time.monotonic()
        return {r for r, t in self._hb_seen.items()
                if r < self.num_workers and now - t > self._hb_timeout}

    def _command(self, head, body):
        """reference kvstore_dist_server.h:149-162 ``CommandHandle``."""
        if head == K_STOP_SERVER:
            self._stop.set()
            with self._barrier_cv:
                self._barrier_cv.notify_all()
            return None
        if head == K_CONTROLLER:
            from . import optimizer as opt
            with self._lock:
                # peer-supplied blob: decode through the transport
                # allowlist, never stock pickle
                self._updater = opt.get_updater(_restricted_loads(body))
            return None
        return None  # kSyncMode etc.: accepted, no-op in the async server

    def _barrier(self, rank=None):
        """Count one arrival per worker; release everyone when all
        ``num_workers`` are in (reference: Postoffice::Barrier).

        The wait itself stays UNBOUNDED (a slow worker is legal) — but
        when the heartbeat registry shows a missing rank went SILENT
        past hb_timeout, the wait fails naming the dead ranks instead of
        blocking the surviving workers forever."""
        with self._barrier_cv:
            gen = self._barrier_gen
            if rank is not None:
                self._barrier_ranks.add(rank)
            self._barrier_count += 1
            if self._barrier_count >= self.num_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_ranks = set()
                self._barrier_cv.notify_all()
                return
            while self._barrier_gen == gen and not self._stop.is_set():
                self._barrier_cv.wait(0.1)
                if self._barrier_gen != gen or self._stop.is_set():
                    break
                silent = self._silent_ranks() - self._barrier_ranks
                if silent:
                    arrived = sorted(self._barrier_ranks)
                    # unwind this arrival so a later retry re-enters
                    # cleanly once the dead rank is replaced
                    self._barrier_count -= 1
                    if rank is not None:
                        self._barrier_ranks.discard(rank)
                    raise RuntimeError(
                        "barrier timed out: worker rank(s) %s missing "
                        "(no heartbeat for > %.1fs); arrived rank(s): %s"
                        % (sorted(silent), self._hb_timeout, arrived))

    # -- connection plumbing -------------------------------------------------
    def _serve_conn(self, conn):
        try:
            with conn:
                while not self._stop.is_set():
                    try:
                        msg = _recv_msg(conn)
                    except (ConnectionError, OSError):
                        return
                    if msg and msg[0] == "req":
                        # client envelope: (op, client_id, seq, inner) —
                        # the exactly-once path (reconnect + replay)
                        _, cid, seq, inner = msg
                        reply = self._exactly_once(cid, seq, inner)
                        role = "server"
                    else:
                        # raw message (heartbeat pings, legacy callers):
                        # NOT fault-injection targetable — a delay-acks
                        # plan must never stall the liveness signal
                        # (faultinject.py's heartbeat-exemption contract)
                        try:
                            reply = ("ok", self._handle(msg))
                        except Exception as exc:  # noqa: BLE001
                            reply = ("err",
                                     f"{type(exc).__name__}: {exc}")
                        role = None
                    try:
                        _send_msg(conn, reply, fi_role=role)
                    except (ConnectionError, OSError):
                        # the client died / reconnected while we worked:
                        # the reply stays in the dedup window, so the
                        # replay on the new connection is acked from
                        # cache — drop this connection only
                        return
        except Exception:  # noqa: BLE001 — conn died mid-reply
            pass

    def run(self):
        """Blocking accept loop; returns after a kStopServer command."""
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                if faultinject.server_accept(conn):
                    continue   # injected refusal: already closed
                _set_nodelay(conn)
                t = threading.Thread(target=self._serve_conn, args=(conn,),
                                     daemon=True)
                t.start()
                self._threads.append(t)
                self._conns.append(conn)
        finally:
            self._listener.close()

    def stop(self):
        self._stop.set()
        with self._barrier_cv:
            self._barrier_cv.notify_all()
        # close live connections too: a handler blocked in _recv_msg only
        # re-checks _stop after servicing a request, so without this a
        # "stopped" server still answers one more op per connection —
        # clients must see EOF promptly (and the crash-simulation tests
        # rely on exactly that)
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass

    def start_background(self):
        """Run the accept loop in a daemon thread (in-process tests)."""
        # analysis: allow(bare-thread): a crash unwinds through run()'s finally, closing the listener — every client observes it as refused connects within its retry budget, and in-flight conns keep their own _serve_conn handlers
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        return t


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _init_kvstore_server_module():
    """Turn a ``DMLC_ROLE=server`` process into a blocking server, then
    exit — the reference hook verbatim (python/mxnet/kvstore_server.py:75:
    importing the library in a server-role process never returns to user
    code)."""
    if os.environ.get("DMLC_ROLE") != "server":
        return
    # This function blocks INSIDE `import mxnet_tpu`, so the package module
    # would stay flagged as initializing forever — and any connection
    # thread that triggers `import mxnet_tpu.*` (pickle.loads of an
    # optimizer does) would block on the parent module's import lock:
    # a guaranteed deadlock.  The package body is fully executed at this
    # point (this hook is its last statement), so clear the flag, and
    # pre-import everything the request handlers touch.
    import mxnet_tpu  # noqa: PLC0415 — self, already in sys.modules
    spec = getattr(mxnet_tpu, "__spec__", None)
    if spec is not None:
        spec._initializing = False
    from . import optimizer as _opt  # noqa: F401 — handler dependency
    from . import ndarray as _nd     # noqa: F401
    import jax.numpy as _jnp         # noqa: F401
    sid = int(os.environ.get("DMLC_SERVER_ID", "0"))
    uris = os.environ.get("MXT_SERVER_URIS", "")
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    host, port = "127.0.0.1", 0
    if uris:
        my = uris.split(",")[sid]
        host, port = my.rsplit(":", 1)
        port = int(port)
        # loopback-advertised servers (local launcher) bind loopback ONLY
        # — _recv_msg unpickles from any peer, so never expose the port
        # beyond what the deployment needs; ssh-mode servers must accept
        # remote workers and bind all interfaces (trusted-cluster model,
        # see module docstring)
        if host not in ("127.0.0.1", "localhost"):
            host = "0.0.0.0"
    server = KVStoreServer(server_id=sid, num_workers=num_workers,
                           host=host, port=port)
    print(f"kvstore server {sid} listening on port {server.port}",
          flush=True)
    server.run()
    sys.exit(0)
