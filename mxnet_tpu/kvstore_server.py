"""Async parameter-server process: the backend of kvstore ``dist_async``.

TPU-native re-design of the reference's server stack
(src/kvstore/kvstore_dist_server.h; bootstrap in
python/mxnet/kvstore_server.py:28-75).  The reference runs ps-lite
``KVServer`` processes over ZMQ; async mode applies each worker's push to
the stored weight the moment it arrives (kvstore_dist_server.h:405-430 —
``DataHandleDefault``'s non-sync branch runs ``updater_(key, recved,
&stored)`` immediately, no cross-worker aggregation barrier).  That is
the one kvstore mode SPMD collectives cannot express — allreduce is
synchronous by construction — so here the servers come back as plain
host processes:

* transport: length-prefixed pickled messages over TCP (ps-lite/ZMQ's
  role; no new dependency).
* apply: one global store lock — the reference server is ALSO serialized
  (its single-thread ``Executor`` run loop, kvstore_dist_server.h:50-106),
  so per-push locking is the faithful concurrency model.
* placement: servers pin ``JAX_PLATFORMS=cpu`` (set by tools/launch.py);
  updates are tiny CPU math and a server must never touch a TPU — the
  accelerators belong to the workers, exactly as the reference gives
  servers no GPU context.

Process model mirrors the reference exactly: ``tools/launch.py -s S``
starts S copies of the *same user command* with ``DMLC_ROLE=server``;
importing :mod:`mxnet_tpu` in such a process enters the blocking server
loop and exits when the job is torn down, so user training scripts work
unmodified as server commands (reference kvstore_server.py:75
``_init_kvstore_server_module``).

Worker-side counterpart: :class:`mxnet_tpu.kvstore.KVStoreDistAsync`.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import sys
import threading
import time
from collections import OrderedDict

import numpy as np

from . import faultinject
from . import profiler as _prof
from . import tracing as _tr
from . import wirecodec as _codec
from . import health as _health
from .analysis import hb as _hb
from .base import env as _env
from .compression import (RowSparsePayload, WirePayload,
                          decompress as _decompress,
                          validate_rowsparse as _validate_rowsparse)

# reference command codes (kvstore_dist_server.h:44-45): kStopServer=-1
# tears down, kSyncMode=-2 switches the reference server to sync
# aggregation (a documented no-op here — this server IS the async mode,
# and doubles as the channel-flush sync token), and any head >= 0 routes
# to the controller (CommandHandle :150-162), where head 0 carries the
# pickled optimizer (python/mxnet/kvstore.py set_optimizer).
K_CONTROLLER = 0
K_STOP_SERVER = -1
K_SYNC_MODE = -2


# -- wire frame ---------------------------------------------------------------
# A message is ONE frame:
#
#     >Q  total length of everything after this field
#     >I  skeleton length S
#     S bytes   pickled SKELETON: the message with every ndarray replaced
#               by a _Buf(index, dtype, shape) placeholder
#     ...       the raw tensor buffers, concatenated in index order
#
# Tensors therefore never pass through pickle: the sender writes each
# array's memoryview straight to the socket (no tobytes() copy) and the
# receiver maps np.frombuffer views over one contiguous read.  The
# skeleton — the only remaining pickled bytes from a peer — is decoded
# through a class-allowlisted Unpickler (below).


class _Buf:
    """Skeleton placeholder for a raw tensor buffer riding after it."""

    __slots__ = ("i", "dtype", "shape")

    def __init__(self, i, dtype, shape):
        self.i = i
        self.dtype = dtype
        self.shape = tuple(shape)

    def __reduce__(self):
        return (_Buf, (self.i, self.dtype, self.shape))

    @property
    def nbytes(self):
        return (int(np.prod(self.shape, dtype=np.int64))
                * np.dtype(self.dtype).itemsize)


def _pack(obj, bufs):
    """Replace every ndarray in ``obj`` with a _Buf placeholder,
    appending the (contiguous) array to ``bufs``.  Object-dtype arrays
    cannot ride a raw buffer and stay in the skeleton."""
    if isinstance(obj, np.ndarray) and not obj.dtype.hasobject:
        # NOTE: ascontiguousarray promotes 0-d to 1-d — keep the
        # logical shape from the original array
        arr = np.ascontiguousarray(obj)
        ref = _Buf(len(bufs), arr.dtype.str, obj.shape)
        bufs.append(arr)
        return ref
    if isinstance(obj, tuple):
        return tuple(_pack(x, bufs) for x in obj)
    if isinstance(obj, list):
        return [_pack(x, bufs) for x in obj]
    if isinstance(obj, dict):
        return {k: _pack(v, bufs) for k, v in obj.items()}
    if isinstance(obj, WirePayload):
        return WirePayload(obj.kind, obj.shape, obj.threshold,
                           _pack(obj.data, bufs))
    if isinstance(obj, RowSparsePayload):
        return RowSparsePayload(_pack(obj.indices, bufs), obj.nrows,
                                _pack(obj.data, bufs))
    return obj


def _unpack(obj, body, offsets):
    if isinstance(obj, _Buf):
        return np.frombuffer(
            body, dtype=np.dtype(obj.dtype),
            count=int(np.prod(obj.shape, dtype=np.int64)),
            offset=offsets[obj.i]).reshape(obj.shape)
    if isinstance(obj, tuple):
        return tuple(_unpack(x, body, offsets) for x in obj)
    if isinstance(obj, list):
        return [_unpack(x, body, offsets) for x in obj]
    if isinstance(obj, dict):
        return {k: _unpack(v, body, offsets) for k, v in obj.items()}
    if isinstance(obj, WirePayload):
        return WirePayload(obj.kind, obj.shape, obj.threshold,
                           _unpack(obj.data, body, offsets))
    if isinstance(obj, RowSparsePayload):
        return RowSparsePayload(_unpack(obj.indices, body, offsets),
                                obj.nrows,
                                _unpack(obj.data, body, offsets))
    return obj


def _collect_bufs(obj, refs):
    if isinstance(obj, _Buf):
        refs.append(obj)
    elif isinstance(obj, (tuple, list)):
        for x in obj:
            _collect_bufs(x, refs)
    elif isinstance(obj, dict):
        for v in obj.values():
            _collect_bufs(v, refs)
    elif isinstance(obj, WirePayload):
        _collect_bufs(obj.data, refs)
    elif isinstance(obj, RowSparsePayload):
        _collect_bufs(obj.indices, refs)
        _collect_bufs(obj.data, refs)


# -- restricted deserialization ----------------------------------------------
# _recv_msg decodes bytes from ANY connected peer; a stock pickle.loads
# would let that peer name arbitrary importable callables (os.system,
# ...).  With tensors moved to raw-buffer frames, the remaining pickled
# skeletons/blobs only ever reference our own classes plus a handful of
# numpy/jax reconstruction helpers — so find_class admits mxnet_tpu
# classes (the reference semantics ship user optimizer/updater classes)
# plus an EXPLICIT (module, name) set.  Whole-root allowances for
# numpy/jax would re-open the door: numpy alone ships importable
# command/exec helpers (numpy.testing.runstring, distutils exec_command)
# that a REDUCE opcode could call with attacker arguments.
_SAFE_BUILTINS = frozenset({
    "complex", "frozenset", "set", "slice", "range", "bytearray",
    "object", "tuple", "list", "dict",
})
_SAFE_GLOBALS = frozenset({
    ("collections", "OrderedDict"),
    ("numpy", "dtype"),
    ("numpy", "ndarray"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "_reconstruct"),   # older numpy pickles
    ("numpy.core.multiarray", "scalar"),
    ("jax._src.array", "_reconstruct_array"),
    # the wire marker classes, by NAME: their home modules also hold
    # classes with side-effecting constructors (KVStoreServer binds a
    # listening socket) that must stay out of REDUCE reach
    ("mxnet_tpu.kvstore_server", "_Buf"),
    ("mxnet_tpu.compression", "WirePayload"),
    ("mxnet_tpu.compression", "RowSparsePayload"),
})
# Only CLASSES from these modules — the pickle surface the reference
# semantics actually ship (optimizer/updater/scheduler objects, NDArray
# states).  A whole-package allowance would admit module-level
# callables and classes with side-effecting constructors (recordio/
# checkpoint file writers, server sockets) as REDUCE gadgets.
_SAFE_MXT_MODULES = (
    "mxnet_tpu.optimizer", "mxnet_tpu.lr_scheduler",
    "mxnet_tpu.ndarray", "mxnet_tpu.initializer",
    "mxnet_tpu.gluon.parameter",
    # Module.init_optimizer ships optimizers carrying sym/idx2name
    # context (reference: optimizer.py Optimizer attributes)
    "mxnet_tpu.symbol", "mxnet_tpu.attribute", "mxnet_tpu.name",
)


def _env_allowlist():
    """Operator-extensible trust: MXNET_KVSTORE_PICKLE_ALLOWLIST is a
    comma-separated list of ``module`` or ``module:name`` entries (a
    bare module admits every name in it).  This is the escape hatch for
    the reference's custom-optimizer flow — a user-defined optimizer
    class living in ``__main__``/their own package can be shipped to
    the servers by explicitly naming its module in the job env (the
    launcher propagates env to every role)."""
    raw = os.environ.get("MXNET_KVSTORE_PICKLE_ALLOWLIST", "")
    entries = []
    for item in raw.split(","):
        item = item.strip()
        if item:
            mod, _, name = item.partition(":")
            entries.append((mod, name or None))
    return entries


class _RestrictedUnpickler(pickle.Unpickler):  # analysis: allow(unsafe-pickle): this IS the allowlisted decoder — find_class below enforces the class allowlist every other site must route through
    def find_class(self, module, name):
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        if (module, name) in _SAFE_GLOBALS:
            return super().find_class(module, name)
        if any(module == m or module.startswith(m + ".")
               for m in _SAFE_MXT_MODULES):
            import inspect
            obj = super().find_class(module, name)
            if inspect.isclass(obj):
                return obj
        for mod, ename in _env_allowlist():
            if module == mod and (ename is None or name == ename):
                return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"kvstore wire: refusing to unpickle {module}.{name} "
            "(not in the transport allowlist; for custom optimizer/"
            "updater classes set MXNET_KVSTORE_PICKLE_ALLOWLIST="
            f"{module}:{name} on every job role)")


def _restricted_loads(data):
    """pickle.loads through the transport allowlist — for wire skeletons
    and peer-supplied control blobs (shipped optimizers, state blobs)."""
    import io
    return _RestrictedUnpickler(io.BytesIO(data)).load()


def _set_nodelay(sock):
    """Disable Nagle on a kvstore data socket.  A frame is two-plus
    ``sendall`` calls (header+skeleton, then each raw tensor buffer);
    with Nagle on, the small header write can sit in the kernel waiting
    on the peer's delayed ACK before the tensor bytes follow — a
    ~40 ms-class stall per frame on a real network (docs/PERF_NOTES.md
    round 9).  Loopback never shows it, which is exactly why it must be
    set unconditionally at connect/accept rather than found later on a
    chip."""
    import socket as _socket
    try:
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
    except (OSError, AttributeError):
        pass   # non-TCP socket (tests stub with socketpairs)


def _iov_max() -> int:
    try:
        return min(int(os.sysconf("SC_IOV_MAX")), 1024)
    except (AttributeError, OSError, ValueError):
        return 16


_IOV_MAX = _iov_max()


def _send_vec(sock, parts) -> int:
    """Write ``parts`` (bytes-likes) in order with as few syscalls as
    possible: vectored ``sendmsg`` chunked at IOV_MAX with a partial-
    send resume loop, or per-part ``sendall`` when the platform lacks
    sendmsg / MXNET_KVSTORE_SENDMSG=0.  Returns the syscall count."""
    # drop zero-length parts BEFORE casting (empty iovecs would stall
    # the loop, and casting a 0-in-shape ndarray view raises)
    parts = [m.cast("B") for m in (memoryview(p) for p in parts)
             if m.nbytes]
    n = 0
    if not (_env("MXNET_KVSTORE_SENDMSG", 1)
            and hasattr(sock, "sendmsg")):
        for p in parts:
            sock.sendall(p)
            n += 1
        return n
    i = 0
    while i < len(parts):
        sent = sock.sendmsg(parts[i:i + _IOV_MAX])
        n += 1
        while sent > 0:
            pn = parts[i].nbytes
            if sent >= pn:
                sent -= pn
                i += 1
            else:
                parts[i] = parts[i][sent:]
                sent = 0
    return n


def _frame_parts(obj, binary_ok):
    """Encode ``obj`` into its on-wire frame as an ordered list of
    bytes-likes plus counter meta ``(parts, frame_bytes, codec_bytes,
    pickle_bytes)``.  Both transports carry the IDENTICAL bytes — the
    socket path scatter-gathers the parts through ``sendmsg``
    (:func:`_send_msg`), the same-host shm lane memcpys them into a
    ring record (mxnet_tpu/shmlane.py) — so receivers self-
    discriminate on the first byte either way (0xB1 = v2 binary frame,
    0x00 = the legacy pickle frame's ``>Q`` high byte)."""
    if binary_ok and _codec.is_hot(obj):
        enc = _codec.encode_frame(obj)
        if enc is not None:
            head, bufs = enc
            total = len(head) + sum(a.nbytes for a in bufs)
            return [head] + list(bufs), total, len(head) - 13, 0
    bufs = []
    skel = pickle.dumps(_pack(obj, bufs),
                        protocol=pickle.HIGHEST_PROTOCOL)
    total = 4 + len(skel) + sum(a.nbytes for a in bufs)
    # header as its own buffer — NOT `header + skel`, which would
    # copy the whole skeleton to save one iovec
    parts = [struct.pack(">QI", total, len(skel)), skel]
    parts += bufs
    return parts, 8 + total, 0, len(skel)


def _frame_obj(data):
    """Decode ONE complete frame from a contiguous buffer — the shm
    ring pops whole records, so unlike :func:`_recv_msg` there is no
    short-read loop, but the two formats and the restricted-pickle
    trust boundary are identical."""
    view = memoryview(data)
    if view[0] == _codec.FRAME_MAGIC:
        total, desc_len = struct.unpack(">QI", view[1:13])
        desc = bytes(view[13:13 + desc_len])
        body = bytes(view[13 + desc_len:13 + total - 4])
        return _codec.decode_frame(desc, body)
    total, skel_len = struct.unpack(">QI", view[:12])
    skel = _restricted_loads(bytes(view[12:12 + skel_len]))
    body = bytes(view[12 + skel_len:12 + total - 4])
    refs = []
    _collect_bufs(skel, refs)
    if not refs:
        return skel
    offsets, off = {}, 0
    for ref in sorted(refs, key=lambda r: r.i):
        offsets[ref.i] = off
        off += ref.nbytes
    return _unpack(skel, body, offsets)


def _send_msg(sock, obj, fi_role=None, byte_kind="sent"):
    """Zero-copy framed send: the registry-generated binary codec for
    hot messages on negotiated connections (wirecodec frame v2), the
    skeleton-pickle frame for everything else — one vectored syscall
    per frame either way (_send_vec).  ``fi_role`` tags DATA-channel
    traffic for the deterministic fault-injection hooks ("client" may
    be severed at an exact message, "server" may delay acks); untagged
    sends (heartbeats, hellos) are exempt so a plan hits only what it
    targets.  ``byte_kind`` names the byte counter family the frame
    lands in: the default "sent" is the TCP data wire to the parameter
    servers; the hierarchical tier's in-host mesh channels count under
    "ici_sent" (or "shm_sent" when the same-host lane carries them),
    and control-plane traffic (heartbeats, roster beats, hellos) under
    "control" so bench.py reports gradients, mesh, and control
    separately (profiler.wire_bytes_total / ici_bytes_total /
    shm_bytes_total / control_bytes_total)."""
    if fi_role == "client":
        faultinject.client_send(sock)
    elif fi_role == "server":
        faultinject.server_reply_delay()
        if faultinject.server_blackhole():
            # injected gray failure: the reply is swallowed, the
            # connection stays open — the caller believes it sent
            return
    parts, frame_bytes, codec_bytes, pickle_bytes = _frame_parts(
        obj, _codec.sock_binary(sock))
    if codec_bytes:
        _prof.record_serialization("codec_bytes", codec_bytes)
    if pickle_bytes and not _prof.is_control_byte_kind(byte_kind):
        _prof.record_serialization("pickle_bytes", pickle_bytes)
    _prof.record_channel_bytes(byte_kind, frame_bytes)
    _prof.record_serialization("send_syscalls", _send_vec(sock, parts))
    if fi_role == "client":
        faultinject.client_sent(sock)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock, fi_role=None, byte_kind="recv"):
    """Receive one frame of either format — a v2 binary frame's first
    byte is the 0xB1 magic, a legacy pickle frame's is the always-zero
    high byte of its ``>Q`` total, so the receiver self-discriminates
    and accepts both regardless of negotiation (which only gates what
    a sender emits)."""
    if fi_role == "client":
        faultinject.client_recv(sock)
    hdr = _recv_exact(sock, 12)
    if hdr[0] == _codec.FRAME_MAGIC:
        hdr += _recv_exact(sock, 1)
        total, desc_len = struct.unpack(">QI", hdr[1:13])
        if desc_len + 4 > total:
            raise ValueError("wirecodec: descriptor overruns frame")
        desc = _recv_exact(sock, desc_len)
        body = _recv_exact(sock, total - 4 - desc_len)
        _prof.record_channel_bytes(byte_kind, 9 + total)
        return _codec.decode_frame(desc, body)
    total, skel_len = struct.unpack(">QI", hdr)
    skel = _restricted_loads(_recv_exact(sock, skel_len))
    body = _recv_exact(sock, total - 4 - skel_len)
    _prof.record_channel_bytes(byte_kind, 8 + total)
    refs = []
    _collect_bufs(skel, refs)
    if not refs:
        return skel
    offsets, off = {}, 0
    for ref in sorted(refs, key=lambda r: r.i):
        offsets[ref.i] = off
        off += ref.nbytes
    return _unpack(skel, body, offsets)


class KVStoreServer:
    """One async parameter-server shard.

    Holds a slice of the key space (workers route each key to
    ``crc32(key) % num_servers``); applies the installed optimizer to
    every arriving gradient immediately (async SGD), or stores the pushed
    value verbatim when no optimizer is installed (the reference's
    assign-on-merge semantics, kvstore_local.h:173).
    """

    def __init__(self, server_id=0, num_workers=1,
                 host="127.0.0.1", port=0, hb_timeout=None,
                 elastic=None, uri=None, roster_servers=None):
        self.server_id = server_id
        self.num_workers = num_workers
        # the hot shared containers are hb-tracked: identity in
        # production, race-checked wrappers under the happens-before
        # sanitizer's shim (mxnet_tpu.analysis.hb)
        self._store = _hb.track({}, "KVStoreServer._store")
        self._updater = None
        self._lock = threading.Lock()
        self._barrier_cv = threading.Condition()
        # barrier state is per-rank SEQUENCE-numbered, not a bare count:
        # an arrival (rank, b) is released once every live rank's
        # highest arrival reaches b.  In the common case (all ranks at
        # the same b) the last arrival releases everyone — exactly the
        # old counting behavior — but a worker whose barrier reply died
        # with a failing coordinator can RETRY the same logical barrier
        # (same b) against the successor idempotently, instead of
        # entering a phantom extra rendezvous that would skew every
        # later barrier and hang the job's final one.
        self._barrier_high = {}   # rank -> highest bseq arrived
        self._barrier_done = {}   # rank -> highest bseq released
        # joiners align to the cohort: a rank that joins (or rejoins)
        # mid-job may arrive with a sequence below the cohort's pending
        # rendezvous; its first arrival is offset there ONE-SHOT and
        # the offset rides the reply so the CLIENT adopts the effective
        # sequence — deliberately no server-side offset state, so a
        # failover successor starting empty loses nothing
        self._barrier_joined = set()   # ranks whose next arrival aligns
        # the client identity last seen BARRIERING per rank: a fresh
        # client generation under an old rank id (a job resumed against
        # live servers) starts a fresh sequence — stale release marks
        # must not no-op its first rendezvous
        self._barrier_client = {}
        self._stop = threading.Event()
        self._listener = socket.create_server((host, port))
        self._listener.settimeout(0.5)
        self.port = self._listener.getsockname()[1]
        self._threads = []
        self._conns = []
        # exactly-once: per-client (rank, nonce) dedup window.  A client
        # that reconnects replays its unacked request with the SAME
        # (client_id, seq); the cached reply is returned without
        # re-applying — a replayed push that was already applied is
        # acked idempotently (reference analog: ps-lite resender).
        # The channel is serial, so the live replay set is ONE envelope —
        # but the window must stay >= 2: a zombie connection's handler
        # can process its final buffered request AFTER the replay (and
        # the client's next request) completed on the new connection,
        # and that late duplicate must still hit the cache.  Pull
        # replies embed whole arrays, so the window is deliberately
        # small; client windows are LRU-capped too (a relaunched client
        # arrives under a fresh nonce and must not pin the old one).
        # With the PIPELINED client (MXNET_KVSTORE_WINDOW envelopes in
        # flight) a reconnect replays the whole window, so the reply
        # cache must cover it: default 2x the client window (plus the
        # zombie-duplicate slack), read from the same env the launcher
        # exports to every role.
        self._dedup_window = int(_env(
            "MXNET_KVSTORE_DEDUP_WINDOW",
            max(8, 2 * int(_env("MXNET_KVSTORE_WINDOW", 8)))))
        self._dedup_clients = 256
        self._dedup = _hb.track(OrderedDict(),
                                "KVStoreServer._dedup")
        self._dedup_cv = threading.Condition()
        self.dedup_count = 0          # replays served from the window
        # liveness: last ping (or enveloped request) per worker rank.
        # Barrier waits stay UNBOUNDED by design — but a rank that was
        # alive and went silent past hb_timeout turns the wait into an
        # error naming the missing ranks instead of blocking forever.
        self._hb_timeout = float(
            hb_timeout if hb_timeout is not None
            else _env("MXNET_KVSTORE_HEARTBEAT_TIMEOUT", 15.0))
        self._hb_seen = {}            # rank -> last monotonic timestamp
        # extension ops: subsystems riding the kvstore wire (the serving
        # tier) register additional envelope types here instead of
        # forking the frame/allowlist/exactly-once stack.  Dispatch is
        # the LAST resort in _handle, so an extension can never shadow a
        # core op.
        self._ext_ops = {}
        # -- elastic membership (mxnet_tpu.membership) --------------------
        # Slot 0 of the CURRENT roster is the COORDINATOR
        # (membership.coordinator_uri — roster-derived, not a fixed
        # server id): it owns the generation-numbered membership ledger,
        # renegotiates barriers when a rank is evicted, and banks the
        # other servers' periodic state snapshots.  EVERY elastic server
        # runs the beat loop, fanning beats (and snapshots) out to every
        # peer — so the snapshot bank outlives any single server — and
        # on coordinator silence each survivor independently elects
        # membership.elect_successor; the elected one verifies the death
        # and promotes itself (_maybe_promote), rebuilding the ledger
        # from the survivors' ledger_reports + its local peer bank.
        self._elastic = bool(_env("MXNET_KVSTORE_ELASTIC", False)
                             if elastic is None else elastic)
        self.uri = uri or f"{host}:{self.port}"
        # the coordinator ledger is created LAZILY (first roster op /
        # first barrier): in-process tests only know every server's
        # bound port — and can set MXT_SERVER_URIS — after construction
        self._membership = None
        self._membership_lock = threading.Lock()
        self._roster_servers = list(roster_servers) if roster_servers \
            else None
        self._beat_thread = None
        self._beat_seq = 0
        self._snapshot_s = float(_env("MXNET_KVSTORE_SNAPSHOT_S", 0.0))
        # this server's view of the live roster (updated from every
        # coordinator beat reply) — the rebuild source on promotion
        self._known_roster = None
        self._known_gen = 0
        self._known_workers = None
        # peer snapshot bank: uri -> (beat seq, snapshot struct).  Grown
        # from the beat fan-out on EVERY server, so the killed-server
        # recovery source no longer dies with server 0; promoted into
        # the rebuilt ledger on failover.
        self._peer_snapshots = _hb.track(
            {}, "KVStoreServer._peer_snapshots")
        # peer stats bank: uri -> (beat seq, compact profiler counters).
        # Beats piggyback profiler.snapshot(compact=True), banked on
        # EVERY server with the same newest-seq-wins rule as snapshots —
        # so the last-known counters of a SIGKILLed member survive its
        # death (and the coordinator's death) and ride the "stats"
        # envelope's stats_bank field (docs/OBSERVABILITY.md)
        self._peer_stats = _hb.track({}, "KVStoreServer._peer_stats")
        self._promoted = False        # this server succeeded a dead coord
        self._coord_last_ok = None    # last successful coordinator beat
        self._coord_refused = False   # last coordinator dial was refused
        self._peer_heard = set()      # peers that EVER acked a beat
        self._peer_refused = set()    # heard-then-refused peers (evidence)
        # handoff dedup: wire key -> newest applied roster generation
        # (values), same for optimizer state; base key -> generation the
        # stale wire forms were purged at.  Quorum re-pushes and
        # replayed envelopes are idempotent through these.
        self._handoff_gen = _hb.track(
            {}, "KVStoreServer._handoff_gen")
        self._handoff_state_gen = _hb.track(
            {}, "KVStoreServer._handoff_state_gen")
        self._handoff_base_gen = _hb.track(
            {}, "KVStoreServer._handoff_base_gen")

    def register_op(self, op: str, fn) -> None:
        """Register an extension envelope type: ``fn(msg, rank) ->
        reply payload``.  The handler runs under the same exactly-once
        envelope, allowlisted decode and error-reply contract as the
        built-in ops; core op names are reserved."""
        if op in ("ping", "init", "push", "push_multi", "pull",
                  "pull_rows", "pull_rowsparse", "assign",
                  "get_states", "set_states",
                  "command", "barrier", "req", "stats", "roster_get",
                  "roster_join", "roster_leave", "roster_dead",
                  "roster_beat", "roster_snapshot", "handoff",
                  "handoff_state", "ledger_report", "roster_fwd",
                  "codec_hello"):
            raise ValueError(f"cannot override core kvstore op {op!r}")
        self._ext_ops[op] = fn

    # -- request handlers ----------------------------------------------------
    def _apply_push(self, key, arr):
        """reference kvstore_dist_server.h:405-430: async branch applies the
        updater right away; a pushed value with no updater replaces the
        stored one (assign, not add).  A compressed payload (2bit/fp16
        wire mode) is dequantized here — the stored weight stays fp32."""
        from .ndarray import NDArray
        import jax.numpy as jnp
        if isinstance(arr, RowSparsePayload):
            return self._apply_push_sparse(key, arr)
        if isinstance(arr, WirePayload):
            arr = _decompress(arr)
        grad = NDArray(jnp.asarray(arr))
        with self._lock:
            stored = self._store.get(key)
            if stored is None:
                raise KeyError(f"push to uninitialized key {key!r}")
            if self._updater is not None:
                # child of the srv.push envelope span: on the merged
                # timeline the optimizer apply separates from
                # decode/lock time (docs/OBSERVABILITY.md)
                # protocol: span(phase)
                with _tr.span("srv.updater_apply", cat="server"):
                    self._updater(_key_int(key), grad, stored)
            else:
                stored._set_data(grad._data)

    def _apply_push_sparse(self, key, p):
        """Row-sparse push: only the touched rows arrived.  Re-validate
        the descriptor here — the binary codec already gated it, but
        the pickle path has no decode-time check — then hand the
        updater a RowSparseNDArray so the optimizer's sparse impl
        touches exactly those rows (momentum rows included)."""
        from .ndarray import NDArray
        from .ndarray.sparse import RowSparseNDArray
        import jax.numpy as jnp
        _validate_rowsparse(p)
        data = p.data
        if isinstance(data, WirePayload):
            data = _decompress(data)
        idx = np.asarray(p.indices, dtype=np.int64)
        # bucket the row count to the next power of two — zero rows
        # under an out-of-range id, which dedup_rows/mode='drop'
        # scatters discard.  Per-stripe counts vary every push, and each
        # fresh row count would otherwise cost an XLA compile of the
        # sparse-update kernels (the serving tier's bucketed-predict
        # trick, applied to the updater).
        n = int(idx.shape[0])
        cap = (1 << (n - 1).bit_length()) if n else 1
        if cap != n:
            data = np.concatenate(
                [np.asarray(data),
                 np.zeros((cap - n,) + tuple(np.shape(data))[1:],
                          np.asarray(data).dtype)])
            idx = np.concatenate([idx, np.full(cap - n, p.nrows,
                                               np.int64)])
        with self._lock:
            stored = self._store.get(key)
            if stored is None:
                raise KeyError(f"push to uninitialized key {key!r}")
            if p.nrows != int(stored.shape[0]):
                raise ValueError(
                    f"row-sparse push to key {key!r}: payload declares "
                    f"{p.nrows} rows, stored table has "
                    f"{int(stored.shape[0])}")
            if tuple(np.shape(data))[1:] != tuple(stored.shape)[1:]:
                raise ValueError(
                    f"row-sparse push to key {key!r}: row shape "
                    f"{tuple(np.shape(data))[1:]} does not match stored "
                    f"{tuple(stored.shape)[1:]}")
            if self._updater is not None:
                grad = RowSparseNDArray(
                    NDArray(jnp.asarray(data)), NDArray(jnp.asarray(idx)),
                    tuple(stored.shape))
                # protocol: span(phase)
                with _tr.span("srv.updater_apply", cat="server"):
                    self._updater(_key_int(key), grad, stored)
            elif idx.size:
                # assign semantics, restricted to the touched rows
                stored._set_data(stored._data.at[jnp.asarray(idx)]
                                 .set(jnp.asarray(data)))

    def _handle(self, msg, rank=None, client=None):
        op = msg[0]
        if op == "ping":  # protocol: replay(idempotent) reply(none)
            # heartbeat: out-of-band liveness (its own connection — the
            # data channel may legitimately block in a barrier)
            if len(msg) > 1:
                self._note_ping(msg[1])
            return None
        if op == "init":  # protocol: replay(idempotent) reply(none)
            # first init wins; later inits of the same key are ignored
            # (reference: the server keeps the first-arriving value,
            # kvstore_dist_server.h DataHandleDefault init path)
            _, key, arr = msg
            from .ndarray import NDArray
            import jax.numpy as jnp
            with self._lock:
                if key not in self._store:
                    self._store[key] = NDArray(jnp.asarray(arr))
            return None
        if op == "push":  # protocol: replay(dedup-window) reply(none) codec(binary)
            _, key, arr = msg
            self._apply_push(key, arr)
            return None
        if op == "push_multi":  # protocol: replay(dedup-window) reply(none) codec(binary)
            # coalesced small-key push: one envelope, applied in order
            # (the worker groups sub-threshold keys bound for this shard
            # into a single frame — one RTT instead of K)
            _, entries = msg
            for key, arr in entries:
                self._apply_push(key, arr)
            return None
        if op == "assign":  # protocol: replay(idempotent) reply(none)
            # store the pushed value VERBATIM, bypassing any installed
            # updater, creating the key if absent.  Control-plane
            # metadata (the serving weight-version counter) must be a
            # plain register: routing it through "push" would hand it to
            # the SGD updater as a gradient.
            _, key, arr = msg
            from .ndarray import NDArray
            import jax.numpy as jnp
            if isinstance(arr, WirePayload):
                arr = _decompress(arr)
            with self._lock:
                stored = self._store.get(key)
                if stored is None:
                    self._store[key] = NDArray(jnp.asarray(arr))
                else:
                    stored._set_data(jnp.asarray(arr))
            return None
        if op == "pull":  # protocol: replay(pure) reply(ndarray) codec(binary)
            _, key = msg
            with self._lock:
                stored = self._store.get(key)
                if stored is None:
                    raise KeyError(f"pull of uninitialized key {key!r}")
                return np.asarray(stored.asnumpy())
        if op == "pull_rows":  # protocol: replay(pure) reply(rows + full shape)
            # O(requested rows) row-sparse pull (reference:
            # DataHandleRowSparse, kvstore_dist_server.h:211 — only the
            # requested rows travel)
            _, key, ids = msg
            with self._lock:
                stored = self._store.get(key)
                if stored is None:
                    raise KeyError(f"pull of uninitialized key {key!r}")
                full = np.asarray(stored.asnumpy())
                return full[ids], full.shape
        if op == "pull_rowsparse":  # protocol: replay(pure) reply(rows + full shape) codec(binary)
            # binary-codec row-sparse pull: the id list arrives as one
            # i64 tensor buffer and the row block replies zero-copy —
            # wire cost is rows_touched x row_bytes + 8 x rows_touched,
            # never the full table (reference: PullRowSparse)
            _, key, ids = msg
            ids = np.asarray(ids, dtype=np.int64).reshape(-1)
            with self._lock:
                stored = self._store.get(key)
                if stored is None:
                    raise KeyError(f"pull of uninitialized key {key!r}")
                full = np.asarray(stored.asnumpy())
                if ids.size and (int(ids.min()) < 0
                                 or int(ids.max()) >= full.shape[0]):
                    raise ValueError(
                        f"pull_rowsparse of key {key!r}: row ids out of "
                        f"range for {full.shape[0]} rows")
                return np.ascontiguousarray(full[ids]), full.shape
        if op == "get_states":  # protocol: replay(pure) reply(states blob | None)
            # optimizer-state checkpointing: this shard's {key: state}
            # dict, optionally with the optimizer itself (reference:
            # server-side optimizer states live in the server,
            # kvstore_dist_server.h:131).  Return only keys the shard
            # OWNS (is in _store): set_states broadcasts the full merged
            # union to every server, so after further training the
            # updater also holds stale loaded copies of OTHER shards'
            # keys — without this filter a save→load→train→save flow
            # with ≥2 servers lets a stale copy overwrite the owner's
            # fresh state in the client-side merge (ADVICE r5).
            dump = bool(msg[1]) if len(msg) > 1 else False
            with self._lock:
                if self._updater is None:
                    return None
                states = self._updater.states
                if self._store:
                    owned = {_key_int(k) for k in self._store}
                    states = {k: v for k, v in states.items()
                              if k in owned}
                # an EMPTY store means this shard never saw an init/push
                # (pure load→save relay, e.g. checkpoint migration):
                # return everything — the client-side merge prefers each
                # key's OWNER, so these can never shadow fresh state
                return pickle.dumps((states, self._updater.optimizer)
                                    if dump else states)
        if op == "set_states":  # protocol: replay(idempotent) reply(none)
            _, blob = msg
            with self._lock:
                if self._updater is None:
                    raise RuntimeError(
                        "set_states before an optimizer was installed")
                # decode the peer-supplied blob through the transport
                # allowlist (Updater.set_states accepts the loaded dict)
                self._updater.set_states(_restricted_loads(blob))
            return None
        if op == "command":  # protocol: replay(idempotent) reply(none)
            _, head, body = msg
            return self._command(head, body)
        if op == "barrier":  # protocol: replay(idempotent) reply(generation | generation, realign)
            return self._barrier(rank, msg[1] if len(msg) > 1 else None,
                                 client=client)
        if op == "stats":  # protocol: replay(pure) reply(profiler snapshot + stats_bank)
            # the universal observability envelope: EVERY server (and
            # every subclass — the serving replica generalizes its old
            # serving_stats through this) answers with the full
            # profiler snapshot plus server identity and the last-
            # known-stats bank of its peers (docs/OBSERVABILITY.md)
            return self._stats_payload()
        if op == "roster_get":  # protocol: replay(idempotent) reply(roster wire)
            return self._roster_op(("roster_get",))
        # protocol: replay(idempotent) reply(roster wire | roster wire + barrier floor)
        if op in ("roster_join", "roster_leave", "roster_dead"):
            _, role, ident = msg
            return self._roster_op((op, role, ident))
        if op == "roster_fwd":  # protocol: replay(idempotent) reply(forwarded op reply)
            # a peer forwarded a roster op it could not serve (it is not
            # the coordinator): dispatch locally, NEVER re-forward — one
            # hop bounds the succession-window relay
            return self._roster_op(tuple(msg[1]), forwarded=True)
        if op == "roster_beat":  # protocol: replay(idempotent) reply(roster wire | none)
            # a peer server's liveness beat, optionally carrying its
            # state snapshot (raw message: beats must never be stalled
            # by a delay-acks fault plan, like heartbeats).  EVERY
            # elastic server banks the snapshot — the bank must outlive
            # the coordinator — and the coordinator's reply carries the
            # full roster so peers track the membership they may one
            # day have to rebuild.
            _, suri, seq, snap = msg[:4]
            stats = msg[4] if len(msg) > 4 else None
            self._bank_peer_snapshot(suri, seq, snap)
            if stats is not None:
                self._bank_peer_stats(suri, seq, stats)
            m = self._get_membership()
            if m is None:
                return None
            m.note_server_beat(suri, seq=seq, snapshot=snap, stats=stats)
            return m.roster().as_wire()
        if op == "roster_snapshot":  # protocol: replay(pure) reply(snapshot struct | none)
            # serve from the ledger bank OR the local peer bank: the
            # request must be answerable on whichever server is the
            # coordinator after a failover
            _, ident = msg
            m = self._get_membership()
            snap = m.snapshot_of(ident) if m is not None else None
            if snap is None:
                # under self._lock: the beat handlers bank into this
                # dict under the same lock from other connection
                # threads (hb-sanitizer finding, ISSUE 15)
                with self._lock:
                    have = self._peer_snapshots.get(ident)
                snap = have[1] if have else None
            if snap is None and m is None:
                self._require_membership()   # classic not-coordinator error
            return snap
        if op == "ledger_report":  # protocol: replay(pure) reply(report dict)
            # ("ledger_report", True) is the SLIM form the promotion
            # sweep uses (generation + beat seq only); the bare op also
            # names the live key set, for operator forensics
            return self._ledger_report(
                slim=bool(msg[1]) if len(msg) > 1 else False)
        if op == "handoff":  # protocol: replay(per-generation) reply(applied bool)
            _, gen, wire_key, arr, bkey = msg
            return self._apply_handoff(int(gen), wire_key, arr, bkey)
        if op == "handoff_state":  # protocol: replay(per-generation) reply(applied bool)
            _, gen, wire_key, state, bkey = msg
            return self._apply_handoff_state(int(gen), wire_key, state,
                                             bkey)
        ext = self._ext_ops.get(op)
        if ext is not None:
            return ext(msg, rank)
        raise ValueError(f"unknown op {op!r}")

    # -- exactly-once delivery ----------------------------------------------
    def _traced_exactly_once(self, cid, seq, inner, wctx):
        """The exactly-once path under a server-side span.  ``wctx`` is
        the envelope's optional trace field ``(trace_id, parent
        span_id, client send epoch-us)``: with it the span is a CHILD
        of the worker-side call — and a REPLAYED envelope carries the
        original field, so reconnects annotate the same trace; with
        tracing on but an untraced client the span roots fresh.  The
        send stamp rides into span args for the merge tool's
        clock-offset estimate (tools/trace_merge.py --spans)."""
        if not _tr.enabled():
            return self._exactly_once(cid, seq, inner)
        op = inner[0] if isinstance(inner, (tuple, list)) and inner \
            else "?"
        args = None
        if wctx is not None and len(wctx) > 2:
            args = {"client_send_us": float(wctx[2])}
        sp = _tr.span_begin(
            "srv.%s" % op, cat="server",
            ctx=(wctx[0], wctx[1]) if wctx is not None else None,
            args=args)
        try:
            return self._exactly_once(cid, seq, inner)
        finally:
            _tr.span_end(sp)

    def _exactly_once(self, client_id, seq, inner):
        """Serve one enveloped request with at-most-once application.

        A replayed (client_id, seq) that already completed returns the
        CACHED reply (``dedup_count`` ticks); one still in flight on
        another connection thread (e.g. the original connection died
        while its handler blocks in a barrier) is WAITED for, never
        double-entered — the replay then also gets the cached reply."""
        cid = tuple(client_id) if isinstance(client_id, list) else client_id
        if isinstance(cid, tuple) and cid:
            self._note_ping(cid[0])   # any request is liveness evidence
        with self._dedup_cv:
            st = self._dedup.get(cid)
            if st is None:
                st = self._dedup[cid] = {"inflight": set(),
                                         "replies": OrderedDict()}
            self._dedup.move_to_end(cid)
            while len(self._dedup) > self._dedup_clients:
                old_cid, old_st = next(iter(self._dedup.items()))
                if old_st["inflight"]:
                    break   # never drop a window with work in flight
                self._dedup.popitem(last=False)
            while seq in st["inflight"] and not self._stop.is_set():
                self._dedup_cv.wait(0.1)
            if seq in st["replies"]:
                self.dedup_count += 1
                # a replayed envelope served from cache: mark it on the
                # trace — the replay carries the ORIGINAL trace field,
                # so this instant lands in the original trace, proving
                # the reconnect was absorbed idempotently
                # protocol: span(phase)
                _tr.instant("srv.dedup_hit", args={"seq": seq})
                return st["replies"][seq]
            st["inflight"].add(seq)
        rank = cid[0] if isinstance(cid, tuple) and cid else None
        reply = None
        try:
            try:
                reply = ("ok", self._handle(inner, rank=rank, client=cid))
            except Exception as exc:  # noqa: BLE001 — to the client
                reply = ("err", f"{type(exc).__name__}: {exc}")
        finally:
            # cache + un-inflight atomically: a replay racing this exact
            # moment must see either "in flight" or the cached reply,
            # never a gap it could re-apply through
            with self._dedup_cv:
                st["inflight"].discard(seq)
                if reply is not None:
                    st["replies"][seq] = reply
                    while len(st["replies"]) > self._dedup_window:
                        st["replies"].popitem(last=False)
                self._dedup_cv.notify_all()
        return reply

    # -- liveness ------------------------------------------------------------
    def _note_ping(self, rank):
        try:
            rank = int(rank)
        except (TypeError, ValueError):
            return
        with self._barrier_cv:
            self._hb_seen[rank] = time.monotonic()

    def _silent_ranks(self):
        """Worker ranks that HAVE been heard from and then went silent
        past hb_timeout.  A rank that never pinged is indistinguishable
        from one that is still starting up — never declared dead.
        Caller holds _barrier_cv."""
        if self._hb_timeout <= 0:
            return set()
        now = time.monotonic()
        live = self._live_worker_ranks()
        return {r for r, t in self._hb_seen.items()
                if r in live and now - t > self._hb_timeout}

    def _live_worker_ranks(self):
        m = self._get_membership()
        if m is not None:
            return set(m.workers_snapshot())
        return set(range(self.num_workers))

    def _heartbeat_ages(self, ranks):
        """Per-rank last-heartbeat age, for barrier failures that must
        carry EVIDENCE, not just rank ids.  Caller holds _barrier_cv."""
        now = time.monotonic()
        parts = []
        for r in sorted(ranks):
            t = self._hb_seen.get(r)
            parts.append("rank %s: %s" % (
                r, "never heard from" if t is None
                else "last heartbeat %.1fs ago" % (now - t)))
        return "; ".join(parts)

    # -- elastic membership (coordinator half; mxnet_tpu.membership) ---------
    def _roster_uris(self, self_fallback=True):
        """This server's best view of the roster server order: the live
        roster learned from coordinator beat replies, else the bootstrap
        roster (ctor / MXT_SERVER_URIS — in-process tests set the env
        after binding ports), else just self (``self_fallback=False``
        returns [] instead, for callers that must distinguish "no
        roster source at all" — coordinator-role derivation falls back
        to the launcher's server_id there)."""
        uris = (self._known_roster or self._roster_servers
                or [u for u in os.environ.get(
                    "MXT_SERVER_URIS", "").split(",") if u])
        if not uris and self_fallback:
            return [self.uri]
        return uris

    def _is_coordinator(self):
        """Whether THIS server currently holds the coordinator role —
        roster-derived (membership.coordinator_uri over the live view),
        never a hardcoded server id: a failover re-seats slot 0.  A
        promoted successor stays coordinator for good (the old one is
        dead by verified evidence).  Until ANY roster source exists
        (ctor roster, beat replies, MXT_SERVER_URIS — in-process tests
        set the env after binding ports), the launcher's server_id
        decides: without this, the [self.uri] fallback would make EVERY
        just-started elastic server consider itself coordinator, arming
        ONLY_COORDINATOR fault plans (and minting throwaway ledgers) on
        non-slot-0 servers."""
        if not self._elastic:
            return False
        if self._promoted:
            return True
        from .membership import coordinator_uri
        uris = self._roster_uris(self_fallback=False)
        if not uris:
            return self.server_id == 0
        return coordinator_uri(uris) == self.uri

    def _get_membership(self):
        """The coordinator ledger — the roster's slot-0 server of an
        elastic job only (lazily created so in-process tests can bind
        ports and set MXT_SERVER_URIS before the first roster op
        arrives)."""
        if not self._is_coordinator():
            return None
        with self._membership_lock:
            if self._membership is None:
                uris = self._roster_uris()
                from .membership import MembershipCoordinator
                self._membership = MembershipCoordinator(
                    uris, range(self.num_workers))
            return self._membership

    def _require_membership(self):
        m = self._get_membership()
        if m is None:
            raise RuntimeError(
                "not the roster coordinator (roster ops go to slot 0 "
                "of the live roster of an elastic job; set "
                "MXNET_KVSTORE_ELASTIC=1)")
        return m

    def _roster_op(self, inner, forwarded=False):
        """Dispatch one roster op at the right server: locally when this
        server is (or — on CONFIRMED coordinator death — just became)
        the coordinator; otherwise forwarded ONE hop to the live
        coordinator.  The forwarding keeps roster ops flowing through
        the succession window: a worker or late joiner whose stale
        roster points at any surviving server still reaches the ledger,
        and its envelope replays dedup exactly like every other op."""
        m = self._get_membership()
        if m is None and self._elastic:
            dead_hint = None
            if inner[0] == "roster_dead" and len(inner) == 3 \
                    and inner[1] == "server":
                dead_hint = str(inner[2])
            if self._maybe_promote(dead_hint=dead_hint):
                m = self._get_membership()
            else:
                return self._forward_roster_op(inner, forwarded)
        if m is None:
            self._require_membership()   # raises the classic error
        if inner[0] == "roster_get":
            return self._roster_get(m)
        _op, role, ident = inner
        return self._roster_mutate(m, _op[len("roster_"):], role, ident)

    def _forward_roster_op(self, inner, forwarded):
        """Relay a roster op to the live coordinator over a short-lived
        socket (one hop only).  A refused relay dial is itself death
        evidence: re-try the succession check before giving up."""
        from .membership import coordinator_uri, elect_successor
        if forwarded:
            raise RuntimeError(
                "forwarded roster op reached a non-coordinator (roster "
                "views diverged mid-succession); retry against the "
                "current roster")
        addr = self._coordinator_addr()
        if addr is not None:
            try:
                status, payload = self._oneshot_request(
                    addr, ("roster_fwd", list(inner)),
                    self._hb_timeout or 15.0)
                if status != "ok":
                    raise RuntimeError(str(payload))
                return payload
            except (ConnectionError, OSError):
                # the coordinator refused/died mid-relay: that IS local
                # evidence — run the succession check before failing
                curi = coordinator_uri(self._roster_uris())
                if self._maybe_promote(dead_hint=curi):
                    return self._roster_op(inner, forwarded=True)
        curi = coordinator_uri(self._roster_uris())
        succ = elect_successor(self._roster_uris(), {curi})
        raise RuntimeError(
            "not the roster coordinator (coordinator %s unreachable "
            "from %s; deterministic successor is %s)"
            % (curi, self.uri, succ))

    # -- coordinator failover (succession + ledger rebuild) ------------------
    def _coordinator_silent(self):
        """LOCAL evidence of coordinator death from the beat loop: the
        last dial was refused (decisive — the port is gone), or a
        previously-acking coordinator has been silent past hb_timeout.
        Never-heard-never-dead: a coordinator we never reached may still
        be starting up."""
        if self._coord_refused:
            return True
        if self._hb_timeout <= 0 or self._coord_last_ok is None:
            return False
        return time.monotonic() - self._coord_last_ok > self._hb_timeout

    def _probe_confirmed_dead(self, curi):
        """Probe a peer's listener before acting on its reported death
        (the coordinator pre-promotion, and each intermediate slot the
        succession election walks past).  ONLY a REFUSED dial confirms
        death — the port is gone, the process with it.  A completed
        connect means it is alive, and a TIMEOUT is inconclusive (a
        slow or partitioned-from-us coordinator may still be serving
        workers that can reach it): both REFUSE the promotion — the
        no-split-brain guard.  Succession therefore never fires on
        reachability alone; a host that vanishes without closing its
        ports (cable pull) degrades to the pre-failover behavior
        (the job fails loudly) rather than risking two coordinators."""
        import socket as _socket
        try:
            sock = _socket.create_connection(
                self._uri_addr(curi),
                timeout=min(2.0, self._hb_timeout or 2.0))
        except ConnectionRefusedError:
            return True
        except ValueError:
            return True    # malformed uri can never serve again
        except OSError:
            return False   # timeout/unreachable: inconclusive, refuse
        try:
            sock.close()
        except OSError:
            pass
        return False

    def _maybe_promote(self, dead_hint=None):
        """Deterministic succession: promote this server to coordinator
        iff (a) the current coordinator is confirmed dead by LOCAL
        evidence — beat silence / refused dials, or a probe when a peer
        reports it dead (``dead_hint``) — and (b)
        membership.elect_successor over the last-known roster and the
        full locally-evidenced dead set picks this very server.  When
        the election lands on an INTERMEDIATE slot, that slot is probed
        too and the election walks on if it is also dead — so a
        simultaneous multi-server preemption (coordinator AND the next
        slots) still seats the true survivor in one call.  Pure
        arithmetic plus local probes, no votes.  Idempotent and
        thread-safe; True when this server IS the coordinator on
        exit."""
        from .membership import coordinator_uri, elect_successor
        if not self._elastic:
            return False
        if self._promoted:
            return True
        uris = self._roster_uris()
        curi = coordinator_uri(uris)
        if curi is None or curi == self.uri:
            return self._is_coordinator()
        hinted = dead_hint is not None and str(dead_hint) == curi
        if not hinted and not self._coordinator_silent():
            return False
        if not self._probe_confirmed_dead(curi):
            return False
        dead = {curi} | set(self._peer_refused)
        dead.discard(self.uri)
        while True:
            succ = elect_successor(uris, dead)
            if succ is None or succ == self.uri:
                break
            if self._probe_confirmed_dead(succ):
                dead.add(succ)     # intermediate slot dead too: walk on
                continue
            return False           # a live better-ranked successor leads
        if succ != self.uri:
            return False
        self._promote_to_coordinator(dead)
        return self._promoted

    def _promote_to_coordinator(self, dead_uris):
        """Become the coordinator: sweep the surviving servers for their
        ledger_reports, rebuild the ledger at max(reported generation)+1
        (membership.rebuild_ledger — stale-coordinator envelopes are
        rejected by the existing per-generation staleness checks), and
        promote the local peer snapshot bank into it.  ``dead_uris`` is
        the election's full probe-confirmed dead set — every member is
        excluded from the rebuilt roster, so a multi-death succession
        never re-seats a corpse at slot 0.  In-flight roster ops from
        workers replay against this server through the ordinary
        exactly-once envelope path; the workers' three-phase handoff
        then reconstructs the dead servers' stripes."""
        from . import membership as _mem
        if isinstance(dead_uris, str):
            dead_uris = {dead_uris}
        t0 = time.monotonic()
        if self._promoted:
            return
        # the failover_rebuild_s gauge, as a SPAN with its two halves as
        # children: the peer sweep (network round trips) vs the pure
        # ledger rebuild — on the merged timeline the rebuild window
        # sits between the dead coordinator's last span and the first
        # post-succession barrier release (docs/OBSERVABILITY.md)
        # protocol: span(phase)
        fsp = _tr.span_begin("srv.failover_rebuild", cat="elastic",
                             args={"dead": sorted(dead_uris)})
        try:
            # the sweep dials peers with real socket timeouts: run it
            # BEFORE taking the ledger lock, or every _get_membership()
            # caller (barrier arrivals included) would stall behind the
            # promotion's network round trips.  Racing promoters both
            # sweep; the lock below picks one winner.
            uris = [u for u in self._roster_uris() if u not in dead_uris]
            with _tr.span("failover.sweep", cat="elastic"):
                reports = [self._ledger_report(slim=True)]
                for u in uris:
                    if u == self.uri:
                        continue
                    r = self._sweep_ledger_report(u)
                    if r is not None:
                        reports.append(r)
            workers = self._known_workers
            if workers is None:
                workers = range(self.num_workers)
            with self._lock:
                snapshots = dict(self._peer_snapshots)
            with _tr.span("failover.rebuild", cat="elastic"):
                with self._membership_lock:
                    if self._promoted:
                        return
                    self._membership = _mem.rebuild_ledger(
                        uris, workers, reports, snapshots)
                    self._promoted = True
                    self._known_roster = list(uris)
                    self._known_gen = self._membership.generation
        finally:
            _tr.span_end(fsp)
        faultinject.note_coordinator(True)
        _prof.record_channel_event("kvstore.coordinator_failover")
        _prof.record_channel_gauge("kvstore.coordinator_slot",
                                   self.server_id)
        _prof.record_channel_gauge("kvstore.failover_rebuild_s",
                                   time.monotonic() - t0)
        _prof.record_channel_gauge("kvstore.roster_generation",
                                   self._known_gen)
        _health.note("failover", dead=sorted(dead_uris),
                     generation=int(self._known_gen),
                     rebuild_s=round(time.monotonic() - t0, 3))
        _health.dump("failover")
        print("kvstore server %d (%s): promoted to roster coordinator "
              "(predecessor(s) %s dead; generation resumes at %d)"
              % (self.server_id, self.uri, sorted(dead_uris),
                 self._known_gen), flush=True)

    def _ledger_report(self, slim=False):
        """This server's contribution to a successor's ledger rebuild:
        last-known generation and beat seq (the successor resumes the
        generation counter past every report, so any envelope the dead
        coordinator's epoch stamped is stale).  The full form also
        names the live key set — operator forensics (which keys a dead
        server held), NOT a merge input; the promotion sweep asks for
        ``slim=True`` so a real job's thousands of wire keys never ride
        the latency-critical rebuild."""
        m = self._membership
        gen = m.generation if m is not None else self._known_gen
        with self._lock:
            # any generation this shard WITNESSED raises the floor: a
            # handoff applied at G proves G was issued even if no beat
            # reply ever carried it here (the coordinator can die within
            # one beat interval of issuing G — the correlated-preemption
            # window).  Without this the successor could resume AT G and
            # the per-(wire key, generation) handoff dedup would swallow
            # the next round's handoffs as duplicates.
            for d in (self._handoff_gen, self._handoff_state_gen,
                      self._handoff_base_gen):
                if d:
                    gen = max(gen, max(d.values()))
            keys = None if slim else sorted(self._store)
        out = {"uri": self.uri, "generation": int(gen),
               "beat_seq": int(self._beat_seq)}
        if keys is not None:
            out["keys"] = keys
        return out

    def _oneshot_request(self, addr, msg, timeout):
        """One raw request over a short-lived socket — the shared dial/
        send/await/close shape behind roster forwarding and the ledger
        sweep (one place to keep the nodelay/timeout treatment).
        Returns the (status, payload) reply; transport faults raise so
        each caller keeps its own error policy."""
        import socket as _socket
        sock = _socket.create_connection(addr, timeout=timeout)
        try:
            sock.settimeout(timeout)
            _set_nodelay(sock)
            _send_msg(sock, msg)
            return _recv_msg(sock)
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _sweep_ledger_report(self, uri):
        """Demand one peer's ledger_report over a short-lived socket
        (promotion sweep).  An unreachable peer is skipped — it either
        re-joins through the ordinary path or gets evicted on
        silence."""
        try:
            status, payload = self._oneshot_request(
                self._uri_addr(uri), ("ledger_report", True),
                min(5.0, self._hb_timeout or 5.0))
            return payload if status == "ok" else None
        except Exception:  # noqa: BLE001 — an unreachable peer is skipped
            return None

    def _bank_peer_snapshot(self, uri, seq, snap):
        """Bank one peer's beat snapshot locally — the every-server half
        of the bank that must outlive server 0 (membership.bank_newest
        is the shared newest-seq-wins rule)."""
        from .membership import bank_newest
        with self._lock:
            bank_newest(self._peer_snapshots, uri, seq, snap)

    def _bank_peer_stats(self, uri, seq, stats):
        """Bank one peer's piggybacked counter snapshot (same
        newest-seq-wins rule as state snapshots; served by the "stats"
        envelope's stats_bank field)."""
        from .membership import bank_newest
        with self._lock:
            bank_newest(self._peer_stats, uri, seq, stats)

    def _stats_payload(self):
        """The ``("stats",)`` reply: the FULL profiler snapshot
        (dispatch/host-sync/channel counts, gauges, byte counters,
        latency rings, tracing state — profiler.snapshot is the one
        source every consumer shares) plus this server's identity and
        its last-known-stats bank of peers, which OUTLIVES any member's
        death the way the state-snapshot bank does.  Subclasses extend
        rather than replace (the serving replica adds its serving
        section on top)."""
        snap = _prof.snapshot()
        m = self._membership   # peek — never force-create the ledger
        snap["server"] = {
            "server_id": self.server_id,
            "uri": self.uri,
            "num_workers": self.num_workers,
            "dedup_count": self.dedup_count,
            "elastic": self._elastic,
            "coordinator": self._is_coordinator() if self._elastic
            else False,
            "beat_seq": int(self._beat_seq),
            "roster_generation": int(
                m.generation if m is not None else self._known_gen),
        }
        with self._lock:
            snap["stats_bank"] = {
                u: dict(entry[1], beat_seq=int(entry[0]))
                for u, entry in self._peer_stats.items()
                if isinstance(entry[1], dict)}
        if m is not None:
            # the ledger's bank (grown from beats the coordinator saw,
            # preloaded across failovers) backfills peers this server's
            # local bank never heard from
            for u, entry in m.stats_bank().items():
                if isinstance(entry[1], dict):
                    snap["stats_bank"].setdefault(
                        u, dict(entry[1], beat_seq=int(entry[0])))
        return snap

    def _note_roster_wire(self, payload):
        """Digest a beat reply carrying the live roster (only
        coordinators put one on the wire).  Generation-monotonic: a
        stale roster — an old coordinator that has not yet learned of
        its own replacement — can never regress this server's view."""
        try:
            gen, servers, workers = payload
        except (TypeError, ValueError):
            return
        if not isinstance(servers, (list, tuple)) or not servers:
            return
        if int(gen) < self._known_gen:
            return
        self._known_gen = int(gen)
        self._known_roster = [str(u) for u in servers]
        self._known_workers = list(workers) \
            if isinstance(workers, (list, tuple)) else None

    def _evict_silent_servers(self, m):
        """Coordinator-driven server eviction: a server whose beat went
        silent past hb_timeout is removed from the roster (the worker-
        report path converges to the same state; both are idempotent)."""
        for u in m.silent_servers(self._hb_timeout):
            try:
                m.report_dead_server(u)
            except RuntimeError:
                continue   # the last server is never evicted
            _prof.record_channel_event("kvstore.server_eviction")
            _health.note("server_evicted", uri=u, by="beat_silence")
            _prof.record_channel_gauge("kvstore.roster_generation",
                                       m.generation)

    def _roster_get(self, m):
        self._evict_silent_servers(m)
        return m.roster().as_wire()

    def _roster_mutate(self, m, action, role, ident):
        """join/leave/dead for either role; returns the FULL post-change
        roster so the caller refreshes in the same round trip.  All
        mutations are idempotent — racing duplicate reports of one dead
        server collapse into a single generation bump (and a worker's
        report of the already-replaced dead coordinator is a no-op: the
        rebuild removed it before the report arrived)."""
        before = m.generation
        if role == "server":
            uri = str(ident)
            if action == "join":
                m.join_server(uri)
            elif action == "leave":
                m.leave_server(uri)
            else:
                if uri == self.uri:
                    # a false-positive report (reporter's heartbeat
                    # blip) relayed to the very coordinator it names:
                    # answering this request IS proof of life — refusing
                    # keeps a live coordinator from evicting itself
                    # (split brain via a self-removed roster)
                    raise RuntimeError(
                        "refusing dead-server report naming this "
                        "coordinator — it is alive (it is answering "
                        "the report)")
                m.report_dead_server(uri)
        elif role == "worker":
            rank = int(ident)
            if action == "join":
                with self._barrier_cv:
                    if rank not in m.workers_snapshot():
                        self._barrier_joined.add(rank)
                        # a genuinely re-joining rank (relaunch under
                        # the same id) must not inherit its
                        # predecessor's release marks — a stale done
                        # would let its first barriers sail through
                        # without a rendezvous
                        self._barrier_high.pop(rank, None)
                        self._barrier_done.pop(rank, None)
                    m.join_worker(rank)
            elif action == "leave":
                m.leave_worker(rank)
                with self._barrier_cv:
                    self._forget_barrier_rank(rank)
            else:
                m.evict_worker(rank)
                with self._barrier_cv:
                    self._forget_barrier_rank(rank)
        else:
            raise ValueError(f"unknown roster role {role!r}")
        after = m.generation
        floor = None
        if role == "worker" and action == "join":
            with self._barrier_cv:
                floor = self._barrier_floor_locked()
        if after != before:
            if action == "dead":
                _prof.record_channel_event(
                    "kvstore.server_eviction" if role == "server"
                    else "kvstore.worker_eviction")
                _health.note("%s_evicted" % role, ident=str(ident),
                             by="report", generation=after)
            _prof.record_channel_gauge("kvstore.roster_generation", after)
            with self._barrier_cv:
                # membership changed: parked barrier waiters must
                # re-evaluate their target against the new roster
                self._barrier_release_locked()
                self._barrier_cv.notify_all()
        wire = m.roster().as_wire()
        if floor is not None:
            # a joining WORKER also receives the cohort's barrier floor:
            # it seeds its own barrier sequence there, so raw client
            # sequences stay globally cohort-aligned — which is what
            # lets a failover successor start with EMPTY barrier state
            # and still pair every retried arrival exactly
            wire = wire + (floor,)
        return wire

    def _apply_handoff(self, gen, wire_key, arr, bkey):
        """Install a handed-off VALUE (the workers' quorum re-push, or a
        snapshot restripe).  First delivery per (wire_key, generation)
        wins; duplicates — every worker races to hand off the same
        bytes, and replays ride the exactly-once envelope on top — are
        acked without re-applying.  The first handoff of a logical key
        in a generation purges that key's stale wire forms (old stripe
        keys / whole-key form) plus their optimizer state, so a
        re-striped layout never leaves orphans behind."""
        from .ndarray import NDArray
        import jax.numpy as jnp
        if isinstance(arr, WirePayload):
            arr = _decompress(arr)
        with self._lock:
            if gen <= self._handoff_gen.get(wire_key, -1):
                _prof.record_channel_event("kvstore.handoff_dup")
                return False
            if self._handoff_base_gen.get(bkey, -1) < gen:
                self._handoff_base_gen[bkey] = gen
                stale = [k for k in self._store
                         if k == bkey or k.startswith(bkey + "@s")]
                for k in stale:
                    del self._store[k]
                    if self._updater is not None:
                        self._updater.states.pop(_key_int(k), None)
                        self._updater.states_synced.pop(_key_int(k), None)
            self._handoff_gen[wire_key] = gen
            self._store[wire_key] = NDArray(jnp.asarray(arr))
        _prof.record_channel_event("kvstore.handoff_applied")
        return True

    def _apply_handoff_state(self, gen, wire_key, state, bkey):
        """Install handed-off OPTIMIZER STATE for one wire key (from the
        coordinator's snapshot of the departed server, restriped by the
        handing-off worker).  Same first-per-generation dedup as value
        handoff; a None state clears the slot so the optimizer re-creates
        fresh state (the non-row-decomposable fallback)."""
        idx = _key_int(wire_key)
        with self._lock:
            if self._updater is None:
                return False
            if gen <= self._handoff_state_gen.get(wire_key, -1):
                _prof.record_channel_event("kvstore.handoff_dup")
                return False
            self._handoff_state_gen[wire_key] = gen
            st = _state_to_nd(state)
            if st is None:
                self._updater.states.pop(idx, None)
                self._updater.states_synced.pop(idx, None)
            else:
                self._updater.states[idx] = st
                self._updater.states_synced[idx] = True
        _prof.record_channel_event("kvstore.handoff_state_applied")
        return True

    def _snapshot_struct(self):
        """This shard's full state as a wire structure ({wire_key: np
        value} + per-key optimizer state) — what the beat loop ships to
        the coordinator so a SIGKILL does not take the shard's optimizer
        state to its grave.  Rides the zero-copy frames (np arrays never
        pass through pickle)."""
        with self._lock:
            store = {k: np.asarray(v.asnumpy())
                     for k, v in self._store.items()}
            states = {}
            if self._updater is not None:
                owned = {_key_int(k) for k in self._store}
                for k, st in self._updater.states.items():
                    if k in owned:
                        states[str(k)] = _state_to_np(st)
        return {"store": store, "states": states}

    def _command(self, head, body):
        """reference kvstore_dist_server.h:149-162 ``CommandHandle``."""
        if head == K_STOP_SERVER:
            self._stop.set()
            with self._barrier_cv:
                self._barrier_cv.notify_all()
            return None
        if head == K_CONTROLLER:
            from . import optimizer as opt
            with self._lock:
                # peer-supplied blob: decode through the transport
                # allowlist, never stock pickle
                self._updater = opt.get_updater(_restricted_loads(body))
            return None
        return None  # kSyncMode etc.: accepted, no-op in the async server

    def _barrier_target_ranks(self):
        """The live worker ranks a barrier must rendezvous (re-read
        every evaluation, so an eviction mid-wait shrinks the set).
        Caller holds _barrier_cv."""
        m = self._get_membership()
        if m is not None:
            return set(m.workers_snapshot())
        return set(range(self.num_workers))

    def _barrier_release_locked(self):
        """Advance the per-rank release floor: an arrival ``(rank, b)``
        releases once every LIVE rank's highest arrival reaches ``b``
        (the floor).  Caller holds _barrier_cv; True when anything
        released."""
        live = self._barrier_target_ranks()
        if not live:
            return False
        floor = min(self._barrier_high.get(r, 0) for r in live)
        released = False
        for r, high in self._barrier_high.items():
            done = min(high, floor)
            if done > self._barrier_done.get(r, 0):
                self._barrier_done[r] = done
                released = True
        if released:
            self._barrier_cv.notify_all()
        return released

    def _barrier_released(self, rank, bseq):
        """Caller holds _barrier_cv."""
        return bseq <= self._barrier_done.get(rank, 0)

    def _barrier_floor_locked(self):
        """The cohort's release floor — min done over live ranks that
        have ARRIVED at least once (a not-yet-arrived fellow joiner
        must not drag the floor to zero).  A joining worker seeds its
        barrier sequence here, so raw client sequences are cohort-
        aligned from the first call.  Caller holds _barrier_cv."""
        live = self._barrier_target_ranks()
        arrived = [r for r in live if self._barrier_high.get(r, 0) > 0]
        if not arrived:
            return 0
        return min(self._barrier_done.get(r, 0) for r in arrived)

    def _forget_barrier_rank(self, rank):
        """Drop a departed rank's barrier state (a relaunch under the
        same rank id starts a fresh, join-aligned sequence).  A parked
        arrival of the departing rank is RELEASED — it is off the
        roster either way, and letting it go beats stranding its
        connection thread forever.  Caller holds _barrier_cv."""
        self._hb_seen.pop(rank, None)
        high = self._barrier_high.pop(rank, None)
        if high:
            self._barrier_done[rank] = max(
                self._barrier_done.get(rank, 0), high)
        else:
            self._barrier_done.pop(rank, None)
        self._barrier_joined.discard(rank)
        self._barrier_client.pop(rank, None)
        self._barrier_cv.notify_all()

    def _barrier(self, rank=None, bseq=None, client=None):
        """Rendezvous every live worker (reference: Postoffice::Barrier).

        Arrivals carry a per-rank barrier SEQUENCE number ``bseq`` (the
        worker's count of barrier() calls; server-assigned
        ``high(rank)+1`` when absent): arrival ``(rank, b)`` is released
        once every live rank's highest arrival is >= ``b``.  In
        lockstep this is exactly the old counting barrier — the last
        arrival releases everyone — but it is additionally IDEMPOTENT:
        a worker whose barrier reply died with a failing COORDINATOR
        retries the same ``(rank, b)`` against the successor and is
        released immediately if the rendezvous already happened,
        instead of entering a phantom extra barrier that would skew
        every later rendezvous (and hang the job's final one).  That
        idempotence is what makes the barrier exact through the
        succession window.

        The wait itself stays UNBOUNDED (a slow worker is legal) — but
        when the heartbeat registry shows a missing rank went SILENT
        past hb_timeout:

        * **static roster** — the wait fails naming the dead ranks AND
          each one's last-heartbeat age (operators get evidence, not
          just ids);
        * **elastic coordinator** — the barrier RENEGOTIATES instead of
          failing: the silent rank is evicted (generation bump), the
          floor re-reads the live roster, and the parked survivors are
          released the moment the shrunken set has all arrived.
          Returns the roster generation so workers piggyback bump
          discovery on every barrier.  An evicted rank that was merely
          slow and arrives later is re-admitted (join, another bump)
          with a fresh barrier sequence."""
        # deterministic stall injection (faultinject.delay_barrier_release
        # / MXNET_FI_STALL_BARRIER_MS): delays THIS arrival's handling
        # before it registers, so every other rank's park — and this
        # rank's reply — stretch by exactly the armed delay.  The
        # CPU-testable wedge the health watchdog gates trip on.
        faultinject.barrier_stall()
        with self._barrier_cv:
            if client is not None and rank is not None:
                prev = self._barrier_client.get(rank)
                if prev is not None and prev != client:
                    # a NEW client generation is barriering under an
                    # old rank id (trainer resumed against live
                    # servers): its sequence restarts at 1, so it
                    # realigns exactly like a joiner — one-shot offset
                    # to the cohort's pending rendezvous, adopted
                    # client-side via the reply.  Without this the
                    # predecessors' release marks would turn the
                    # resumed job's first rendezvous into instant
                    # no-ops.
                    self._barrier_joined.add(rank)
                self._barrier_client[rank] = client
            m = self._get_membership()
            if m is not None and rank is not None \
                    and rank not in m.workers_snapshot():
                m.join_worker(rank)
                self._barrier_joined.add(rank)
                # fresh sequence on re-admission (see _roster_mutate)
                self._barrier_high.pop(rank, None)
                self._barrier_done.pop(rank, None)
                _prof.record_channel_gauge("kvstore.roster_generation",
                                           m.generation)
            if rank is None:
                # anonymous raw-message arrival: tracked under a
                # synthetic rank outside every live set — it waits for
                # the live workers' rendezvous without being waited for
                rank = -1
            joined = rank in self._barrier_joined
            self._barrier_joined.discard(rank)
            if joined:
                # align the joiner to the cohort's earliest pending
                # rendezvous: the ARRIVED live ranks' release floor + 1
                # (a fellow just-joined rank that has not arrived yet
                # must not drag the alignment down to rendezvous 1)
                others = [r for r in self._barrier_target_ranks()
                          if r != rank
                          and self._barrier_high.get(r, 0) > 0]
                first = (min(self._barrier_done.get(r, 0)
                             for r in others) + 1) if others else 1
            realign = 0
            if bseq is None:
                # server-assigned sequence (legacy raw arrivals, tests):
                # already in effective terms
                bseq = self._barrier_high.get(rank, 0) + 1
                if joined:
                    bseq = max(bseq, first)
            else:
                bseq = int(bseq)
                if joined and first > bseq:
                    # one-shot: this arrival runs at the cohort's
                    # sequence, and the offset rides the reply so the
                    # client bumps its own counter — raw sequences are
                    # globally aligned again from the next call, with
                    # no server-side offset to lose at a failover
                    realign = first - bseq
                    bseq = first
            self._barrier_high[rank] = max(
                self._barrier_high.get(rank, 0), bseq)
            self._barrier_release_locked()
            # the park (arrival -> release) is a span nested under the
            # srv.barrier envelope span: on the merged timeline the
            # rendezvous skew between ranks — and a renegotiation's
            # eviction window — reads directly off the park widths
            # protocol: span(phase)
            park = _tr.span_begin("srv.barrier_park", cat="server",
                                  args={"rank": rank, "bseq": bseq})
            # the park is a registered health wait: a rendezvous parked
            # past MXNET_HEALTH_BARRIER_STALL_S trips the server-side
            # watchdog too, so BOTH halves of a wedged barrier degrade
            wtok = _health.wait_begin("srv.barrier_park")
            try:
                while not self._barrier_released(rank, bseq) \
                        and not self._stop.is_set():
                    self._barrier_cv.wait(0.1)
                    if self._barrier_released(rank, bseq) \
                            or self._stop.is_set():
                        break
                    live = self._barrier_target_ranks()
                    waiting_for = {r for r in live
                                   if self._barrier_high.get(r, 0) < bseq}
                    silent = self._silent_ranks() & waiting_for
                    if not silent:
                        continue
                    if m is not None:
                        for r in sorted(silent):
                            m.evict_worker(r)
                            self._forget_barrier_rank(r)
                            _prof.record_channel_event(
                                "kvstore.worker_eviction")
                        _prof.record_channel_gauge(
                            "kvstore.roster_generation", m.generation)
                        self._barrier_release_locked()
                        continue
                    arrived = sorted(
                        r for r in live
                        if self._barrier_high.get(r, 0) >= bseq)
                    ages = self._heartbeat_ages(silent)
                    raise RuntimeError(
                        "barrier timed out: worker rank(s) %s missing "
                        "(no heartbeat for > %.1fs; %s); "
                        "arrived rank(s): %s"
                        % (sorted(silent), self._hb_timeout, ages,
                           arrived))
            finally:
                _tr.span_end(park)
                _health.wait_end(wtok)
            payload = self._barrier_payload()
            return (payload, realign) if realign else payload

    def _barrier_payload(self):
        """Barrier replies carry the roster generation on an elastic
        coordinator (None otherwise) — the zero-extra-RTT way workers
        learn of roster bumps at every sync point.  Caller holds
        _barrier_cv."""
        m = self._get_membership()
        return None if m is None else m.generation

    # -- elastic beat loop (every elastic server) ----------------------------
    @staticmethod
    def _uri_addr(uri):
        host, port = uri.rsplit(":", 1)
        return (host, int(port))

    def _coordinator_addr(self):
        """(host, port) of the LIVE roster's coordinator, or None when
        this server is it (or no roster is known yet).  Derived through
        membership.coordinator_uri over the freshest roster view — the
        single source of truth the worker-side twin
        (KVStoreDistAsync._coordinator_conn) routes through too, so a
        failover re-seats both sides identically."""
        from .membership import coordinator_uri
        curi = coordinator_uri(self._roster_uris())
        if curi is None or curi == self.uri:
            return None
        return self._uri_addr(curi)

    def _beat_loop(self):
        """Every elastic server beats every OTHER roster server on its
        own sockets: liveness toward the coordinator (whose reply
        carries the live roster, so peers track the membership they may
        one day rebuild) and snapshot fan-out everywhere — each peer
        banks the beats it receives, so the snapshot bank (the
        killed-server recovery source) OUTLIVES any single server,
        including the coordinator.  A missed beat IS the signal — the
        coordinator evicts silent peers — so faults are swallowed and
        the socket re-dialed next tick.  Coordinator SILENCE is also
        detected here: a refused dial (decisive) or hb_timeout of quiet
        feeds _maybe_promote, where the deterministically elected
        successor verifies the death and takes over."""
        import socket as _socket
        interval = float(_env("MXNET_KVSTORE_HEARTBEAT_INTERVAL", 5.0))
        if interval <= 0:
            interval = 5.0
        last_snap = None
        socks = {}
        try:
            while not self._stop.is_set():
                if self.uri not in self._roster_uris():
                    # not a roster MEMBER (a serving replica in the
                    # train-and-serve topology sees MXT_SERVER_URIS +
                    # MXNET_KVSTORE_ELASTIC without ever being on the
                    # roster): observe, never beat — the server-side
                    # twin of the worker's roster_member=False
                    faultinject.note_coordinator(False)
                    self._stop.wait(interval)
                    continue
                faultinject.note_coordinator(self._is_coordinator())
                from .membership import coordinator_uri
                curi = coordinator_uri(self._roster_uris())
                snap = None
                now = time.monotonic()
                if self._snapshot_s > 0 and (
                        last_snap is None
                        or now - last_snap >= self._snapshot_s):
                    snap = self._snapshot_struct()
                # every beat piggybacks this server's compact counter
                # snapshot (channel counts/gauges/bytes, wire clocks):
                # peers bank it newest-seq-wins, so the cluster holds a
                # last-known-stats view of every member that survives
                # its SIGKILL (docs/OBSERVABILITY.md stats bank)
                beat_stats = _prof.snapshot(compact=True)
                sent_snap = False
                for uri in list(self._roster_uris()):
                    if uri == self.uri:
                        continue
                    self._beat_seq += 1
                    faultinject.server_beat(self._beat_seq)
                    try:
                        sock = socks.get(uri)
                        if sock is None:
                            sock = _socket.create_connection(
                                self._uri_addr(uri),
                                timeout=self._hb_timeout or 15.0)
                            sock.settimeout(self._hb_timeout or 15.0)
                            socks[uri] = sock
                        _send_msg(sock, ("roster_beat", self.uri,
                                         self._beat_seq, snap,
                                         beat_stats),
                                  byte_kind="control")
                        status, payload = _recv_msg(
                            sock, byte_kind="control_recv")
                        if status == "ok":
                            if snap is not None:
                                sent_snap = True
                            # digest ANY roster-carrying reply (only a
                            # coordinator puts one on the wire): after a
                            # failover the new coordinator is NOT the uri
                            # this server still believes leads, and its
                            # replies are how the stale view heals
                            self._note_roster_wire(payload)
                            self._peer_heard.add(uri)
                            self._peer_refused.discard(uri)
                            if uri == curi:
                                self._coord_last_ok = time.monotonic()
                                self._coord_refused = False
                    except Exception as exc:  # noqa: BLE001 — the miss IS the signal
                        _prof.record_channel_event("kvstore.beat_miss")
                        if isinstance(exc, ConnectionRefusedError) \
                                and uri in self._peer_heard:
                            # a HEARD-FROM peer's port is GONE — decisive
                            # death evidence, banked for the succession
                            # election's dead set.  Never-heard-never-
                            # dead still holds: a refused dial to a peer
                            # that never acked is just one still binding
                            # its listener at job start, and promoting
                            # off it would split the roster from minute
                            # zero
                            self._peer_refused.add(uri)
                            if uri == curi:
                                self._coord_refused = True
                            # flight-recorder evidence: a survivor's
                            # bundle names the peer whose port vanished
                            # (the postmortem's who-died witness line)
                            _health.note("peer_refused", uri=uri,
                                         coordinator=bool(uri == curi))
                        sock = socks.pop(uri, None)
                        if sock is not None:
                            try:
                                sock.close()
                            except OSError:
                                pass
                if sent_snap:
                    last_snap = now
                if not self._is_coordinator():
                    self._maybe_promote()
                # prune channels to servers no longer on the roster
                for uri in list(socks):
                    if uri not in self._roster_uris():
                        s = socks.pop(uri)
                        try:
                            s.close()
                        except OSError:
                            pass
                self._stop.wait(min(interval, self._snapshot_s)
                                if self._snapshot_s > 0 else interval)
        except Exception:  # noqa: BLE001 — park the crash as a counter:
            # the loop's death is observable (beats stop -> the
            # coordinator evicts this server on silence; if this WAS the
            # coordinator, the successor takes over), never silent
            _prof.record_channel_event("kvstore.beat_loop_crash")
        finally:
            for sock in socks.values():
                try:
                    sock.close()
                except OSError:
                    pass

    def leave(self):
        """GRACEFUL departure (scale-down, planned preemption): ship one
        final state snapshot to the coordinator, deregister from the
        roster (generation bump — workers re-stripe and hand the state
        back out at their next sync point), then stop serving.  The
        kill-path twin — SIGKILL, no goodbye — is what the periodic
        snapshot exists for."""
        import socket as _socket
        addr = self._coordinator_addr()
        if addr is not None:
            try:
                sock = _socket.create_connection(addr, timeout=15.0)
                sock.settimeout(15.0)
                try:
                    self._beat_seq += 1
                    _send_msg(sock, ("roster_beat", self.uri,
                                     self._beat_seq,
                                     self._snapshot_struct()),
                              byte_kind="control")
                    _recv_msg(sock, byte_kind="control_recv")
                    _send_msg(sock, ("roster_leave", "server", self.uri),
                              byte_kind="control")
                    _recv_msg(sock, byte_kind="control_recv")
                finally:
                    sock.close()
            except Exception:  # noqa: BLE001 — departing anyway; the
                # coordinator will evict us on beat silence instead
                _prof.record_channel_event("kvstore.beat_miss")
        self.stop()

    # -- connection plumbing -------------------------------------------------
    def _serve_conn(self, conn):
        recv_kind = "recv"
        try:
            with conn:
                while not self._stop.is_set():
                    try:
                        msg = _recv_msg(conn, byte_kind=recv_kind)
                    except (ConnectionError, OSError):
                        return
                    reply_kind = "sent"
                    if msg and msg[0] == "req":
                        # client envelope: (op, client_id, seq, inner
                        # [, trace]) — the exactly-once path (reconnect
                        # + replay); the optional 5th element is the
                        # span context propagated from the worker
                        _, cid, seq, inner = msg[:4]
                        reply = self._traced_exactly_once(
                            cid, seq, inner,
                            msg[4] if len(msg) > 4 else None)
                        role = "server"
                    else:
                        # raw message (codec hellos, heartbeat pings,
                        # legacy callers): NOT fault-injection
                        # targetable — a delay-acks plan must never
                        # stall the liveness signal (faultinject.py's
                        # heartbeat-exemption contract)
                        hello = _codec.handle_hello(conn, msg)
                        if hello is not None:
                            reply = hello
                        else:
                            try:
                                reply = ("ok", self._handle(msg))
                            except Exception as exc:  # noqa: BLE001
                                reply = ("err",
                                         f"{type(exc).__name__}: {exc}")
                        role = None
                        if msg and msg[0] in ("ping", "roster_beat",
                                              "roster_leave"):
                            # these ops live on DEDICATED control
                            # sockets (heartbeat threads, beat loops) —
                            # latch this connection's byte family to
                            # "control" so wire_bytes_per_step measures
                            # gradients only.  codec_hello must NOT
                            # latch: every socket (incl. data) hellos
                            # once at connect
                            recv_kind = "control_recv"
                            reply_kind = "control"
                    try:
                        _send_msg(conn, reply, fi_role=role,
                                  byte_kind=reply_kind)
                    except (ConnectionError, OSError):
                        # the client died / reconnected while we worked:
                        # the reply stays in the dedup window, so the
                        # replay on the new connection is acked from
                        # cache — drop this connection only
                        return
                    if role == "server":
                        # enveloped replies only: the deterministic ack
                        # count behind the process-level kill point
                        faultinject.server_replied()
        except Exception:  # noqa: BLE001 — conn died mid-reply
            pass

    def run(self):
        """Blocking accept loop; returns after a kStopServer command."""
        if self._elastic:
            faultinject.note_coordinator(self._is_coordinator())
            if self._beat_thread is None:
                self._beat_thread = threading.Thread(
                    target=self._beat_loop, daemon=True)
                self._beat_thread.start()
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                if faultinject.server_accept(conn):
                    continue   # injected refusal: already closed
                _set_nodelay(conn)
                t = threading.Thread(target=self._serve_conn, args=(conn,),
                                     daemon=True)
                t.start()
                self._threads.append(t)
                self._conns.append(conn)
        finally:
            self._listener.close()

    def stop(self):
        self._stop.set()
        with self._barrier_cv:
            self._barrier_cv.notify_all()
        # close live connections too: a handler blocked in _recv_msg only
        # re-checks _stop after servicing a request, so without this a
        # "stopped" server still answers one more op per connection —
        # clients must see EOF promptly (and the crash-simulation tests
        # rely on exactly that)
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass

    def start_background(self):
        """Run the accept loop in a daemon thread (in-process tests)."""
        # analysis: allow(bare-thread): a crash unwinds through run()'s finally, closing the listener — every client observes it as refused connects within its retry budget, and in-flight conns keep their own _serve_conn handlers
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        return t


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _state_to_np(state):
    """Optimizer state → plain numpy for the snapshot/handoff wire
    (rides the zero-copy frames; non-array state is not
    row-decomposable and maps to None — see membership.restripe_states)."""
    from .ndarray import NDArray
    if state is None:
        return None
    if isinstance(state, NDArray):
        return np.asarray(state.asnumpy())
    if isinstance(state, np.ndarray):
        return state
    if isinstance(state, (tuple, list)):
        return tuple(_state_to_np(s) for s in state)
    return None


def _state_to_nd(state):
    """Wire numpy state → the NDArray shapes Updater stores."""
    from .ndarray import NDArray
    import jax.numpy as jnp
    if state is None:
        return None
    if isinstance(state, np.ndarray):
        return NDArray(jnp.asarray(state))
    if isinstance(state, (tuple, list)):
        parts = tuple(_state_to_nd(s) for s in state)
        return None if all(p is None for p in parts) else parts
    return None


def _init_kvstore_server_module():
    """Turn a ``DMLC_ROLE=server`` process into a blocking server, then
    exit — the reference hook verbatim (python/mxnet/kvstore_server.py:75:
    importing the library in a server-role process never returns to user
    code)."""
    if os.environ.get("DMLC_ROLE") != "server":
        return
    # This function blocks INSIDE `import mxnet_tpu`, so the package module
    # would stay flagged as initializing forever — and any connection
    # thread that triggers `import mxnet_tpu.*` (pickle.loads of an
    # optimizer does) would block on the parent module's import lock:
    # a guaranteed deadlock.  The package body is fully executed at this
    # point (this hook is its last statement), so clear the flag, and
    # pre-import everything the request handlers touch.
    import mxnet_tpu  # noqa: PLC0415 — self, already in sys.modules
    spec = getattr(mxnet_tpu, "__spec__", None)
    if spec is not None:
        spec._initializing = False
    from . import optimizer as _opt  # noqa: F401 — handler dependency
    from . import ndarray as _nd     # noqa: F401
    import jax.numpy as _jnp         # noqa: F401
    sid = int(os.environ.get("DMLC_SERVER_ID", "0"))
    uris = os.environ.get("MXT_SERVER_URIS", "")
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    host, port, my = "127.0.0.1", 0, None
    if uris:
        my = uris.split(",")[sid]
        host, port = my.rsplit(":", 1)
        port = int(port)
        # loopback-advertised servers (local launcher) bind loopback ONLY
        # — _recv_msg unpickles from any peer, so never expose the port
        # beyond what the deployment needs; ssh-mode servers must accept
        # remote workers and bind all interfaces (trusted-cluster model,
        # see module docstring)
        if host not in ("127.0.0.1", "localhost"):
            host = "0.0.0.0"
    # identity on the roster = the ADVERTISED uri (the bind host may be
    # 0.0.0.0 in ssh mode; workers and the coordinator know us by the
    # launcher-assigned address)
    server = KVStoreServer(server_id=sid, num_workers=num_workers,
                           host=host, port=port, uri=my)
    print(f"kvstore server {sid} listening on port {server.port}",
          flush=True)
    server.run()
    sys.exit(0)
