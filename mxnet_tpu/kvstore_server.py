"""Async parameter-server process: the backend of kvstore ``dist_async``.

TPU-native re-design of the reference's server stack
(src/kvstore/kvstore_dist_server.h; bootstrap in
python/mxnet/kvstore_server.py:28-75).  The reference runs ps-lite
``KVServer`` processes over ZMQ; async mode applies each worker's push to
the stored weight the moment it arrives (kvstore_dist_server.h:405-430 —
``DataHandleDefault``'s non-sync branch runs ``updater_(key, recved,
&stored)`` immediately, no cross-worker aggregation barrier).  That is
the one kvstore mode SPMD collectives cannot express — allreduce is
synchronous by construction — so here the servers come back as plain
host processes:

* transport: length-prefixed pickled messages over TCP (ps-lite/ZMQ's
  role; no new dependency).
* apply: one global store lock — the reference server is ALSO serialized
  (its single-thread ``Executor`` run loop, kvstore_dist_server.h:50-106),
  so per-push locking is the faithful concurrency model.
* placement: servers pin ``JAX_PLATFORMS=cpu`` (set by tools/launch.py);
  updates are tiny CPU math and a server must never touch a TPU — the
  accelerators belong to the workers, exactly as the reference gives
  servers no GPU context.

Process model mirrors the reference exactly: ``tools/launch.py -s S``
starts S copies of the *same user command* with ``DMLC_ROLE=server``;
importing :mod:`mxnet_tpu` in such a process enters the blocking server
loop and exits when the job is torn down, so user training scripts work
unmodified as server commands (reference kvstore_server.py:75
``_init_kvstore_server_module``).

Worker-side counterpart: :class:`mxnet_tpu.kvstore.KVStoreDistAsync`.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import sys
import threading
import time
from collections import OrderedDict

import numpy as np

from . import faultinject
from . import profiler as _prof
from .base import env as _env
from .compression import WirePayload, decompress as _decompress

# reference command codes (kvstore_dist_server.h:44-45): kStopServer=-1
# tears down, kSyncMode=-2 switches the reference server to sync
# aggregation (a documented no-op here — this server IS the async mode,
# and doubles as the channel-flush sync token), and any head >= 0 routes
# to the controller (CommandHandle :150-162), where head 0 carries the
# pickled optimizer (python/mxnet/kvstore.py set_optimizer).
K_CONTROLLER = 0
K_STOP_SERVER = -1
K_SYNC_MODE = -2


# -- wire frame ---------------------------------------------------------------
# A message is ONE frame:
#
#     >Q  total length of everything after this field
#     >I  skeleton length S
#     S bytes   pickled SKELETON: the message with every ndarray replaced
#               by a _Buf(index, dtype, shape) placeholder
#     ...       the raw tensor buffers, concatenated in index order
#
# Tensors therefore never pass through pickle: the sender writes each
# array's memoryview straight to the socket (no tobytes() copy) and the
# receiver maps np.frombuffer views over one contiguous read.  The
# skeleton — the only remaining pickled bytes from a peer — is decoded
# through a class-allowlisted Unpickler (below).


class _Buf:
    """Skeleton placeholder for a raw tensor buffer riding after it."""

    __slots__ = ("i", "dtype", "shape")

    def __init__(self, i, dtype, shape):
        self.i = i
        self.dtype = dtype
        self.shape = tuple(shape)

    def __reduce__(self):
        return (_Buf, (self.i, self.dtype, self.shape))

    @property
    def nbytes(self):
        return (int(np.prod(self.shape, dtype=np.int64))
                * np.dtype(self.dtype).itemsize)


def _pack(obj, bufs):
    """Replace every ndarray in ``obj`` with a _Buf placeholder,
    appending the (contiguous) array to ``bufs``.  Object-dtype arrays
    cannot ride a raw buffer and stay in the skeleton."""
    if isinstance(obj, np.ndarray) and not obj.dtype.hasobject:
        # NOTE: ascontiguousarray promotes 0-d to 1-d — keep the
        # logical shape from the original array
        arr = np.ascontiguousarray(obj)
        ref = _Buf(len(bufs), arr.dtype.str, obj.shape)
        bufs.append(arr)
        return ref
    if isinstance(obj, tuple):
        return tuple(_pack(x, bufs) for x in obj)
    if isinstance(obj, list):
        return [_pack(x, bufs) for x in obj]
    if isinstance(obj, dict):
        return {k: _pack(v, bufs) for k, v in obj.items()}
    if isinstance(obj, WirePayload):
        return WirePayload(obj.kind, obj.shape, obj.threshold,
                           _pack(obj.data, bufs))
    return obj


def _unpack(obj, body, offsets):
    if isinstance(obj, _Buf):
        return np.frombuffer(
            body, dtype=np.dtype(obj.dtype),
            count=int(np.prod(obj.shape, dtype=np.int64)),
            offset=offsets[obj.i]).reshape(obj.shape)
    if isinstance(obj, tuple):
        return tuple(_unpack(x, body, offsets) for x in obj)
    if isinstance(obj, list):
        return [_unpack(x, body, offsets) for x in obj]
    if isinstance(obj, dict):
        return {k: _unpack(v, body, offsets) for k, v in obj.items()}
    if isinstance(obj, WirePayload):
        return WirePayload(obj.kind, obj.shape, obj.threshold,
                           _unpack(obj.data, body, offsets))
    return obj


def _collect_bufs(obj, refs):
    if isinstance(obj, _Buf):
        refs.append(obj)
    elif isinstance(obj, (tuple, list)):
        for x in obj:
            _collect_bufs(x, refs)
    elif isinstance(obj, dict):
        for v in obj.values():
            _collect_bufs(v, refs)
    elif isinstance(obj, WirePayload):
        _collect_bufs(obj.data, refs)


# -- restricted deserialization ----------------------------------------------
# _recv_msg decodes bytes from ANY connected peer; a stock pickle.loads
# would let that peer name arbitrary importable callables (os.system,
# ...).  With tensors moved to raw-buffer frames, the remaining pickled
# skeletons/blobs only ever reference our own classes plus a handful of
# numpy/jax reconstruction helpers — so find_class admits mxnet_tpu
# classes (the reference semantics ship user optimizer/updater classes)
# plus an EXPLICIT (module, name) set.  Whole-root allowances for
# numpy/jax would re-open the door: numpy alone ships importable
# command/exec helpers (numpy.testing.runstring, distutils exec_command)
# that a REDUCE opcode could call with attacker arguments.
_SAFE_BUILTINS = frozenset({
    "complex", "frozenset", "set", "slice", "range", "bytearray",
    "object", "tuple", "list", "dict",
})
_SAFE_GLOBALS = frozenset({
    ("collections", "OrderedDict"),
    ("numpy", "dtype"),
    ("numpy", "ndarray"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "_reconstruct"),   # older numpy pickles
    ("numpy.core.multiarray", "scalar"),
    ("jax._src.array", "_reconstruct_array"),
    # the wire marker classes, by NAME: their home modules also hold
    # classes with side-effecting constructors (KVStoreServer binds a
    # listening socket) that must stay out of REDUCE reach
    ("mxnet_tpu.kvstore_server", "_Buf"),
    ("mxnet_tpu.compression", "WirePayload"),
})
# Only CLASSES from these modules — the pickle surface the reference
# semantics actually ship (optimizer/updater/scheduler objects, NDArray
# states).  A whole-package allowance would admit module-level
# callables and classes with side-effecting constructors (recordio/
# checkpoint file writers, server sockets) as REDUCE gadgets.
_SAFE_MXT_MODULES = (
    "mxnet_tpu.optimizer", "mxnet_tpu.lr_scheduler",
    "mxnet_tpu.ndarray", "mxnet_tpu.initializer",
    "mxnet_tpu.gluon.parameter",
    # Module.init_optimizer ships optimizers carrying sym/idx2name
    # context (reference: optimizer.py Optimizer attributes)
    "mxnet_tpu.symbol", "mxnet_tpu.attribute", "mxnet_tpu.name",
)


def _env_allowlist():
    """Operator-extensible trust: MXNET_KVSTORE_PICKLE_ALLOWLIST is a
    comma-separated list of ``module`` or ``module:name`` entries (a
    bare module admits every name in it).  This is the escape hatch for
    the reference's custom-optimizer flow — a user-defined optimizer
    class living in ``__main__``/their own package can be shipped to
    the servers by explicitly naming its module in the job env (the
    launcher propagates env to every role)."""
    raw = os.environ.get("MXNET_KVSTORE_PICKLE_ALLOWLIST", "")
    entries = []
    for item in raw.split(","):
        item = item.strip()
        if item:
            mod, _, name = item.partition(":")
            entries.append((mod, name or None))
    return entries


class _RestrictedUnpickler(pickle.Unpickler):  # analysis: allow(unsafe-pickle): this IS the allowlisted decoder — find_class below enforces the class allowlist every other site must route through
    def find_class(self, module, name):
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        if (module, name) in _SAFE_GLOBALS:
            return super().find_class(module, name)
        if any(module == m or module.startswith(m + ".")
               for m in _SAFE_MXT_MODULES):
            import inspect
            obj = super().find_class(module, name)
            if inspect.isclass(obj):
                return obj
        for mod, ename in _env_allowlist():
            if module == mod and (ename is None or name == ename):
                return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"kvstore wire: refusing to unpickle {module}.{name} "
            "(not in the transport allowlist; for custom optimizer/"
            "updater classes set MXNET_KVSTORE_PICKLE_ALLOWLIST="
            f"{module}:{name} on every job role)")


def _restricted_loads(data):
    """pickle.loads through the transport allowlist — for wire skeletons
    and peer-supplied control blobs (shipped optimizers, state blobs)."""
    import io
    return _RestrictedUnpickler(io.BytesIO(data)).load()


def _set_nodelay(sock):
    """Disable Nagle on a kvstore data socket.  A frame is two-plus
    ``sendall`` calls (header+skeleton, then each raw tensor buffer);
    with Nagle on, the small header write can sit in the kernel waiting
    on the peer's delayed ACK before the tensor bytes follow — a
    ~40 ms-class stall per frame on a real network (docs/PERF_NOTES.md
    round 9).  Loopback never shows it, which is exactly why it must be
    set unconditionally at connect/accept rather than found later on a
    chip."""
    import socket as _socket
    try:
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
    except (OSError, AttributeError):
        pass   # non-TCP socket (tests stub with socketpairs)


def _send_msg(sock, obj, fi_role=None):
    """Zero-copy framed send (skeleton pickle + raw tensor buffers).
    ``fi_role`` tags DATA-channel traffic for the deterministic fault-
    injection hooks ("client" may be severed at an exact message,
    "server" may delay acks); untagged sends (heartbeats) are exempt so
    a plan hits only what it targets."""
    if fi_role == "client":
        faultinject.client_send(sock)
    elif fi_role == "server":
        faultinject.server_reply_delay()
    bufs = []
    skel = pickle.dumps(_pack(obj, bufs),
                        protocol=pickle.HIGHEST_PROTOCOL)
    total = 4 + len(skel) + sum(a.nbytes for a in bufs)
    _prof.record_channel_bytes("sent", 8 + total)
    sock.sendall(struct.pack(">QI", total, len(skel)) + skel)
    for arr in bufs:
        sock.sendall(memoryview(arr).cast("B"))
    if fi_role == "client":
        faultinject.client_sent(sock)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _recv_msg(sock, fi_role=None):
    if fi_role == "client":
        faultinject.client_recv(sock)
    total, skel_len = struct.unpack(">QI", _recv_exact(sock, 12))
    skel = _restricted_loads(_recv_exact(sock, skel_len))
    body = _recv_exact(sock, total - 4 - skel_len)
    _prof.record_channel_bytes("recv", 8 + total)
    refs = []
    _collect_bufs(skel, refs)
    if not refs:
        return skel
    offsets, off = {}, 0
    for ref in sorted(refs, key=lambda r: r.i):
        offsets[ref.i] = off
        off += ref.nbytes
    return _unpack(skel, body, offsets)


class KVStoreServer:
    """One async parameter-server shard.

    Holds a slice of the key space (workers route each key to
    ``crc32(key) % num_servers``); applies the installed optimizer to
    every arriving gradient immediately (async SGD), or stores the pushed
    value verbatim when no optimizer is installed (the reference's
    assign-on-merge semantics, kvstore_local.h:173).
    """

    def __init__(self, server_id=0, num_workers=1,
                 host="127.0.0.1", port=0, hb_timeout=None,
                 elastic=None, uri=None, roster_servers=None):
        self.server_id = server_id
        self.num_workers = num_workers
        self._store = {}          # key -> NDArray (host CPU)
        self._updater = None
        self._lock = threading.Lock()
        self._barrier_cv = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_ranks = set()   # ranks currently arrived
        self._stop = threading.Event()
        self._listener = socket.create_server((host, port))
        self._listener.settimeout(0.5)
        self.port = self._listener.getsockname()[1]
        self._threads = []
        self._conns = []
        # exactly-once: per-client (rank, nonce) dedup window.  A client
        # that reconnects replays its unacked request with the SAME
        # (client_id, seq); the cached reply is returned without
        # re-applying — a replayed push that was already applied is
        # acked idempotently (reference analog: ps-lite resender).
        # The channel is serial, so the live replay set is ONE envelope —
        # but the window must stay >= 2: a zombie connection's handler
        # can process its final buffered request AFTER the replay (and
        # the client's next request) completed on the new connection,
        # and that late duplicate must still hit the cache.  Pull
        # replies embed whole arrays, so the window is deliberately
        # small; client windows are LRU-capped too (a relaunched client
        # arrives under a fresh nonce and must not pin the old one).
        # With the PIPELINED client (MXNET_KVSTORE_WINDOW envelopes in
        # flight) a reconnect replays the whole window, so the reply
        # cache must cover it: default 2x the client window (plus the
        # zombie-duplicate slack), read from the same env the launcher
        # exports to every role.
        self._dedup_window = int(_env(
            "MXNET_KVSTORE_DEDUP_WINDOW",
            max(8, 2 * int(_env("MXNET_KVSTORE_WINDOW", 8)))))
        self._dedup_clients = 256
        self._dedup = OrderedDict()   # client_id -> {inflight, replies}
        self._dedup_cv = threading.Condition()
        self.dedup_count = 0          # replays served from the window
        # liveness: last ping (or enveloped request) per worker rank.
        # Barrier waits stay UNBOUNDED by design — but a rank that was
        # alive and went silent past hb_timeout turns the wait into an
        # error naming the missing ranks instead of blocking forever.
        self._hb_timeout = float(
            hb_timeout if hb_timeout is not None
            else _env("MXNET_KVSTORE_HEARTBEAT_TIMEOUT", 15.0))
        self._hb_seen = {}            # rank -> last monotonic timestamp
        # extension ops: subsystems riding the kvstore wire (the serving
        # tier) register additional envelope types here instead of
        # forking the frame/allowlist/exactly-once stack.  Dispatch is
        # the LAST resort in _handle, so an extension can never shadow a
        # core op.
        self._ext_ops = {}
        # -- elastic membership (mxnet_tpu.membership) --------------------
        # Server 0 of the roster is the COORDINATOR: it owns the
        # generation-numbered membership ledger, renegotiates barriers
        # when a rank is evicted, and banks non-coordinator servers'
        # periodic state snapshots (the killed-server recovery source).
        # Non-coordinator elastic servers run a beat loop toward the
        # coordinator instead.
        self._elastic = bool(_env("MXNET_KVSTORE_ELASTIC", False)
                             if elastic is None else elastic)
        self.uri = uri or f"{host}:{self.port}"
        # the coordinator ledger is created LAZILY (first roster op /
        # first barrier): in-process tests only know every server's
        # bound port — and can set MXT_SERVER_URIS — after construction
        self._membership = None
        self._membership_lock = threading.Lock()
        self._roster_servers = list(roster_servers) if roster_servers \
            else None
        self._beat_thread = None
        self._beat_seq = 0
        self._snapshot_s = float(_env("MXNET_KVSTORE_SNAPSHOT_S", 0.0))
        # handoff dedup: wire key -> newest applied roster generation
        # (values), same for optimizer state; base key -> generation the
        # stale wire forms were purged at.  Quorum re-pushes and
        # replayed envelopes are idempotent through these.
        self._handoff_gen = {}
        self._handoff_state_gen = {}
        self._handoff_base_gen = {}

    def register_op(self, op: str, fn) -> None:
        """Register an extension envelope type: ``fn(msg, rank) ->
        reply payload``.  The handler runs under the same exactly-once
        envelope, allowlisted decode and error-reply contract as the
        built-in ops; core op names are reserved."""
        if op in ("ping", "init", "push", "push_multi", "pull",
                  "pull_rows", "assign", "get_states", "set_states",
                  "command", "barrier", "req", "roster_get",
                  "roster_join", "roster_leave", "roster_dead",
                  "roster_beat", "roster_snapshot", "handoff",
                  "handoff_state"):
            raise ValueError(f"cannot override core kvstore op {op!r}")
        self._ext_ops[op] = fn

    # -- request handlers ----------------------------------------------------
    def _apply_push(self, key, arr):
        """reference kvstore_dist_server.h:405-430: async branch applies the
        updater right away; a pushed value with no updater replaces the
        stored one (assign, not add).  A compressed payload (2bit/fp16
        wire mode) is dequantized here — the stored weight stays fp32."""
        from .ndarray import NDArray
        import jax.numpy as jnp
        if isinstance(arr, WirePayload):
            arr = _decompress(arr)
        grad = NDArray(jnp.asarray(arr))
        with self._lock:
            stored = self._store.get(key)
            if stored is None:
                raise KeyError(f"push to uninitialized key {key!r}")
            if self._updater is not None:
                self._updater(_key_int(key), grad, stored)
            else:
                stored._set_data(grad._data)

    def _handle(self, msg, rank=None):
        op = msg[0]
        if op == "ping":
            # heartbeat: out-of-band liveness (its own connection — the
            # data channel may legitimately block in a barrier)
            if len(msg) > 1:
                self._note_ping(msg[1])
            return None
        if op == "init":
            # first init wins; later inits of the same key are ignored
            # (reference: the server keeps the first-arriving value,
            # kvstore_dist_server.h DataHandleDefault init path)
            _, key, arr = msg
            from .ndarray import NDArray
            import jax.numpy as jnp
            with self._lock:
                if key not in self._store:
                    self._store[key] = NDArray(jnp.asarray(arr))
            return None
        if op == "push":
            _, key, arr = msg
            self._apply_push(key, arr)
            return None
        if op == "push_multi":
            # coalesced small-key push: one envelope, applied in order
            # (the worker groups sub-threshold keys bound for this shard
            # into a single frame — one RTT instead of K)
            _, entries = msg
            for key, arr in entries:
                self._apply_push(key, arr)
            return None
        if op == "assign":
            # store the pushed value VERBATIM, bypassing any installed
            # updater, creating the key if absent.  Control-plane
            # metadata (the serving weight-version counter) must be a
            # plain register: routing it through "push" would hand it to
            # the SGD updater as a gradient.
            _, key, arr = msg
            from .ndarray import NDArray
            import jax.numpy as jnp
            if isinstance(arr, WirePayload):
                arr = _decompress(arr)
            with self._lock:
                stored = self._store.get(key)
                if stored is None:
                    self._store[key] = NDArray(jnp.asarray(arr))
                else:
                    stored._set_data(jnp.asarray(arr))
            return None
        if op == "pull":
            _, key = msg
            with self._lock:
                stored = self._store.get(key)
                if stored is None:
                    raise KeyError(f"pull of uninitialized key {key!r}")
                return np.asarray(stored.asnumpy())
        if op == "pull_rows":
            # O(requested rows) row-sparse pull (reference:
            # DataHandleRowSparse, kvstore_dist_server.h:211 — only the
            # requested rows travel)
            _, key, ids = msg
            with self._lock:
                stored = self._store.get(key)
                if stored is None:
                    raise KeyError(f"pull of uninitialized key {key!r}")
                full = np.asarray(stored.asnumpy())
                return full[ids], full.shape
        if op == "get_states":
            # optimizer-state checkpointing: this shard's {key: state}
            # dict, optionally with the optimizer itself (reference:
            # server-side optimizer states live in the server,
            # kvstore_dist_server.h:131).  Return only keys the shard
            # OWNS (is in _store): set_states broadcasts the full merged
            # union to every server, so after further training the
            # updater also holds stale loaded copies of OTHER shards'
            # keys — without this filter a save→load→train→save flow
            # with ≥2 servers lets a stale copy overwrite the owner's
            # fresh state in the client-side merge (ADVICE r5).
            dump = bool(msg[1]) if len(msg) > 1 else False
            with self._lock:
                if self._updater is None:
                    return None
                states = self._updater.states
                if self._store:
                    owned = {_key_int(k) for k in self._store}
                    states = {k: v for k, v in states.items()
                              if k in owned}
                # an EMPTY store means this shard never saw an init/push
                # (pure load→save relay, e.g. checkpoint migration):
                # return everything — the client-side merge prefers each
                # key's OWNER, so these can never shadow fresh state
                return pickle.dumps((states, self._updater.optimizer)
                                    if dump else states)
        if op == "set_states":
            _, blob = msg
            with self._lock:
                if self._updater is None:
                    raise RuntimeError(
                        "set_states before an optimizer was installed")
                # decode the peer-supplied blob through the transport
                # allowlist (Updater.set_states accepts the loaded dict)
                self._updater.set_states(_restricted_loads(blob))
            return None
        if op == "command":
            _, head, body = msg
            return self._command(head, body)
        if op == "barrier":
            return self._barrier(rank)
        if op == "roster_get":
            return self._roster_get()
        if op in ("roster_join", "roster_leave", "roster_dead"):
            _, role, ident = msg
            return self._roster_mutate(op[len("roster_"):], role, ident)
        if op == "roster_beat":
            # a non-coordinator server's liveness beat, optionally
            # carrying its state snapshot (raw message: beats must never
            # be stalled by a delay-acks fault plan, like heartbeats)
            _, suri, seq, snap = msg
            m = self._get_membership()
            if m is None:
                return None
            m.note_server_beat(suri, seq=seq, snapshot=snap)
            return m.generation
        if op == "roster_snapshot":
            _, ident = msg
            m = self._require_membership()
            return m.snapshot_of(ident)
        if op == "handoff":
            _, gen, wire_key, arr, bkey = msg
            return self._apply_handoff(int(gen), wire_key, arr, bkey)
        if op == "handoff_state":
            _, gen, wire_key, state, bkey = msg
            return self._apply_handoff_state(int(gen), wire_key, state,
                                             bkey)
        ext = self._ext_ops.get(op)
        if ext is not None:
            return ext(msg, rank)
        raise ValueError(f"unknown op {op!r}")

    # -- exactly-once delivery ----------------------------------------------
    def _exactly_once(self, client_id, seq, inner):
        """Serve one enveloped request with at-most-once application.

        A replayed (client_id, seq) that already completed returns the
        CACHED reply (``dedup_count`` ticks); one still in flight on
        another connection thread (e.g. the original connection died
        while its handler blocks in a barrier) is WAITED for, never
        double-entered — the replay then also gets the cached reply."""
        cid = tuple(client_id) if isinstance(client_id, list) else client_id
        if isinstance(cid, tuple) and cid:
            self._note_ping(cid[0])   # any request is liveness evidence
        with self._dedup_cv:
            st = self._dedup.get(cid)
            if st is None:
                st = self._dedup[cid] = {"inflight": set(),
                                         "replies": OrderedDict()}
            self._dedup.move_to_end(cid)
            while len(self._dedup) > self._dedup_clients:
                old_cid, old_st = next(iter(self._dedup.items()))
                if old_st["inflight"]:
                    break   # never drop a window with work in flight
                self._dedup.popitem(last=False)
            while seq in st["inflight"] and not self._stop.is_set():
                self._dedup_cv.wait(0.1)
            if seq in st["replies"]:
                self.dedup_count += 1
                return st["replies"][seq]
            st["inflight"].add(seq)
        rank = cid[0] if isinstance(cid, tuple) and cid else None
        reply = None
        try:
            try:
                reply = ("ok", self._handle(inner, rank=rank))
            except Exception as exc:  # noqa: BLE001 — to the client
                reply = ("err", f"{type(exc).__name__}: {exc}")
        finally:
            # cache + un-inflight atomically: a replay racing this exact
            # moment must see either "in flight" or the cached reply,
            # never a gap it could re-apply through
            with self._dedup_cv:
                st["inflight"].discard(seq)
                if reply is not None:
                    st["replies"][seq] = reply
                    while len(st["replies"]) > self._dedup_window:
                        st["replies"].popitem(last=False)
                self._dedup_cv.notify_all()
        return reply

    # -- liveness ------------------------------------------------------------
    def _note_ping(self, rank):
        try:
            rank = int(rank)
        except (TypeError, ValueError):
            return
        with self._barrier_cv:
            self._hb_seen[rank] = time.monotonic()

    def _silent_ranks(self):
        """Worker ranks that HAVE been heard from and then went silent
        past hb_timeout.  A rank that never pinged is indistinguishable
        from one that is still starting up — never declared dead.
        Caller holds _barrier_cv."""
        if self._hb_timeout <= 0:
            return set()
        now = time.monotonic()
        live = self._live_worker_ranks()
        return {r for r, t in self._hb_seen.items()
                if r in live and now - t > self._hb_timeout}

    def _live_worker_ranks(self):
        m = self._get_membership()
        if m is not None:
            return set(m.workers_snapshot())
        return set(range(self.num_workers))

    def _heartbeat_ages(self, ranks):
        """Per-rank last-heartbeat age, for barrier failures that must
        carry EVIDENCE, not just rank ids.  Caller holds _barrier_cv."""
        now = time.monotonic()
        parts = []
        for r in sorted(ranks):
            t = self._hb_seen.get(r)
            parts.append("rank %s: %s" % (
                r, "never heard from" if t is None
                else "last heartbeat %.1fs ago" % (now - t)))
        return "; ".join(parts)

    # -- elastic membership (coordinator half; mxnet_tpu.membership) ---------
    def _get_membership(self):
        """The coordinator ledger — server 0 of an elastic roster only
        (lazily created so in-process tests can bind ports and set
        MXT_SERVER_URIS before the first roster op arrives)."""
        if not self._elastic or self.server_id != 0:
            return None
        with self._membership_lock:
            if self._membership is None:
                uris = self._roster_servers or \
                    [u for u in os.environ.get(
                        "MXT_SERVER_URIS", "").split(",") if u] or \
                    [self.uri]
                from .membership import MembershipCoordinator
                self._membership = MembershipCoordinator(
                    uris, range(self.num_workers))
            return self._membership

    def _require_membership(self):
        m = self._get_membership()
        if m is None:
            raise RuntimeError(
                "not the roster coordinator (roster ops go to server 0 "
                "of an elastic job; set MXNET_KVSTORE_ELASTIC=1)")
        return m

    def _evict_silent_servers(self, m):
        """Coordinator-driven server eviction: a server whose beat went
        silent past hb_timeout is removed from the roster (the worker-
        report path converges to the same state; both are idempotent)."""
        for u in m.silent_servers(self._hb_timeout):
            try:
                m.report_dead_server(u)
            except RuntimeError:
                continue   # the last server is never evicted
            _prof.record_channel_event("kvstore.server_eviction")
            _prof.record_channel_gauge("kvstore.roster_generation",
                                       m.generation)

    def _roster_get(self):
        m = self._require_membership()
        self._evict_silent_servers(m)
        return m.roster().as_wire()

    def _roster_mutate(self, action, role, ident):
        """join/leave/dead for either role; returns the FULL post-change
        roster so the caller refreshes in the same round trip.  All
        mutations are idempotent — racing duplicate reports of one dead
        server collapse into a single generation bump."""
        m = self._require_membership()
        before = m.generation
        if role == "server":
            uri = str(ident)
            if action == "join":
                m.join_server(uri)
            elif action == "leave":
                m.leave_server(uri)
            else:
                m.report_dead_server(uri)
        elif role == "worker":
            rank = int(ident)
            if action == "join":
                m.join_worker(rank)
            elif action == "leave":
                m.leave_worker(rank)
                with self._barrier_cv:
                    self._hb_seen.pop(rank, None)
            else:
                m.evict_worker(rank)
                with self._barrier_cv:
                    self._hb_seen.pop(rank, None)
        else:
            raise ValueError(f"unknown roster role {role!r}")
        after = m.generation
        if after != before:
            if action == "dead":
                _prof.record_channel_event(
                    "kvstore.server_eviction" if role == "server"
                    else "kvstore.worker_eviction")
            _prof.record_channel_gauge("kvstore.roster_generation", after)
            with self._barrier_cv:
                # membership changed: parked barrier waiters must
                # re-evaluate their target against the new roster
                self._barrier_release_locked()
                self._barrier_cv.notify_all()
        return m.roster().as_wire()

    def _apply_handoff(self, gen, wire_key, arr, bkey):
        """Install a handed-off VALUE (the workers' quorum re-push, or a
        snapshot restripe).  First delivery per (wire_key, generation)
        wins; duplicates — every worker races to hand off the same
        bytes, and replays ride the exactly-once envelope on top — are
        acked without re-applying.  The first handoff of a logical key
        in a generation purges that key's stale wire forms (old stripe
        keys / whole-key form) plus their optimizer state, so a
        re-striped layout never leaves orphans behind."""
        from .ndarray import NDArray
        import jax.numpy as jnp
        if isinstance(arr, WirePayload):
            arr = _decompress(arr)
        with self._lock:
            if gen <= self._handoff_gen.get(wire_key, -1):
                _prof.record_channel_event("kvstore.handoff_dup")
                return False
            if self._handoff_base_gen.get(bkey, -1) < gen:
                self._handoff_base_gen[bkey] = gen
                stale = [k for k in self._store
                         if k == bkey or k.startswith(bkey + "@s")]
                for k in stale:
                    del self._store[k]
                    if self._updater is not None:
                        self._updater.states.pop(_key_int(k), None)
                        self._updater.states_synced.pop(_key_int(k), None)
            self._handoff_gen[wire_key] = gen
            self._store[wire_key] = NDArray(jnp.asarray(arr))
        _prof.record_channel_event("kvstore.handoff_applied")
        return True

    def _apply_handoff_state(self, gen, wire_key, state, bkey):
        """Install handed-off OPTIMIZER STATE for one wire key (from the
        coordinator's snapshot of the departed server, restriped by the
        handing-off worker).  Same first-per-generation dedup as value
        handoff; a None state clears the slot so the optimizer re-creates
        fresh state (the non-row-decomposable fallback)."""
        idx = _key_int(wire_key)
        with self._lock:
            if self._updater is None:
                return False
            if gen <= self._handoff_state_gen.get(wire_key, -1):
                _prof.record_channel_event("kvstore.handoff_dup")
                return False
            self._handoff_state_gen[wire_key] = gen
            st = _state_to_nd(state)
            if st is None:
                self._updater.states.pop(idx, None)
                self._updater.states_synced.pop(idx, None)
            else:
                self._updater.states[idx] = st
                self._updater.states_synced[idx] = True
        _prof.record_channel_event("kvstore.handoff_state_applied")
        return True

    def _snapshot_struct(self):
        """This shard's full state as a wire structure ({wire_key: np
        value} + per-key optimizer state) — what the beat loop ships to
        the coordinator so a SIGKILL does not take the shard's optimizer
        state to its grave.  Rides the zero-copy frames (np arrays never
        pass through pickle)."""
        with self._lock:
            store = {k: np.asarray(v.asnumpy())
                     for k, v in self._store.items()}
            states = {}
            if self._updater is not None:
                owned = {_key_int(k) for k in self._store}
                for k, st in self._updater.states.items():
                    if k in owned:
                        states[str(k)] = _state_to_np(st)
        return {"store": store, "states": states}

    def _command(self, head, body):
        """reference kvstore_dist_server.h:149-162 ``CommandHandle``."""
        if head == K_STOP_SERVER:
            self._stop.set()
            with self._barrier_cv:
                self._barrier_cv.notify_all()
            return None
        if head == K_CONTROLLER:
            from . import optimizer as opt
            with self._lock:
                # peer-supplied blob: decode through the transport
                # allowlist, never stock pickle
                self._updater = opt.get_updater(_restricted_loads(body))
            return None
        return None  # kSyncMode etc.: accepted, no-op in the async server

    def _barrier_target(self):
        """How many arrivals release the barrier.  Elastic coordinator:
        the LIVE roster's worker count (re-read every evaluation, so an
        eviction mid-wait shrinks the target); otherwise the static
        num_workers.  Caller holds _barrier_cv."""
        m = self._get_membership()
        if m is not None:
            return max(1, len(m.workers_snapshot()))
        return self.num_workers

    def _barrier_release_locked(self):
        """Release the barrier if the arrival count meets the (possibly
        just-shrunk) target.  Caller holds _barrier_cv."""
        if self._barrier_count < self._barrier_target() \
                or self._barrier_count <= 0:
            return False
        self._barrier_count = 0
        self._barrier_gen += 1
        self._barrier_ranks = set()
        self._barrier_cv.notify_all()
        return True

    def _barrier(self, rank=None):
        """Count one arrival per worker; release everyone when every
        live worker is in (reference: Postoffice::Barrier).

        The wait itself stays UNBOUNDED (a slow worker is legal) — but
        when the heartbeat registry shows a missing rank went SILENT
        past hb_timeout:

        * **static roster** — the wait fails naming the dead ranks AND
          each one's last-heartbeat age (operators get evidence, not
          just ids);
        * **elastic coordinator** — the barrier RENEGOTIATES instead of
          failing: the silent rank is evicted (generation bump), the
          target re-reads the live roster, and the parked survivors are
          released the moment the shrunken target is met.  Returns the
          roster generation so workers piggyback bump discovery on every
          barrier.  An evicted rank that was merely slow and arrives
          later is re-admitted (join, another bump) — its arrival must
          not corrupt the count."""
        with self._barrier_cv:
            m = self._get_membership()
            if m is not None and rank is not None \
                    and rank not in m.workers_snapshot():
                m.join_worker(rank)
                _prof.record_channel_gauge("kvstore.roster_generation",
                                           m.generation)
            gen = self._barrier_gen
            if rank is not None:
                self._barrier_ranks.add(rank)
            self._barrier_count += 1
            if self._barrier_release_locked():
                return self._barrier_payload()
            while self._barrier_gen == gen and not self._stop.is_set():
                self._barrier_cv.wait(0.1)
                if self._barrier_gen != gen or self._stop.is_set():
                    break
                silent = self._silent_ranks() - self._barrier_ranks
                if not silent:
                    continue
                if m is not None:
                    for r in sorted(silent):
                        m.evict_worker(r)
                        self._hb_seen.pop(r, None)
                        _prof.record_channel_event(
                            "kvstore.worker_eviction")
                    _prof.record_channel_gauge(
                        "kvstore.roster_generation", m.generation)
                    if self._barrier_release_locked():
                        return self._barrier_payload()
                    continue
                arrived = sorted(self._barrier_ranks)
                ages = self._heartbeat_ages(silent)
                # unwind this arrival so a later retry re-enters
                # cleanly once the dead rank is replaced
                self._barrier_count -= 1
                if rank is not None:
                    self._barrier_ranks.discard(rank)
                raise RuntimeError(
                    "barrier timed out: worker rank(s) %s missing "
                    "(no heartbeat for > %.1fs; %s); arrived rank(s): %s"
                    % (sorted(silent), self._hb_timeout, ages, arrived))
            return self._barrier_payload()

    def _barrier_payload(self):
        """Barrier replies carry the roster generation on an elastic
        coordinator (None otherwise) — the zero-extra-RTT way workers
        learn of roster bumps at every sync point.  Caller holds
        _barrier_cv."""
        m = self._get_membership()
        return None if m is None else m.generation

    # -- elastic beat loop (non-coordinator half) ----------------------------
    def _coordinator_addr(self):
        """(host, port) of roster server 0, or None.  Resolved lazily
        from the ctor roster / MXT_SERVER_URIS (in-process tests set the
        env after binding ports)."""
        uris = self._roster_servers or \
            [u for u in os.environ.get("MXT_SERVER_URIS", "").split(",")
             if u]
        if not uris or uris[0] == self.uri:
            return None
        host, port = uris[0].rsplit(":", 1)
        return (host, int(port))

    def _beat_loop(self):
        """Non-coordinator elastic servers beat the coordinator on their
        own socket (liveness) and piggyback a full state snapshot every
        MXNET_KVSTORE_SNAPSHOT_S seconds (the killed-server recovery
        source).  A missed beat IS the signal — the coordinator evicts
        on silence — so faults here are swallowed and the socket
        re-dialed next tick."""
        import socket as _socket
        interval = float(_env("MXNET_KVSTORE_HEARTBEAT_INTERVAL", 5.0))
        if interval <= 0:
            interval = 5.0
        last_snap = None
        sock = None
        while not self._stop.is_set():
            addr = self._coordinator_addr()
            if addr is not None:
                snap = None
                now = time.monotonic()
                if self._snapshot_s > 0 and (
                        last_snap is None
                        or now - last_snap >= self._snapshot_s):
                    snap = self._snapshot_struct()
                try:
                    if sock is None:
                        sock = _socket.create_connection(
                            addr, timeout=self._hb_timeout or 15.0)
                        sock.settimeout(self._hb_timeout or 15.0)
                    self._beat_seq += 1
                    _send_msg(sock, ("roster_beat", self.uri,
                                     self._beat_seq, snap))
                    status, _payload = _recv_msg(sock)
                    if status == "ok" and snap is not None:
                        last_snap = now
                except Exception:  # noqa: BLE001 — the miss IS the signal
                    _prof.record_channel_event("kvstore.beat_miss")
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                        sock = None
            self._stop.wait(min(interval, self._snapshot_s)
                            if self._snapshot_s > 0 else interval)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def leave(self):
        """GRACEFUL departure (scale-down, planned preemption): ship one
        final state snapshot to the coordinator, deregister from the
        roster (generation bump — workers re-stripe and hand the state
        back out at their next sync point), then stop serving.  The
        kill-path twin — SIGKILL, no goodbye — is what the periodic
        snapshot exists for."""
        import socket as _socket
        addr = self._coordinator_addr()
        if addr is not None:
            try:
                sock = _socket.create_connection(addr, timeout=15.0)
                sock.settimeout(15.0)
                try:
                    self._beat_seq += 1
                    _send_msg(sock, ("roster_beat", self.uri,
                                     self._beat_seq,
                                     self._snapshot_struct()))
                    _recv_msg(sock)
                    _send_msg(sock, ("roster_leave", "server", self.uri))
                    _recv_msg(sock)
                finally:
                    sock.close()
            except Exception:  # noqa: BLE001 — departing anyway; the
                # coordinator will evict us on beat silence instead
                _prof.record_channel_event("kvstore.beat_miss")
        self.stop()

    # -- connection plumbing -------------------------------------------------
    def _serve_conn(self, conn):
        try:
            with conn:
                while not self._stop.is_set():
                    try:
                        msg = _recv_msg(conn)
                    except (ConnectionError, OSError):
                        return
                    if msg and msg[0] == "req":
                        # client envelope: (op, client_id, seq, inner) —
                        # the exactly-once path (reconnect + replay)
                        _, cid, seq, inner = msg
                        reply = self._exactly_once(cid, seq, inner)
                        role = "server"
                    else:
                        # raw message (heartbeat pings, legacy callers):
                        # NOT fault-injection targetable — a delay-acks
                        # plan must never stall the liveness signal
                        # (faultinject.py's heartbeat-exemption contract)
                        try:
                            reply = ("ok", self._handle(msg))
                        except Exception as exc:  # noqa: BLE001
                            reply = ("err",
                                     f"{type(exc).__name__}: {exc}")
                        role = None
                    try:
                        _send_msg(conn, reply, fi_role=role)
                    except (ConnectionError, OSError):
                        # the client died / reconnected while we worked:
                        # the reply stays in the dedup window, so the
                        # replay on the new connection is acked from
                        # cache — drop this connection only
                        return
                    if role == "server":
                        # enveloped replies only: the deterministic ack
                        # count behind the process-level kill point
                        faultinject.server_replied()
        except Exception:  # noqa: BLE001 — conn died mid-reply
            pass

    def run(self):
        """Blocking accept loop; returns after a kStopServer command."""
        if self._elastic and self.server_id != 0 \
                and self._beat_thread is None:
            self._beat_thread = threading.Thread(target=self._beat_loop,
                                                 daemon=True)
            self._beat_thread.start()
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                if faultinject.server_accept(conn):
                    continue   # injected refusal: already closed
                _set_nodelay(conn)
                t = threading.Thread(target=self._serve_conn, args=(conn,),
                                     daemon=True)
                t.start()
                self._threads.append(t)
                self._conns.append(conn)
        finally:
            self._listener.close()

    def stop(self):
        self._stop.set()
        with self._barrier_cv:
            self._barrier_cv.notify_all()
        # close live connections too: a handler blocked in _recv_msg only
        # re-checks _stop after servicing a request, so without this a
        # "stopped" server still answers one more op per connection —
        # clients must see EOF promptly (and the crash-simulation tests
        # rely on exactly that)
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass

    def start_background(self):
        """Run the accept loop in a daemon thread (in-process tests)."""
        # analysis: allow(bare-thread): a crash unwinds through run()'s finally, closing the listener — every client observes it as refused connects within its retry budget, and in-flight conns keep their own _serve_conn handlers
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        return t


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _state_to_np(state):
    """Optimizer state → plain numpy for the snapshot/handoff wire
    (rides the zero-copy frames; non-array state is not
    row-decomposable and maps to None — see membership.restripe_states)."""
    from .ndarray import NDArray
    if state is None:
        return None
    if isinstance(state, NDArray):
        return np.asarray(state.asnumpy())
    if isinstance(state, np.ndarray):
        return state
    if isinstance(state, (tuple, list)):
        return tuple(_state_to_np(s) for s in state)
    return None


def _state_to_nd(state):
    """Wire numpy state → the NDArray shapes Updater stores."""
    from .ndarray import NDArray
    import jax.numpy as jnp
    if state is None:
        return None
    if isinstance(state, np.ndarray):
        return NDArray(jnp.asarray(state))
    if isinstance(state, (tuple, list)):
        parts = tuple(_state_to_nd(s) for s in state)
        return None if all(p is None for p in parts) else parts
    return None


def _init_kvstore_server_module():
    """Turn a ``DMLC_ROLE=server`` process into a blocking server, then
    exit — the reference hook verbatim (python/mxnet/kvstore_server.py:75:
    importing the library in a server-role process never returns to user
    code)."""
    if os.environ.get("DMLC_ROLE") != "server":
        return
    # This function blocks INSIDE `import mxnet_tpu`, so the package module
    # would stay flagged as initializing forever — and any connection
    # thread that triggers `import mxnet_tpu.*` (pickle.loads of an
    # optimizer does) would block on the parent module's import lock:
    # a guaranteed deadlock.  The package body is fully executed at this
    # point (this hook is its last statement), so clear the flag, and
    # pre-import everything the request handlers touch.
    import mxnet_tpu  # noqa: PLC0415 — self, already in sys.modules
    spec = getattr(mxnet_tpu, "__spec__", None)
    if spec is not None:
        spec._initializing = False
    from . import optimizer as _opt  # noqa: F401 — handler dependency
    from . import ndarray as _nd     # noqa: F401
    import jax.numpy as _jnp         # noqa: F401
    sid = int(os.environ.get("DMLC_SERVER_ID", "0"))
    uris = os.environ.get("MXT_SERVER_URIS", "")
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    host, port, my = "127.0.0.1", 0, None
    if uris:
        my = uris.split(",")[sid]
        host, port = my.rsplit(":", 1)
        port = int(port)
        # loopback-advertised servers (local launcher) bind loopback ONLY
        # — _recv_msg unpickles from any peer, so never expose the port
        # beyond what the deployment needs; ssh-mode servers must accept
        # remote workers and bind all interfaces (trusted-cluster model,
        # see module docstring)
        if host not in ("127.0.0.1", "localhost"):
            host = "0.0.0.0"
    # identity on the roster = the ADVERTISED uri (the bind host may be
    # 0.0.0.0 in ssh mode; workers and the coordinator know us by the
    # launcher-assigned address)
    server = KVStoreServer(server_id=sid, num_workers=num_workers,
                           host=host, port=port, uri=my)
    print(f"kvstore server {sid} listening on port {server.port}",
          flush=True)
    server.run()
    sys.exit(0)
