"""Registry-generated binary wire codec: pickle off the hot path.

The dist kvstore frame (kvstore_server.py) historically pickled a
message SKELETON per envelope — cheap next to the tensor bytes, but a
per-frame ``pickle.dumps``/``_restricted_loads`` round that the hot
ops (push/pull envelopes, their acks, mesh rounds, serving predicts)
pay millions of times per job.  This module replaces it with a flat
tag-encoded descriptor for exactly the ops the protocol registry
declares ``codec(binary)`` (mxnet_tpu.analysis.protocol — the op set
below is GENERATED from those declarations; ``analysis --check``
drift-fails a stale copy), so steady-state training and serving
serialize zero pickled bytes.

Frame layout v2 (binary)::

    0xB1      magic (one byte)
    >Q  total length of everything after this field
    >I  descriptor length D
    D bytes   tag-encoded DESCRIPTOR: the message with every ndarray
              replaced by a dtype+shape record
    ...       the raw tensor buffers, concatenated in ENCOUNTER order

The arithmetic after the magic byte is the classic ``>QI`` header
(total = 4 + D + sum of buffer bytes), and the receive side still maps
``np.frombuffer`` views over one contiguous body read.  A legacy
pickle frame's first byte is the high byte of its ``>Q`` total — i.e.
always ``0x00`` for any frame under 2**56 bytes — so the two formats
self-discriminate on the first byte and a receiver accepts BOTH at all
times.  Negotiation therefore only gates what a sender EMITS:

* a client opens every persistent connection with a raw (pickled)
  ``("codec_hello", 1)``; a new server registers the connection and
  replies ``("ok", <its version>)``; binary frames flow both ways.
* an old server answers ``("err", "ValueError: unknown op ...")`` and
  an old mesh leader acks raw messages with ``("ok", None)`` — both
  decode as version 0, and the connection stays pure pickle.
* ``MXNET_KVSTORE_CODEC=pickle`` pins either side to version 0 (the
  mixed-version escape hatch ci/run_ci.sh exercises); ``auto`` and
  ``binary`` negotiate.

Cold/extension traffic (roster, stats, handoff, shipped optimizers)
deliberately stays on the allowlisted pickle path — those payloads
carry real classes.  Only envelopes whose inner op is in the generated
``HOT_OPS`` set, and ``("ok"/"err", payload)`` replies, are binary-
eligible; anything the vocabulary below cannot express falls back to
pickle per message, never per job.

The decoder is hostile-input hardened to the same contract as the
restricted unpickler: any malformed length/count/dtype/overrun raises,
the serving loop drops that connection, and the server keeps serving
everyone else (tests/test_wirecodec.py mirrors the hostile-pickle
tests).
"""
from __future__ import annotations

import struct
import threading
import weakref

import numpy as np

from .base import env as _env
from .compression import RowSparsePayload, WirePayload, validate_rowsparse

# codec-table:begin (generated: python -m mxnet_tpu.analysis --codec-table)
HOT_OPS = frozenset({
    "mesh_collect",
    "mesh_push",
    "predict",
    "pull",
    "pull_rowsparse",
    "push",
    "push_multi",
})
CODEC_TABLE_FINGERPRINT = "f46bdbfc897f"
# codec-table:end

CODEC_VERSION = 1

# first byte of a v2 frame; a legacy pickle frame starts with the high
# byte of its >Q total, which is 0x00 for anything under 2**56 bytes
FRAME_MAGIC = 0xB1

HELLO_OP = "codec_hello"

# -- descriptor tags ----------------------------------------------------------
_T_NONE = 0x00
_T_TRUE = 0x01
_T_FALSE = 0x02
_T_INT = 0x03      # >q
_T_FLOAT = 0x04    # >d
_T_STR = 0x05      # >I utf-8 length + bytes
_T_BYTES = 0x06    # >I length + bytes
_T_TUPLE = 0x07    # >I count + items
_T_LIST = 0x08     # >I count + items
_T_DICT = 0x09     # >I count + (key, value) item pairs
_T_NDARRAY = 0x0A  # >B dtype-str length + dtype str + >B ndim + >q*ndim
_T_PAYLOAD = 0x0B  # WirePayload: kind, shape, threshold, data items
_T_ROWSPARSE = 0x0C  # RowSparsePayload: nrows, indices ndarray, data

_INT64_MIN = -(1 << 63)
_INT64_MAX = (1 << 63) - 1
_MAX_DEPTH = 64
_MAX_NDIM = 32


class Unencodable(Exception):
    """The message contains something outside the codec vocabulary —
    the caller falls back to the pickle frame for this message."""


def codec_mode() -> str:
    """The MXNET_KVSTORE_CODEC knob, normalized: 'auto' and 'binary'
    negotiate the binary codec per connection; 'pickle' pins the
    legacy framing (never hellos, answers hellos with version 0)."""
    mode = str(_env("MXNET_KVSTORE_CODEC", "auto")).strip().lower()
    return mode if mode in ("auto", "binary", "pickle") else "auto"


def local_version() -> int:
    """The version this process advertises in hello replies."""
    return 0 if codec_mode() == "pickle" else CODEC_VERSION


# -- per-connection negotiation ----------------------------------------------
# sock -> negotiated peer version.  Weak keys: a connection's entry
# dies with the socket object, so reconnects (fresh sockets) start
# un-negotiated by construction and closed sockets never pin memory.
_neg_lock = threading.Lock()
_negotiated: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def register(sock, version) -> None:
    """Record that the peer on ``sock`` speaks codec ``version``.
    A 'pickle'-pinned process never registers — it neither sends nor
    advertises binary frames (it still DECODES them; the format is
    self-describing, and a hostile peer can emit either regardless)."""
    if codec_mode() == "pickle":
        return
    if not isinstance(version, int) or isinstance(version, bool):
        return
    if version >= 1:
        with _neg_lock:
            try:
                _negotiated[sock] = int(version)
            except TypeError:
                pass   # unweakrefable test double: stays pickle


def sock_binary(sock) -> bool:
    """True when this side may EMIT binary frames on ``sock``."""
    with _neg_lock:
        try:
            ver = _negotiated.get(sock)
        except TypeError:
            return False
    return ver is not None and ver >= 1


def hello_msg():
    return (HELLO_OP, CODEC_VERSION)


def is_hello(msg) -> bool:
    return (isinstance(msg, tuple) and len(msg) == 2
            and msg[0] == HELLO_OP)


def handle_hello(sock, msg):
    """Server side of the negotiation: when ``msg`` is a codec hello,
    register the peer's version for ``sock`` and return the reply to
    send; None when ``msg`` is any other message."""
    if not is_hello(msg):
        return None
    register(sock, msg[1])
    return ("ok", local_version())


def client_hello(sock, send_msg, recv_msg,
                 byte_kinds=("control", "control_recv")) -> int:
    """Client side: one synchronous hello round on a fresh connection
    (before any pipelined traffic).  Returns the peer's version — 0
    for old peers (an old server errs on the unknown op, an old mesh
    leader acks raw messages with ``("ok", None)``), in which case the
    connection simply stays pickle.  Never called when this process is
    pinned to pickle."""
    if codec_mode() == "pickle":
        return 0
    send_msg(sock, hello_msg(), byte_kind=byte_kinds[0])
    reply = recv_msg(sock, byte_kind=byte_kinds[1])
    ver = 0
    if (isinstance(reply, tuple) and len(reply) == 2
            and reply[0] == "ok" and isinstance(reply[1], int)
            and not isinstance(reply[1], bool)):
        ver = int(reply[1])
    if ver >= 1:
        register(sock, ver)
    return ver


# -- what goes binary ---------------------------------------------------------
def is_hot(obj) -> bool:
    """Binary-eligible messages: exactly-once envelopes whose inner op
    is registry-declared hot, and ``("ok"/"err", payload)`` replies
    (acks of hot envelopes; a cold reply that happens to fit the
    vocabulary rides along harmlessly).  Cold requests — roster ops,
    stats, handoffs, shipped optimizer blobs — stay pickle."""
    if not (isinstance(obj, tuple) and obj):
        return False
    if obj[0] == "req" and len(obj) >= 4:
        inner = obj[3]
        return (isinstance(inner, tuple) and bool(inner)
                and inner[0] in HOT_OPS)
    return obj[0] in ("ok", "err") and len(obj) == 2


# -- encode -------------------------------------------------------------------
def _enc(obj, out, bufs, depth=0):
    if depth > _MAX_DEPTH:
        raise Unencodable("nesting too deep")
    if obj is None:
        out.append(_T_NONE)
    elif obj is True:
        out.append(_T_TRUE)
    elif obj is False:
        out.append(_T_FALSE)
    elif type(obj) is int:
        if not (_INT64_MIN <= obj <= _INT64_MAX):
            raise Unencodable("int out of int64 range")
        out.append(_T_INT)
        out += struct.pack(">q", obj)
    elif type(obj) is float:
        out.append(_T_FLOAT)
        out += struct.pack(">d", obj)
    elif type(obj) is str:
        raw = obj.encode("utf-8")
        out.append(_T_STR)
        out += struct.pack(">I", len(raw))
        out += raw
    elif type(obj) is bytes:
        out.append(_T_BYTES)
        out += struct.pack(">I", len(obj))
        out += obj
    elif type(obj) is tuple or type(obj) is list:
        out.append(_T_TUPLE if type(obj) is tuple else _T_LIST)
        out += struct.pack(">I", len(obj))
        for x in obj:
            _enc(x, out, bufs, depth + 1)
    elif type(obj) is dict:
        out.append(_T_DICT)
        out += struct.pack(">I", len(obj))
        for k, v in obj.items():
            _enc(k, out, bufs, depth + 1)
            _enc(v, out, bufs, depth + 1)
    elif isinstance(obj, np.ndarray) and not obj.dtype.hasobject:
        # same contiguity contract as the pickle frame's _pack: the
        # buffer is the C-contiguous copy/view, the LOGICAL shape is
        # the original's (ascontiguousarray promotes 0-d to 1-d)
        arr = np.ascontiguousarray(obj)
        dt = arr.dtype.str.encode("ascii")
        if len(dt) > 255 or arr.ndim > _MAX_NDIM \
                or len(obj.shape) > _MAX_NDIM:
            raise Unencodable("ndarray dtype/ndim outside codec bounds")
        out.append(_T_NDARRAY)
        out.append(len(dt))
        out += dt
        out.append(len(obj.shape))
        for dim in obj.shape:
            out += struct.pack(">q", dim)
        bufs.append(arr)
    elif isinstance(obj, WirePayload):
        out.append(_T_PAYLOAD)
        _enc(obj.kind, out, bufs, depth + 1)
        _enc(tuple(obj.shape) if obj.shape is not None else None,
             out, bufs, depth + 1)
        _enc(obj.threshold, out, bufs, depth + 1)
        _enc(obj.data, out, bufs, depth + 1)
    elif isinstance(obj, RowSparsePayload):
        # indices and value rows each ride as a zero-copy tensor
        # buffer; anything the ndarray branch can't express (e.g. a
        # _Buf placeholder from the pickle path) falls back there
        out.append(_T_ROWSPARSE)
        _enc(int(obj.nrows), out, bufs, depth + 1)
        if not isinstance(obj.indices, np.ndarray):
            raise Unencodable("row-sparse indices not an ndarray")
        _enc(obj.indices, out, bufs, depth + 1)
        _enc(obj.data, out, bufs, depth + 1)
    else:
        raise Unencodable(type(obj).__name__)


def encode_frame(obj):
    """Encode ``obj`` as one v2 frame: ``(head, bufs)`` where ``head``
    is the magic + ``>QI`` header + descriptor in ONE buffer (built in
    place — no header/skeleton concat copy) and ``bufs`` are the raw
    tensor buffers to follow in order.  None when the message falls
    outside the codec vocabulary (caller falls back to pickle)."""
    out = bytearray(13)
    bufs = []
    try:
        _enc(obj, out, bufs)
    except Unencodable:
        return None
    desc_len = len(out) - 13
    total = 4 + desc_len + sum(a.nbytes for a in bufs)
    out[0] = FRAME_MAGIC
    struct.pack_into(">QI", out, 1, total, desc_len)
    return out, bufs


# -- decode (hostile-input hardened) ------------------------------------------
class _Reader:
    __slots__ = ("desc", "pos", "body", "body_off")

    def __init__(self, desc, body):
        self.desc = desc
        self.pos = 0
        self.body = body
        self.body_off = 0

    def take(self, n):
        if n < 0 or self.pos + n > len(self.desc):
            raise ValueError("wirecodec: descriptor overrun")
        out = self.desc[self.pos:self.pos + n]
        self.pos += n
        return out

    def byte(self):
        return self.take(1)[0]

    def u32(self):
        return struct.unpack(">I", self.take(4))[0]

    def i64(self):
        return struct.unpack(">q", self.take(8))[0]

    def remaining(self):
        return len(self.desc) - self.pos


def _dec(r, depth=0):
    if depth > _MAX_DEPTH:
        raise ValueError("wirecodec: descriptor nesting too deep")
    tag = r.byte()
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        return r.i64()
    if tag == _T_FLOAT:
        return struct.unpack(">d", r.take(8))[0]
    if tag == _T_STR:
        return r.take(r.u32()).decode("utf-8")
    if tag == _T_BYTES:
        return bytes(r.take(r.u32()))
    if tag in (_T_TUPLE, _T_LIST):
        n = r.u32()
        if n > r.remaining():   # every item costs >= 1 descriptor byte
            raise ValueError("wirecodec: container count overruns "
                             "descriptor")
        items = [_dec(r, depth + 1) for _ in range(n)]
        return tuple(items) if tag == _T_TUPLE else items
    if tag == _T_DICT:
        n = r.u32()
        if 2 * n > r.remaining():
            raise ValueError("wirecodec: dict count overruns descriptor")
        out = {}
        for _ in range(n):
            k = _dec(r, depth + 1)
            try:
                out[k] = _dec(r, depth + 1)
            except TypeError as exc:
                raise ValueError("wirecodec: unhashable dict key") \
                    from exc
        return out
    if tag == _T_NDARRAY:
        dt_raw = r.take(r.byte())
        try:
            dtype = np.dtype(dt_raw.decode("ascii"))
        except (TypeError, ValueError, UnicodeDecodeError) as exc:
            raise ValueError("wirecodec: bad dtype %r" % dt_raw) from exc
        if dtype.hasobject:
            raise ValueError("wirecodec: object dtype refused")
        ndim = r.byte()
        if ndim > _MAX_NDIM:
            raise ValueError("wirecodec: ndim %d over cap" % ndim)
        shape = tuple(r.i64() for _ in range(ndim))
        count = 1
        for dim in shape:
            if dim < 0:
                raise ValueError("wirecodec: negative dimension")
            count *= dim
        nbytes = count * dtype.itemsize
        if nbytes > len(r.body) - r.body_off:
            raise ValueError("wirecodec: tensor buffer overruns body")
        arr = np.frombuffer(r.body, dtype=dtype, count=count,
                            offset=r.body_off).reshape(shape)
        r.body_off += nbytes
        return arr
    if tag == _T_PAYLOAD:
        kind = _dec(r, depth + 1)
        shape = _dec(r, depth + 1)
        threshold = _dec(r, depth + 1)
        data = _dec(r, depth + 1)
        return WirePayload(kind, shape, threshold, data)
    if tag == _T_ROWSPARSE:
        nrows = _dec(r, depth + 1)
        if not isinstance(nrows, int) or isinstance(nrows, bool):
            raise ValueError("wirecodec: row-sparse nrows not an int")
        indices = _dec(r, depth + 1)
        data = _dec(r, depth + 1)
        try:
            return validate_rowsparse(
                RowSparsePayload(indices, nrows, data))
        except (ValueError, TypeError, OverflowError) as exc:
            raise ValueError(f"wirecodec: {exc}") from exc
    raise ValueError("wirecodec: unknown tag 0x%02x" % tag)


def decode_frame(desc, body):
    """Decode one v2 frame's descriptor + contiguous buffer body.
    Raises ValueError on ANY malformed input — the serving loops treat
    that exactly like a hostile pickle: connection dropped, server
    keeps serving (strict full consumption: trailing descriptor or
    body bytes are an error, not padding)."""
    r = _Reader(desc, body)
    obj = _dec(r)
    if r.pos != len(desc):
        raise ValueError("wirecodec: %d trailing descriptor byte(s)"
                         % (len(desc) - r.pos))
    if r.body_off != len(body):
        raise ValueError("wirecodec: %d trailing body byte(s)"
                         % (len(body) - r.body_off))
    return obj


def frame_len(prefix) -> int:
    """Frame-in-ring framing arithmetic: the COMPLETE byte length of
    the frame whose first 13 bytes begin ``prefix``, for either format.
    A v2 binary frame occupies 13 header bytes (magic + ``>QI``) plus
    ``total - 4`` descriptor/body bytes = ``9 + total``; a legacy
    pickle frame 12 header bytes plus ``total - 4`` = ``8 + total``.
    The same-host shm lane stores ONE frame per length-prefixed ring
    record, and both ends cross-check the record length against this
    before decoding — shared memory has no short reads, so a mismatch
    means ring corruption and kills the lane (TCP fallback), never a
    partial frame."""
    view = memoryview(prefix)
    if view.nbytes < 13:
        raise ValueError("wirecodec: frame prefix shorter than 13 bytes")
    if view[0] == FRAME_MAGIC:
        total, desc_len = struct.unpack(">QI", view[1:13])
        if desc_len + 4 > total:
            raise ValueError("wirecodec: descriptor overruns frame")
        return 9 + int(total)
    total, skel_len = struct.unpack(">QI", view[0:12])
    if skel_len + 4 > total:
        raise ValueError("wirecodec: skeleton overruns frame")
    return 8 + int(total)
