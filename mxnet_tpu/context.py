"""Device context.

TPU-native equivalent of the reference's ``Context`` (python/mxnet/context.py,
include/mxnet/base.h:129-210).  A ``Context`` names a logical device; it maps
onto a PJRT :class:`jax.Device`.  ``mx.tpu(i)`` is the first-class accelerator
context (the reference's ``mx.gpu(i)``); ``mx.gpu`` is kept as an alias so
reference user code runs unchanged.  When no TPU backend is present (unit
tests run with ``JAX_PLATFORMS=cpu`` and a virtual 8-device CPU mesh),
``tpu(i)`` transparently resolves to host device *i*, mirroring how the
reference unit-tests multi-device logic with multiple CPU contexts
(SURVEY.md §4 "Multi-device (fake cluster)").
"""
from __future__ import annotations

import threading
from typing import Optional

from .base import MXNetError


class Context:
    """A logical device (cpu/tpu/gpu-alias) backed by a PJRT jax.Device."""

    # reference devtype ids (base.h:137-146) + tpu extension
    devtype2id = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
    devid2type = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_type, self.device_id = device_type.device_type, device_type.device_id
        else:
            if device_type not in self.devtype2id:
                raise MXNetError(f"unknown device type {device_type!r}")
            self.device_type = device_type
            self.device_id = device_id
        self._old_ctx: Optional[Context] = None

    @property
    def device_typeid(self) -> int:
        return self.devtype2id[self.device_type]

    def jax_device(self):
        """Resolve to the PJRT device backing this context.

        Process-LOCAL devices only: under jax.distributed the global device
        list includes other hosts' devices, which this process cannot
        address (multi-host placement is expressed with meshes/shardings,
        never by binding a Context to a remote device)."""
        import jax
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            try:
                devs = jax.local_devices(backend="cpu")
            except RuntimeError:
                devs = jax.local_devices()
            return devs[self.device_id % len(devs)]
        # tpu / gpu-alias: prefer a real accelerator, else fall back to the
        # default backend (virtual CPU devices in tests).
        devs = jax.local_devices()
        return devs[self.device_id % len(devs)]

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __str__(self):
        return f"{self.device_type}({self.device_id})"

    __repr__ = __str__

    def __enter__(self):
        if not hasattr(self._default_ctx, "value"):
            self._default_ctx.value = Context("cpu", 0)
        self._old_ctx = self._default_ctx.value
        self._default_ctx.value = self
        return self

    def __exit__(self, *args):
        self._default_ctx.value = self._old_ctx

    def empty_cache(self):
        """Release cached device memory (reference: storage pool ReleaseAll).

        PJRT owns HBM; this asks JAX to drop live-but-unreferenced buffers.
        """
        import gc
        gc.collect()


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias for :func:`tpu` so reference scripts run unchanged on TPU pods."""
    return Context("tpu", device_id)


def num_gpus() -> int:
    """Number of accelerator devices visible (reference: mx.context.num_gpus)."""
    import jax
    try:
        return len([d for d in jax.devices() if d.platform != "cpu"])
    except RuntimeError:
        return 0


def num_tpus() -> int:
    return num_gpus()


def current_context() -> Context:
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value
