"""Pretrained-model file cache (reference:
python/mxnet/gluon/model_zoo/model_store.py).

This build runs with zero network egress: `get_model_file` only resolves
files already present under the cache root and raises otherwise, with the
same path layout the reference downloads into (~/.mxnet/models).
"""
from __future__ import annotations

import os

from ...base import MXNetError


def get_model_file(name, root=os.path.join('~', '.mxnet', 'models')):
    root = os.path.expanduser(root)
    file_path = os.path.join(root, f'{name}.params')
    if os.path.exists(file_path):
        return file_path
    raise MXNetError(
        f"Pretrained weights {file_path!r} not found. This environment has "
        f"no network egress — place the .params file there manually "
        f"(reference layout: model_store.py download cache)")


def purge(root=os.path.join('~', '.mxnet', 'models')):
    root = os.path.expanduser(root)
    if os.path.isdir(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
