"""Variational dropout cell
(reference: python/mxnet/gluon/contrib/rnn/rnn_cell.py:26-160).
"""
from __future__ import annotations

from ...rnn.rnn_cell import (ModifierCell, BidirectionalCell,
                             SequentialRNNCell)


class VariationalDropoutCell(ModifierCell):
    """Applies variational dropout (Gal & Ghahramani 2016): ONE dropout
    mask per sequence for inputs/states/outputs, sampled at the first step
    and reused until ``reset()``.

    reference: gluon/contrib/rnn/rnn_cell.py:26 — mask semantics match
    (inputs/outputs/states masks are independent; state dropout applies to
    the first state only, i.e. h, not c).
    """

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        assert not drop_states or not isinstance(base_cell,
                                                 BidirectionalCell), \
            "BidirectionalCell doesn't support variational state dropout; " \
            "apply VariationalDropoutCell to the cells underneath instead."
        assert not drop_states \
            or not isinstance(base_cell, SequentialRNNCell) \
            or not getattr(base_cell, '_bidirectional', False), \
            "Bidirectional SequentialRNNCell doesn't support variational " \
            "state dropout; apply to the cells underneath instead."
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _alias(self):
        return 'vardrop'

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def hybrid_forward(self, F, inputs, states):
        if self.drop_states and self.drop_states_mask is None:
            self.drop_states_mask = F.Dropout(F.ones_like(states[0]),
                                              p=self.drop_states)
        if self.drop_inputs and self.drop_inputs_mask is None:
            self.drop_inputs_mask = F.Dropout(F.ones_like(inputs),
                                              p=self.drop_inputs)
        if self.drop_states:
            states = list(states)
            states[0] = states[0] * self.drop_states_mask
        if self.drop_inputs:
            inputs = inputs * self.drop_inputs_mask
        next_output, next_states = self.base_cell(inputs, states)
        if self.drop_outputs and self.drop_outputs_mask is None:
            self.drop_outputs_mask = F.Dropout(F.ones_like(next_output),
                                               p=self.drop_outputs)
        if self.drop_outputs:
            next_output = next_output * self.drop_outputs_mask
        return next_output, next_states

    def __repr__(self):
        return (f'VariationalDropoutCell(p_in={self.drop_inputs}, '
                f'p_state={self.drop_states}, p_out={self.drop_outputs})')
