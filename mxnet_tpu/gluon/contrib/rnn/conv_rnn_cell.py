"""Convolutional recurrent cells for Gluon
(reference: python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py:37-977).

States are channel-first feature maps ((C,), (C, W), (C, H, W) or
(C, D, H, W) per sample); i2h/h2h projections are convolutions.  The h2h
convolution is stride-1 same-padded (odd kernels only), so the state shape
is constant across steps; the i2h convolution decides the state's spatial
extent from ``input_shape`` at construction, exactly like the reference's
``_decide_shapes``.  Channel-first only (the TPU Convolution op's native
logical layout here; XLA picks physical layouts itself).
"""
from __future__ import annotations

import numpy as np

from ....base import MXNetError
from ...rnn.rnn_cell import HybridRecurrentCell


def _tup(spec, dims, name):
    if isinstance(spec, (int, np.integer)):
        return (int(spec),) * dims
    spec = tuple(int(s) for s in spec)
    if len(spec) != dims:
        raise MXNetError(
            f"{name} must be an int or length-{dims} tuple, got {spec}")
    return spec


def _conv_out_size(dimensions, kernel, pad, dilate):
    return tuple((x + 2 * p - d * (k - 1) - 1) + 1
                 for x, k, p, d in zip(dimensions, kernel, pad, dilate))


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared machinery (reference: conv_rnn_cell.py:37 _BaseConvRNNCell)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, dims, conv_layout, activation,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if conv_layout not in ('NCW', 'NCHW', 'NCDHW')[dims - 1:dims]:
            raise MXNetError(
                f"conv_layout must be channel-first for {dims}D "
                f"(got {conv_layout!r})")
        self._hidden_channels = hidden_channels
        self._input_shape = tuple(input_shape)
        self._conv_layout = conv_layout
        self._activation = activation
        self._i2h_kernel = _tup(i2h_kernel, dims, 'i2h_kernel')
        self._i2h_pad = _tup(i2h_pad, dims, 'i2h_pad')
        self._i2h_dilate = _tup(i2h_dilate, dims, 'i2h_dilate')
        self._h2h_kernel = _tup(h2h_kernel, dims, 'h2h_kernel')
        if any(k % 2 == 0 for k in self._h2h_kernel):
            raise MXNetError(
                f"h2h_kernel must be odd, got {self._h2h_kernel}")
        self._h2h_dilate = _tup(h2h_dilate, dims, 'h2h_dilate')
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))
        self._stride = (1,) * dims

        in_channels = self._input_shape[0]
        spatial = self._input_shape[1:]
        out_spatial = _conv_out_size(spatial, self._i2h_kernel,
                                     self._i2h_pad, self._i2h_dilate)
        total = hidden_channels * self._num_gates
        self._state_shape = (hidden_channels,) + out_spatial
        self.i2h_weight = self.params.get(
            'i2h_weight', shape=(total, in_channels) + self._i2h_kernel,
            init=i2h_weight_initializer)
        self.h2h_weight = self.params.get(
            'h2h_weight', shape=(total, hidden_channels) + self._h2h_kernel,
            init=h2h_weight_initializer)
        self.i2h_bias = self.params.get(
            'i2h_bias', shape=(total,), init=i2h_bias_initializer)
        self.h2h_bias = self.params.get(
            'h2h_bias', shape=(total,), init=h2h_bias_initializer)

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size,) + self._state_shape,
                 '__layout__': self._conv_layout}
                for _ in range(self._num_states)]

    def _conv_forward(self, F, inputs, states,
                      i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, stride=self._stride,
                            pad=self._i2h_pad, dilate=self._i2h_dilate,
                            num_filter=self._hidden_channels
                            * self._num_gates)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, stride=self._stride,
                            pad=self._h2h_pad, dilate=self._h2h_dilate,
                            num_filter=self._hidden_channels
                            * self._num_gates)
        return i2h, h2h

    def __repr__(self):
        return (f'{self.__class__.__name__}'
                f'({self._input_shape} -> {self._state_shape})')


class _ConvRNNCell(_BaseConvRNNCell):
    """reference: conv_rnn_cell.py:176."""

    _num_states = 1

    @property
    def _gate_names(self):
        return ('',)

    def _alias(self):
        return 'conv_rnn'

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class _ConvLSTMCell(_BaseConvRNNCell):
    """reference: conv_rnn_cell.py:419 (Shi et al. 2015)."""

    _num_states = 2

    @property
    def _gate_names(self):
        return ('_i', '_f', '_c', '_o')

    def _alias(self):
        return 'conv_lstm'

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        sl = list(F.SliceChannel(gates, num_outputs=4, axis=1))
        in_gate = F.Activation(sl[0], act_type='sigmoid')
        forget_gate = F.Activation(sl[1], act_type='sigmoid')
        in_transform = self._get_activation(F, sl[2], self._activation)
        out_gate = F.Activation(sl[3], act_type='sigmoid')
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    """reference: conv_rnn_cell.py:703."""

    _num_states = 1

    @property
    def _gate_names(self):
        return ('_r', '_z', '_o')

    def _alias(self):
        return 'conv_gru'

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        i2h_sl = list(F.SliceChannel(i2h, num_outputs=3, axis=1))
        h2h_sl = list(F.SliceChannel(h2h, num_outputs=3, axis=1))
        reset_gate = F.Activation(i2h_sl[0] + h2h_sl[0], act_type='sigmoid')
        update_gate = F.Activation(i2h_sl[1] + h2h_sl[1], act_type='sigmoid')
        next_h_tmp = self._get_activation(
            F, i2h_sl[2] + reset_gate * h2h_sl[2], self._activation)
        next_h = (1. - update_gate) * next_h_tmp + update_gate * states[0]
        return next_h, [next_h]


def _make_cell(base, dims, layout, doc_dims):
    class Cell(base):
        __doc__ = (f"{doc_dims}D convolutional "
                   f"{base.__name__.strip('_').replace('Conv', '')} cell "
                   f"(reference: gluon/contrib/rnn/conv_rnn_cell.py).")

        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                     i2h_weight_initializer=None,
                     h2h_weight_initializer=None,
                     i2h_bias_initializer='zeros',
                     h2h_bias_initializer='zeros',
                     conv_layout=layout, activation='tanh',
                     prefix=None, params=None):
            super().__init__(input_shape, hidden_channels, i2h_kernel,
                             h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                             i2h_weight_initializer,
                             h2h_weight_initializer, i2h_bias_initializer,
                             h2h_bias_initializer, dims, conv_layout,
                             activation, prefix=prefix, params=params)
    return Cell


Conv1DRNNCell = _make_cell(_ConvRNNCell, 1, 'NCW', 1)
Conv2DRNNCell = _make_cell(_ConvRNNCell, 2, 'NCHW', 2)
Conv3DRNNCell = _make_cell(_ConvRNNCell, 3, 'NCDHW', 3)
Conv1DLSTMCell = _make_cell(_ConvLSTMCell, 1, 'NCW', 1)
Conv2DLSTMCell = _make_cell(_ConvLSTMCell, 2, 'NCHW', 2)
Conv3DLSTMCell = _make_cell(_ConvLSTMCell, 3, 'NCDHW', 3)
Conv1DGRUCell = _make_cell(_ConvGRUCell, 1, 'NCW', 1)
Conv2DGRUCell = _make_cell(_ConvGRUCell, 2, 'NCHW', 2)
Conv3DGRUCell = _make_cell(_ConvGRUCell, 3, 'NCDHW', 3)
for _c, _n in [(Conv1DRNNCell, 'Conv1DRNNCell'),
               (Conv2DRNNCell, 'Conv2DRNNCell'),
               (Conv3DRNNCell, 'Conv3DRNNCell'),
               (Conv1DLSTMCell, 'Conv1DLSTMCell'),
               (Conv2DLSTMCell, 'Conv2DLSTMCell'),
               (Conv3DLSTMCell, 'Conv3DLSTMCell'),
               (Conv1DGRUCell, 'Conv1DGRUCell'),
               (Conv2DGRUCell, 'Conv2DGRUCell'),
               (Conv3DGRUCell, 'Conv3DGRUCell')]:
    _c.__name__ = _c.__qualname__ = _n
