"""Gluon contrib: experimental blocks
(reference: python/mxnet/gluon/contrib/)."""
from . import rnn  # noqa: F401
from . import nn   # noqa: F401
