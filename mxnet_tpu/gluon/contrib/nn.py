"""Gluon contrib blocks with no reference analog — TPU-native additions.

ChunkedLMHead is the gluon face of ops/chunked_loss.py: the lm-head
projection and softmax cross-entropy fused over vocab chunks, so the
(N, V) logits never materialize.
"""
from __future__ import annotations

import numpy as np

from ..block import HybridBlock


class ChunkedLMHead(HybridBlock):
    """Fused lm-head projection + per-token CE loss over vocab chunks
    (ops/chunked_loss.py — the flash-attention trick along vocab).

    Call with (hidden (N, in_units), label (N,)) → per-token loss (N,).
    Parameters are named ``weight``/``bias`` like Dense, so a trained
    head's weights load straight into a Dense of the same shape for
    full-logits inference.

    ``in_units`` is REQUIRED (unlike Dense): the loss op has no
    symbolic shape hook to back-fill a deferred weight, and the head's
    input width is always known where an LM is assembled.
    """

    def __init__(self, vocab_size, in_units, num_chunks=8,
                 dtype=np.float32, weight_initializer=None,
                 bias_initializer='zeros', **kwargs):
        super().__init__(**kwargs)
        if int(num_chunks) < 1:
            raise ValueError(f"num_chunks must be >= 1, got {num_chunks}")
        if int(in_units) < 1:
            raise ValueError(
                f"in_units must be a known positive width, got {in_units}"
                " (ChunkedLMHead does not support deferred shape init)")
        self._chunks = int(num_chunks)
        with self.name_scope():
            self.weight = self.params.get(
                'weight', shape=(int(vocab_size), int(in_units)),
                dtype=dtype, init=weight_initializer)
            self.bias = self.params.get(
                'bias', shape=(int(vocab_size),), dtype=dtype,
                init=bias_initializer)

    def hybrid_forward(self, F, hidden, label, weight, bias):
        return F.chunked_lm_loss(hidden, weight, bias, label,
                                 num_chunks=self._chunks)
