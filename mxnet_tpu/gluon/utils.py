"""Gluon utilities (reference: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import math

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.ndarray import array as nd_array


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch_axis into num_slice chunks
    (reference: utils.py:28)."""
    size = data.shape[batch_axis]
    if size < num_slice:
        raise ValueError(
            f"Too many slices ({num_slice}) for data with shape "
            f"{data.shape}")
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}. "
            f"Use a batch size that's a multiple of {num_slice} or set "
            f"even_split=False")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        lo = i * step
        hi = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(lo, hi)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data into len(ctx_list) slices and load one per context
    (reference: utils.py:81).  On a mesh, prefer handing the FULL batch to
    a sharded Module — this helper exists for per-device imperative loops.
    """
    if not isinstance(data, NDArray):
        data = nd_array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm):
    """Rescale arrays so the global L2 norm <= max_norm
    (reference: utils.py:117)."""
    assert len(arrays) > 0
    total = 0.0
    for arr in arrays:
        n = arr.norm().asscalar()
        total += float(n) ** 2
    total = math.sqrt(total)
    if not np.isfinite(total):
        import warnings
        warnings.warn(UserWarning('nan or inf is detected. Clipping '
                                  'results will be undefined.'),
                      stacklevel=2)
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total


def check_sha1(filename, sha1_hash):
    """reference: utils.py check_sha1."""
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, 'rb') as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None):
    """reference: utils.py download — kept for API parity; this build runs
    with no network egress, so a missing local file is an error."""
    import os
    fname = path if path and not os.path.isdir(path) else \
        os.path.join(path or '.', url.split('/')[-1])
    if os.path.exists(fname) and not overwrite and (
            not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    raise MXNetError(
        f"download({url!r}): no network egress in this environment and "
        f"{fname!r} does not exist locally. Place the file there manually.")
