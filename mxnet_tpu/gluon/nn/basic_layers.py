"""Gluon basic layers.

TPU-native port surface of python/mxnet/gluon/nn/basic_layers.py: every
layer is a HybridBlock whose hybrid_forward calls registry ops, so the same
definition runs eagerly (tape autograd) or hybridized (jit cache).
"""
from __future__ import annotations

import numpy as np

from ..block import Block, HybridBlock
from ...base import MXNetError


class Sequential(Block):
    """Stack of Blocks run in order (reference: basic_layers.py:29)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)
            super(Block, self).__setattr__(
                f'_child{len(self._children)-1}', block)

    def forward(self, x):
        for block in self._children:
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]


class HybridSequential(HybridBlock):
    """reference: basic_layers.py:84."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)
            super(Block, self).__setattr__(
                f'_child{len(self._children)-1}', block)

    def hybrid_forward(self, F, x):
        for block in self._children:
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]


class Dense(HybridBlock):
    """Fully-connected layer (reference: basic_layers.py:140)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype=np.float32, weight_initializer=None,
                 bias_initializer='zeros', in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._act_type = activation
        with self.name_scope():
            self.weight = self.params.get(
                'weight', shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    'bias', shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out


class Activation(HybridBlock):
    """reference: basic_layers.py:226."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation   # before super(): _alias() needs it
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class Dropout(HybridBlock):
    """reference: basic_layers.py:258."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """reference: basic_layers.py:300."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer='zeros',
                 gamma_initializer='ones', running_mean_initializer='zeros',
                 running_variance_initializer='ones', in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {'axis': axis, 'eps': epsilon, 'momentum': momentum,
                        'fix_gamma': not scale,
                        'use_global_stats': use_global_stats}
        with self.name_scope():
            self.gamma = self.params.get(
                'gamma', grad_req='write' if scale else 'null',
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                'beta', grad_req='write' if center else 'null',
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                'running_mean', grad_req='null', shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                'running_var', grad_req='null', shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        # eager: _invoke writes the updated moving stats back into the
        # running_mean/var arrays (ndarray.py _invoke aux writeback);
        # hybridized: the cached graph returns new_aux and _call_cached
        # writes them back
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)


class InstanceNorm(HybridBlock):
    """reference: basic_layers.py InstanceNorm."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer='zeros', gamma_initializer='ones',
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                'gamma', grad_req='write' if scale else 'null',
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                'beta', grad_req='write' if center else 'null',
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._eps)


class LayerNorm(HybridBlock):
    """Layer normalization (post-reference addition kept for parity with
    later MXNet; normalizes the last axis)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer='zeros', gamma_initializer='ones',
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                'gamma', grad_req='write' if scale else 'null',
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                'beta', grad_req='write' if center else 'null',
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._eps)


class Embedding(HybridBlock):
    """reference: basic_layers.py Embedding."""

    def __init__(self, input_dim, output_dim, dtype=np.float32,
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {'input_dim': input_dim, 'output_dim': output_dim,
                        'sparse_grad': sparse_grad}
        with self.name_scope():
            self.weight = self.params.get(
                'weight', shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True,
                grad_stype='row_sparse' if sparse_grad else 'default')

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)


class Flatten(HybridBlock):
    """reference: basic_layers.py Flatten."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class Lambda(Block):
    """Wrap a function as a Block (reference: basic_layers.py Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd_mod
            if not hasattr(nd_mod, function):
                raise MXNetError(f"ndarray has no function {function!r}")
            self._func = getattr(nd_mod, function)
            self._func_name = function
        else:
            self._func = function
            self._func_name = getattr(function, '__name__', 'lambda')

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    """reference: basic_layers.py HybridLambda."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func = lambda F, *args: getattr(F, function)(*args)
            self._func_name = function
        else:
            self._func = function
            self._func_name = getattr(function, '__name__', 'lambda')

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)


# -- advanced activations (reference: gluon/nn/activations later versions;
#    LeakyReLU existed in basic_layers.py) ---------------------------------
class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type='leaky', slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer='zeros', **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.alpha = self.params.get('alpha', shape=(1,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type='prelu')


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type='elu', slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type='selu')


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type='gelu')
