"""Gluon convolution / pooling layers.

TPU-native port surface of python/mxnet/gluon/nn/conv_layers.py.  All ops
lower to lax.conv_general_dilated / reduce_window through the registry
(ops/nn.py) and tile onto the MXU; layout stays NCHW user-facing.
"""
from __future__ import annotations

import numpy as np

from ..block import HybridBlock
from ...base import MXNetError


def _tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


class _Conv(HybridBlock):
    """Base conv layer (reference: conv_layers.py:33 _Conv)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer='zeros', op_name='Convolution',
                 adj=None, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        rank = len(kernel_size)
        self._kwargs = {
            'kernel': kernel_size, 'stride': strides, 'dilate': dilation,
            'pad': padding, 'num_filter': channels, 'num_group': groups,
            'no_bias': not use_bias, 'layout': layout}
        if adj is not None:
            self._kwargs['adj'] = adj
        self._op_name = op_name
        self._act_type = activation

        with self.name_scope():
            if op_name == 'Convolution':
                wshape = (channels, in_channels // groups
                          if in_channels else 0) + tuple(kernel_size)
            else:  # Deconvolution weight is (in, out/groups, *k)
                wshape = (in_channels if in_channels else 0,
                          channels // groups) + tuple(kernel_size)
            self.weight = self.params.get(
                'weight', shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    'bias', shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            out = op(x, weight, **self._kwargs)
        else:
            out = op(x, weight, bias, **self._kwargs)
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout='NCW', activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer='zeros', in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), _tuple(strides, 1),
                         _tuple(padding, 1), _tuple(dilation, 1), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout='NCHW', activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer='zeros', in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), _tuple(strides, 2),
                         _tuple(padding, 2), _tuple(dilation, 2), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout='NCDHW', activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer='zeros',
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), _tuple(strides, 3),
                         _tuple(padding, 3), _tuple(dilation, 3), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout='NCW',
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer='zeros', in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 1), _tuple(strides, 1),
                         _tuple(padding, 1), _tuple(dilation, 1), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name='Deconvolution',
                         adj=_tuple(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout='NCHW', activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer='zeros',
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 2), _tuple(strides, 2),
                         _tuple(padding, 2), _tuple(dilation, 2), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name='Deconvolution',
                         adj=_tuple(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout='NCDHW',
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer='zeros', in_channels=0, **kwargs):
        super().__init__(channels, _tuple(kernel_size, 3), _tuple(strides, 3),
                         _tuple(padding, 3), _tuple(dilation, 3), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name='Deconvolution',
                         adj=_tuple(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    """Base pooling (reference: conv_layers.py _Pooling)."""

    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            'kernel': pool_size, 'stride': strides, 'pad': padding,
            'global_pool': global_pool, 'pool_type': pool_type,
            'pooling_convention': 'full' if ceil_mode else 'valid'}

    def _alias(self):
        return 'pool'

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout='NCW',
                 ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 1),
                         _tuple(strides, 1) if strides is not None else None,
                         _tuple(padding, 1), ceil_mode, False, 'max',
                         **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout='NCHW', ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 2),
                         _tuple(strides, 2) if strides is not None else None,
                         _tuple(padding, 2), ceil_mode, False, 'max',
                         **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout='NCDHW', ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 3),
                         _tuple(strides, 3) if strides is not None else None,
                         _tuple(padding, 3), ceil_mode, False, 'max',
                         **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout='NCW',
                 ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 1),
                         _tuple(strides, 1) if strides is not None else None,
                         _tuple(padding, 1), ceil_mode, False, 'avg',
                         **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout='NCHW', ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 2),
                         _tuple(strides, 2) if strides is not None else None,
                         _tuple(padding, 2), ceil_mode, False, 'avg',
                         **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout='NCDHW', ceil_mode=False, **kwargs):
        super().__init__(_tuple(pool_size, 3),
                         _tuple(strides, 3) if strides is not None else None,
                         _tuple(padding, 3), ceil_mode, False, 'avg',
                         **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout='NCW', **kwargs):
        super().__init__((1,), None, (0,), False, True, 'max', **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout='NCHW', **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, 'max', **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout='NCDHW', **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, 'max',
                         **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout='NCW', **kwargs):
        super().__init__((1,), None, (0,), False, True, 'avg', **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout='NCHW', **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, 'avg', **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout='NCDHW', **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, 'avg',
                         **kwargs)


class ReflectionPad2D(HybridBlock):
    """reference: conv_layers.py ReflectionPad2D (Pad op, reflect mode)."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._pad_width = padding

    def hybrid_forward(self, F, x):
        return F.Pad(x, mode='reflect', pad_width=self._pad_width)
