"""Gluon Parameter / ParameterDict.

TPU-native re-design of the reference's python/mxnet/gluon/parameter.py
(Parameter :43, ParameterDict :416).  The reference keeps one NDArray copy
per GPU context (`_init_impl` → `_data` list) and cross-reduces gradients
(`_reduce` :245); here a parameter owns ONE logical jax-backed NDArray —
multi-chip placement is a *sharding* of that array over the active mesh
(mxnet_tpu.parallel), not replication-by-copy, so `list_data` has a single
element and Trainer's gradient aggregation is a GSPMD psum.

Deferred initialization is kept: shape entries of 0 are unknown until the
first forward's input shapes arrive (parameter.py:585 _finish_deferred_init).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import numpy as np

from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray import NDArray
from ..ndarray.ndarray import zeros as nd_zeros, array as nd_array
from .. import initializer as init_mod
from .. import autograd


class DeferredInitializationError(MXNetError):
    """Parameter used before its shape is known (parameter.py:35)."""


class Parameter:
    """A weight/bias tensor with lazy shape + initializer.

    reference: gluon/parameter.py:43.
    """

    def __init__(self, name, grad_req='write', shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype='default', grad_stype='default'):
        self.name = name
        self._grad_req = grad_req if differentiable else 'null'
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._data: Optional[NDArray] = None
        self._grad: Optional[NDArray] = None
        self._deferred_init = None   # (init, ctx, default_init)
        self._trainer = None
        self._stype = stype
        self._grad_stype = grad_stype

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self.shape}, "
                f"dtype={np.dtype(self.dtype).name if self.dtype else None})")

    # -- grad_req -----------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ('write', 'add', 'null')
        if not self._differentiable:
            req = 'null'
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == 'null':
            self._grad = None
        elif self._data is not None:
            self._init_grad()

    # -- init ---------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """reference: parameter.py:303 initialize."""
        if default_init is None:
            default_init = init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self.shape is None or any(s == 0 for s in (self.shape or ())):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                f"Cannot initialize Parameter {self.name!r}: unknown shape "
                f"{self.shape} and allow_deferred_init=False")
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        data = nd_zeros(self.shape, dtype=self.dtype, ctx=ctx[0])
        explicit = init or self.init
        if isinstance(explicit, str):
            explicit = init_mod.create(explicit)
        if explicit is not None:
            # per-parameter init applies regardless of the name pattern
            # (reference: initializer.py __call__ '__init__' attr path)
            explicit._init_weight(init_mod.InitDesc(self.name), data)
        else:
            initializer = default_init
            if isinstance(initializer, str):
                initializer = init_mod.create(initializer)
            initializer(init_mod.InitDesc(self.name), data)
        self._data = data
        self._deferred_init = None
        if self._grad_req != 'null':
            self._init_grad()

    def _load_init(self, data):
        """Initialize directly from a checkpoint value (reference:
        parameter.py _load_init — load_params on a NEVER-initialized net,
        the model-zoo ``pretrained=True`` flow, takes shape AND value from
        the file)."""
        shape = tuple(data.shape)
        if self.shape is not None:
            if len(self.shape) != len(shape):
                raise MXNetError(
                    f"loading {self.name!r}: file rank {len(shape)} "
                    f"({shape}) != declared rank {len(self.shape)} "
                    f"({self.shape})")
            for s, t in zip(self.shape, shape):
                if s not in (0, t):
                    raise MXNetError(
                        f"loading {self.name!r}: file shape {shape} "
                        f"incompatible with declared {self.shape}")
        self.shape = shape
        arr = data if isinstance(data, NDArray) else nd_array(data)
        if self.dtype is not None and str(arr.dtype) != str(self.dtype):
            # match the declared dtype (reference _load_init casts): the
            # gradient _init_grad allocates uses self.dtype, and data/grad
            # dtypes must agree for mark_variables/optimizer updates
            arr = arr.astype(self.dtype)
        elif arr is data:
            arr = data.copy()
        self._data = arr
        self._deferred_init = None
        if self._grad_req != 'null':
            self._init_grad()

    def _finish_deferred_init(self, shape):
        """Complete deferred init once the input-driven shape is known
        (reference: parameter.py:585)."""
        if self._deferred_init is None:
            raise DeferredInitializationError(self.name)
        if self.shape is not None and len(self.shape) == len(shape):
            # merge known dims (0 = unknown)
            merged = tuple(s if s != 0 else t
                           for s, t in zip(self.shape, shape))
        else:
            merged = tuple(shape)
        if any(s == 0 for s in merged):
            raise MXNetError(f"deferred init of {self.name!r}: shape "
                             f"{merged} still has unknown dims")
        self.shape = merged
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    def _init_grad(self):
        if self._grad_stype == 'row_sparse':
            # sparse gradient: autograd fills values+indices for touched
            # rows only (reference: parameter.py grad_stype → sparse-grad
            # Embedding path)
            from ..ndarray.sparse import zeros as sp_zeros
            self._grad = sp_zeros('row_sparse', self.shape,
                                  dtype=self.dtype)
        else:
            self._grad = nd_zeros(self.shape, dtype=self.dtype)
        autograd.mark_variables([self._data], [self._grad],
                                [self._grad_req])

    # -- access -------------------------------------------------------------
    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init is not None:
            raise DeferredInitializationError(
                f"Parameter {self.name!r} has not been initialized yet "
                f"because initialization was deferred (unknown shape). "
                f"Run a forward pass first")
        raise MXNetError(
            f"Parameter {self.name!r} has not been initialized. "
            f"You should initialize parameters (e.g. net.initialize()) "
            f"before use")

    def data(self, ctx=None) -> NDArray:
        self._check_initialized()
        return self._data

    def list_data(self):
        self._check_initialized()
        return [self._data]

    def grad(self, ctx=None) -> NDArray:
        self._check_initialized()
        if self._grad is None:
            raise MXNetError(
                f"Cannot get gradient of Parameter {self.name!r}: "
                f"grad_req='null'")
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        self._check_initialized()
        return [self._data.context]

    def zero_grad(self):
        if self._grad is not None:
            self._grad[:] = 0

    def set_data(self, data):
        """reference: parameter.py set_data."""
        if self._data is None:
            if self._deferred_init is not None:
                self.shape = tuple(data.shape)
                init, ctx, default_init = self._deferred_init
                self._finish_init(init, ctx, default_init)
            else:
                self._check_initialized()
        if isinstance(data, NDArray):
            self._data._set_data(data._data)
        else:
            self._data._set_data(nd_array(data)._data)

    def reset_ctx(self, ctx):
        pass  # single logical array; placement = sharding

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data = self._data.astype(dtype)
            if self._grad is not None:
                self._grad = self._grad.astype(dtype)
                autograd.mark_variables([self._data], [self._grad],
                                        [self._grad_req])

    # -- symbol bridge ------------------------------------------------------
    def var(self):
        from .. import symbol as sym
        shape = self.shape
        if shape is not None and any(s == 0 for s in shape):
            shape = None   # unknown dims: let graph inference back-fill
        return sym.Variable(self.name, shape=shape, dtype=self.dtype)


class Constant(Parameter):
    """A non-differentiable parameter with a fixed value
    (reference: gluon/parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd_array(value)
        self.value = value

        class _CInit(init_mod.Initializer):
            def _init_weight(_self, _name, arr):
                arr[:] = value

        super().__init__(name, grad_req='null', shape=value.shape,
                         dtype=value.dtype, init=_CInit(),
                         differentiable=False)


class ParameterDict:
    """Prefix-scoped dict of Parameters (reference: parameter.py:416)."""

    def __init__(self, prefix='', shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __repr__(self):
        s = '\n'.join(f'  {v}' for v in self._params.values())
        return f"ParameterDict {self._prefix!r} (\n{s}\n)"

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Get or create a Parameter named prefix+name
        (reference: parameter.py:472)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == 'shape' and v is not None:
                        v = tuple(v)
                        if existing is not None and len(existing) == len(v):
                            merged = tuple(
                                a if a != 0 else b
                                for a, b in zip(existing, v))
                            param.shape = merged
                            continue
                    if v is not None and existing != v and k in (
                            'dtype',):
                        raise AssertionError(
                            f"Parameter {name!r} {k} mismatch: "
                            f"{existing} vs {v}")
                elif v is not None:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError(f"no constant {name!r} and no value given")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k!r}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        """reference: parameter.py:800."""
        if init is None:
            init = init_mod.Uniform()
        for v in self._params.values():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self._params.values():
            v.zero_grad()

    def place(self, mesh, rules=None):
        """Place every initialized parameter (and its grad buffer) on
        ``mesh`` — replicated by default, or per ``rules``
        (parallel.ShardingRules) for tensor-parallel layouts.

        This is the gluon entry to SPMD training: after
        ``net.collect_params().place(mesh)`` and dp-sharding the input
        batch, eager/hybridized compute runs as one GSPMD program — the
        mesh analog of the reference's one-copy-per-GPU ``reset_ctx``
        (parameter.py reset_ctx; here placement is a sharding, not a
        copy).  Combine with ``Trainer(..., mesh=mesh, zero_stage=1)``
        for dp-sharded optimizer state."""
        import jax
        from jax.sharding import NamedSharding
        from .. import parallel as _par
        for p in self._params.values():
            if p._data is None:
                raise MXNetError(
                    f"place(): parameter {p.name!r} is not initialized "
                    "(deferred shapes resolve at the first forward — run "
                    "one forward, then place)")
            spec = _par.infer_pspec(p.name, p._data.shape, mesh, rules)
            sh = NamedSharding(mesh, spec)
            p._data._set_data(jax.device_put(p._data._data, sh))
            if p._grad is not None:
                p._grad._set_data(jax.device_put(p._grad._data, sh))

    def setattr(self, name, value):
        for v in self._params.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=''):
        """reference: parameter.py save → NDArray map file
        (serialization.py format)."""
        from .. import serialization
        arg = {}
        for p in self._params.values():
            nm = p.name
            if strip_prefix and nm.startswith(strip_prefix):
                nm = nm[len(strip_prefix):]
            arg[nm] = p._data if p._data is not None else None
            if arg[nm] is None:
                raise MXNetError(f"cannot save uninitialized param {p.name!r}")
        serialization.save_ndarrays(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=''):
        """reference: parameter.py load."""
        from .. import serialization
        loaded = serialization.load_ndarrays(filename)
        loaded = {restore_prefix + k.split(':', 1)[-1]: v
                  for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in loaded:
                    raise MXNetError(f"param {name!r} missing in {filename}")
        for name, v in loaded.items():
            if name not in self._params:
                if ignore_extra:
                    continue
                raise MXNetError(
                    f"param {name!r} in file not in ParameterDict; "
                    f"set ignore_extra=True to skip")
            p = self._params[name]
            if p._data is None and p._deferred_init is None:
                # never-initialized net (model-zoo pretrained flow):
                # shape and value both come from the file
                p._load_init(v)
            else:
                p.set_data(v)
