"""Gluon Block / HybridBlock / SymbolBlock.

TPU-native re-design of python/mxnet/gluon/block.py (Block :120,
HybridBlock :305, SymbolBlock :497).  The reference's hybridization caches
an nnvm graph in a CachedOp (`_build_cache` block.py:364 →
imperative::CachedOp, src/imperative/cached_op.cc); here hybridization
traces ``hybrid_forward(F=symbol, ...)`` into a Symbol once per input
signature and jit-compiles its interpreter — the CachedOp *is* the XLA
compilation cache.  Under ``autograd.record`` the whole cached program is
recorded as ONE tape entry, exactly as the reference records the CachedOp
as a single node (TIsLayerOpBackward, cached_op.cc:475).
"""
from __future__ import annotations

import copy
import re
from typing import Dict, List, Optional

import numpy as np
import jax

from ..base import MXNetError
from .. import name as _name_mod
from ..ndarray import NDArray
from .. import autograd
from .. import random as _rnd
from .parameter import Parameter, ParameterDict, DeferredInitializationError


class _BlockScope:
    """Name scoping for Blocks (reference: block.py:33 _BlockScope)."""
    _current = None

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = _BlockScope._current
        if current is None:
            if prefix is None:
                mgr = getattr(_name_mod.NameManager._current, 'value', None)
                if mgr is None:
                    mgr = _name_mod.NameManager()
                    _name_mod.NameManager._current.value = mgr
                prefix = mgr.get(None, hint) + '_'
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f'{hint}{count}_'
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        self._old_scope = _BlockScope._current
        _BlockScope._current = self
        return self

    def __exit__(self, *a):
        _BlockScope._current = self._old_scope


class Block:
    """Base class of all neural-net layers/models (reference: block.py:120)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ''
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith('_') \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children: List[Block] = []
        self._reg_params: Dict[str, Parameter] = {}

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = '{name}(\n{modstr}\n)'
        modstr = '\n'.join(
            f'  ({i}): {_indent(repr(b), 2)}'
            for i, b in enumerate(self._children))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        """Registers children/params automatically (block.py:180)."""
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)):
                raise TypeError(
                    f"Changing attribute type for {name!r} from "
                    f"{type(existing)} to {type(value)} is not allowed.")
            if isinstance(existing, Block) and isinstance(value, Block):
                # reassignment replaces the old child in place
                self._children[self._children.index(existing)] = value
                super().__setattr__(name, value)
                return
        if isinstance(value, Block):
            self.register_child(value)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed." % name
            self._reg_params[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        """All Parameters of this block and children
        (reference: block.py:228; `select` regex added in 1.x kept for
        API parity)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pat = re.compile(select)
            ret.update({k: v for k, v in self.params.items()
                        if pat.match(k)})
        for child in self._children:
            ret.update(child.collect_params(select))
        return ret

    def register_child(self, block):
        self._children.append(block)

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer as init_mod
        self.collect_params().initialize(
            init or init_mod.Uniform(), ctx, verbose,
            force_reinit=force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children:
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children:
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def save_params(self, filename):
        """reference: block.py save_params."""
        self.collect_params().save(filename, strip_prefix=self.prefix)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        """reference: block.py load_params."""
        self.collect_params().load(filename, ctx, allow_missing,
                                   ignore_extra, self.prefix)

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        """Per-layer output-shape summary (print_summary analog)."""
        lines = [f"{'Layer':<40}{'Output':<24}"]

        def walk(b, x):
            y = b(x)
            lines.append(f"{b.name:<40}{str(getattr(y, 'shape', '?')):<24}")
            return y
        x = inputs[0]
        for c in self._children:
            x = walk(c, x)
        return '\n'.join(lines)


def _indent(s, n):
    pad = ' ' * n
    return ('\n' + pad).join(s.split('\n'))


class _CachedGraph:
    """The CachedOp equivalent: Symbol traced from hybrid_forward +
    jit-compiled interpreter, keyed by input signature
    (reference: cached_op.cc GetForwardGraph :175 per-config caching)."""

    def __init__(self, sym, data_names, param_names, compute_dtype=None):
        from ..executor import build_interpreter
        self.sym = sym
        run, arg_names, aux_names = build_interpreter(
            sym, compute_dtype=compute_dtype)
        self.run = run
        self.arg_names = arg_names
        self.aux_names = aux_names
        self.data_names = data_names
        self.param_names = param_names
        self._jit = jax.jit(
            lambda args, aux, key, t: run(args, aux, key, t),
            static_argnums=(3,))

    def __call__(self, data_vals, param_map, aux_map, is_train):
        by_name = dict(zip(self.data_names, data_vals))
        by_name.update(param_map)
        args = tuple(by_name[n] for n in self.arg_names)
        aux = tuple(aux_map[n] for n in self.aux_names)
        key = _rnd.key_for(self.run)

        is_train = bool(is_train)
        if autograd.is_recording():
            # record the WHOLE cached program as one tape entry; train mode
            # follows autograd.is_training() (record(train_mode=False) must
            # keep Dropout/BN in inference mode, autograd.py:34-100)
            run = self.run
            n_args = len(args)

            def fn(key, *vals, **_):
                a, x = vals[:n_args], vals[n_args:]
                outs, new_aux = run(a, x, key, is_train)
                return tuple(outs) + tuple(new_aux)
            vals = args + aux
            outs, new_aux = self._jit(args, aux, key, is_train)
            return outs, new_aux, (fn, key, vals)
        outs, new_aux = self._jit(args, aux, key, is_train)
        return outs, new_aux, None


class HybridBlock(Block):
    """reference: block.py:305 HybridBlock."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_graphs: Dict[tuple, _CachedGraph] = {}
        self._flags = {}

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = kwargs
        self._cached_graphs = {}
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._cached_graphs = {}
        super().cast(dtype)

    def infer_shape(self, *args):
        """Deferred-shape inference: trace symbolically with the input
        shapes and finish deferred param init
        (reference: block.py _infer_attrs/infer_shape)."""
        self._deferred_infer(args)

    def _deferred_infer(self, args):
        from .. import symbol as sym_mod
        params = {**{n: p for n, p in self._reg_params.items()}}
        with autograd.pause():
            inputs = [sym_mod.Variable(f'data{i}')
                      for i in range(len(args))]
            try:
                out = self.hybrid_forward(
                    sym_mod, *inputs,
                    **{n: p.var() for n, p in params.items()})
            except DeferredInitializationError:
                raise MXNetError(
                    f"{self.name}: cannot infer shapes symbolically")
            out = _flatten_output(out)
            shapes = {f'data{i}': tuple(a.shape)
                      for i, a in enumerate(args)}
            grouped = sym_mod.Group(out) if len(out) > 1 else out[0]
            arg_shapes, _, aux_shapes = grouped.infer_shape_partial(**shapes)
            names = grouped.list_arguments()
            aux_names = grouped.list_auxiliary_states()
            shape_of = dict(zip(names, arg_shapes))
            shape_of.update(dict(zip(aux_names, aux_shapes)))
            for p in self.collect_params().values():
                if p._deferred_init is not None and p.name in shape_of:
                    p._finish_deferred_init(shape_of[p.name])

    def forward(self, x, *args):
        if isinstance(x, NDArray):
            from .. import ndarray as nd_mod
            params_uninit = [p for p in self._reg_params.values()
                             if p._deferred_init is not None]
            if params_uninit:
                self._deferred_infer((x,) + args)
            if self._active:
                return self._call_cached(x, *args)
            try:
                pdata = {n: p.data() for n, p in self._reg_params.items()}
            except DeferredInitializationError:
                self._deferred_infer((x,) + args)
                pdata = {n: p.data() for n, p in self._reg_params.items()}
            return self.hybrid_forward(nd_mod, x, *args, **pdata)
        from .. import symbol as sym_mod
        pvars = {n: p.var() for n, p in self._reg_params.items()}
        return self.hybrid_forward(sym_mod, x, *args, **pvars)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- cached (hybridized) path ------------------------------------------
    def _trace_symbol(self, n_inputs):
        from .. import symbol as sym_mod
        inputs = [sym_mod.Variable(f'data{i}') for i in range(n_inputs)]
        out = self(*inputs)
        out = _flatten_output(out)
        sym = sym_mod.Group(out) if len(out) > 1 else out[0]
        return sym, [f'data{i}' for i in range(n_inputs)]

    def _call_cached(self, *args):
        params = self.collect_params()
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in args)
        cg = self._cached_graphs.get(sig)
        if cg is None:
            sym, data_names = self._trace_symbol(len(args))
            # hybridize(compute_dtype=jnp.bfloat16) → mixed-precision
            # cached program (executor.AMP_FP32_OPS policy), the gluon
            # analog of Module(compute_dtype=...)
            cg = _CachedGraph(sym, data_names,
                              [p.name for p in params.values()],
                              compute_dtype=self._flags.get(
                                  'compute_dtype'))
            self._cached_graphs[sig] = cg
        # finish deferred param init from the traced graph's shapes
        # (reference: _build_cache → infer_shape → _finish_deferred_init)
        deferred = [p for p in params.values()
                    if p._deferred_init is not None]
        if deferred:
            shapes = {dn: tuple(a.shape)
                      for dn, a in zip(cg.data_names, args)}
            arg_shapes, _, aux_shapes = cg.sym.infer_shape_partial(**shapes)
            shape_of = dict(zip(cg.sym.list_arguments(), arg_shapes or []))
            shape_of.update(zip(cg.sym.list_auxiliary_states(),
                                aux_shapes or []))
            for p in deferred:
                if shape_of.get(p.name):
                    p._finish_deferred_init(shape_of[p.name])
        param_map = {}
        aux_map = {}
        for n in cg.arg_names:
            if n in cg.data_names:
                continue
            param_map[n] = params[n].data()._data
        for n in cg.aux_names:
            aux_map[n] = params[n].data()._data
        data_vals = tuple(a._data for a in args)
        is_train = autograd.is_training()
        outs, new_aux, rec = cg(data_vals, param_map, aux_map, is_train)

        out_arrays = [NDArray(o) for o in outs]
        aux_arrays = []
        if is_train:
            for n, v in zip(cg.aux_names, new_aux):
                params[n].data()._set_data(v)
                aux_arrays.append(params[n].data())
        if rec is not None:
            fn, key, vals = rec
            name_to_arr = dict(zip(cg.data_names, args))
            # in_arrays aligned 1:1 with vals = args(arg_names order) + aux
            in_arrays = [name_to_arr[n] if n in name_to_arr
                         else params[n].data() for n in cg.arg_names] + \
                        [params[n].data() for n in cg.aux_names]
            autograd._record(fn, {}, in_arrays, list(vals),
                             out_arrays + aux_arrays, rng_key=key,
                             n_keep=len(out_arrays) + len(aux_arrays))
        if len(out_arrays) == 1:
            return out_arrays[0]
        return out_arrays

    def export(self, path, epoch=0):
        """Save symbol + params like the reference's HybridBlock.export."""
        if not self._cached_graphs:
            raise MXNetError("run forward at least once before export()")
        cg = next(iter(self._cached_graphs.values()))
        cg.sym.save(f'{path}-symbol.json')
        from .. import serialization
        params = self.collect_params()
        arg = {}
        for n in cg.arg_names:
            if n in cg.data_names:
                continue
            arg['arg:' + n] = params[n].data()
        for n in cg.aux_names:
            arg['aux:' + n] = params[n].data()
        serialization.save_ndarrays('%s-%04d.params' % (path, epoch), arg)


def _flatten_output(out):
    if isinstance(out, (list, tuple)):
        res = []
        for o in out:
            res.extend(_flatten_output(o))
        return res
    return [out]


class SymbolBlock(HybridBlock):
    """Wrap an existing Symbol as a callable block
    (reference: block.py:497)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        # raw symbol names ARE the param names (reference: block.py:497
        # SymbolBlock builds its dict with an empty prefix)
        self._params = ParameterDict('', params)
        from .. import symbol as sym_mod
        if isinstance(inputs, sym_mod.Symbol):
            inputs = [inputs]
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(list(outputs))
        self._output_sym = outputs
        self._input_names = [i.name for i in inputs]
        arg_names = outputs.list_arguments()
        aux_names = outputs.list_auxiliary_states()
        for n in arg_names:
            if n not in self._input_names:
                self.params.get(n, allow_deferred_init=True,
                                grad_req='null')
        for n in aux_names:
            self.params.get(n, allow_deferred_init=True, grad_req='null')
        self._cached = None

    def forward(self, *args):
        from ..executor import build_interpreter
        params = self.collect_params()
        if self._cached is None:
            run, arg_names, aux_names = build_interpreter(self._output_sym)
            self._cached = (jax.jit(
                lambda a, x, k: run(a, x, k, False)), arg_names, aux_names,
                run)
        jfn, arg_names, aux_names, run = self._cached
        by_name = dict(zip(self._input_names, (a._data for a in args)))
        for n in arg_names:
            if n not in by_name:
                by_name[n] = params[n].data()._data
        aux = tuple(params[n].data()._data for n in aux_names)
        outs, _ = jfn(tuple(by_name[n] for n in arg_names), aux,
                      _rnd.key_for(run))
        outs = [NDArray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs
