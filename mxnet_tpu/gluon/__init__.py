"""Gluon: the imperative high-level API (reference: python/mxnet/gluon/).

TPU-native design notes: Parameters are single logical (possibly
mesh-sharded) jax arrays, hybridization compiles to jit-cached XLA
programs (block.py), and Trainer's gradient allreduce is fused into
backward by GSPMD (trainer.py).
"""
from .parameter import (Parameter, Constant, ParameterDict,
                        DeferredInitializationError)
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import rnn
from . import loss
from . import data
from . import model_zoo
from . import contrib
from . import utils
from .utils import split_and_load
