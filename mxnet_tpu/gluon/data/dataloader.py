"""DataLoader (reference: python/mxnet/gluon/data/dataloader.py).

The reference forks worker processes that serialize NDArrays over pipes;
here workers are a thread pool (the heavy lifting — decode/augment — is
numpy/PIL releasing the GIL, and device transfer happens once per batch on
the main thread, overlapped with compute by XLA's async dispatch).
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...ndarray import NDArray
from ...ndarray.ndarray import array as nd_array
from .sampler import SequentialSampler, RandomSampler, BatchSampler


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py:36)."""
    if isinstance(data[0], NDArray):
        return nd_array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return nd_array(data)


class DataLoader:
    """reference: dataloader.py:66."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or 'keep')
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers

    def __iter__(self):
        if self._num_workers > 0:
            from collections import deque

            def fetch(batch):
                return self._batchify_fn([self._dataset[i] for i in batch])

            with ThreadPoolExecutor(self._num_workers) as pool:
                # bounded prefetch window (~2 batches per worker): keeps the
                # pool busy without materializing the whole epoch in memory
                pending = deque()
                it = iter(self._batch_sampler)
                for batch in it:
                    pending.append(pool.submit(fetch, batch))
                    if len(pending) >= 2 * self._num_workers:
                        yield pending.popleft().result()
                while pending:
                    yield pending.popleft().result()
        else:
            for batch in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch])

    def __len__(self):
        return len(self._batch_sampler)
