"""Vision datasets + transforms (reference: python/mxnet/gluon/data/vision.py).

MNIST/CIFAR parse the same on-disk formats as the reference (idx-ubyte,
CIFAR binary).  No network egress in this build: files must exist locally
(utils.download raises otherwise).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...base import MXNetError
from ...ndarray.ndarray import array as nd_array
from .dataset import Dataset, RecordFileDataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        if not os.path.isdir(self._root):
            os.makedirs(self._root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from local idx-ubyte(.gz) files (reference: vision.py:36)."""

    def __init__(self, root='~/.mxnet/datasets/mnist', train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        if self._train:
            data_file = 'train-images-idx3-ubyte'
            label_file = 'train-labels-idx1-ubyte'
        else:
            data_file = 't10k-images-idx3-ubyte'
            label_file = 't10k-labels-idx1-ubyte'

        def _open(base):
            for cand, op in ((base, open), (base + '.gz', gzip.open)):
                p = os.path.join(self._root, cand)
                if os.path.exists(p):
                    return op(p, 'rb')
            raise MXNetError(
                f"MNIST file {base}(.gz) not found under {self._root} "
                f"(no network egress; place it there manually)")

        with _open(label_file) as fin:
            struct.unpack(">II", fin.read(8))
            label = np.frombuffer(fin.read(), dtype=np.uint8) \
                .astype(np.int32)
        with _open(data_file) as fin:
            _, num, rows, cols = struct.unpack(">IIII", fin.read(16))
            data = np.frombuffer(fin.read(), dtype=np.uint8)
            data = data.reshape(num, rows, cols, 1)
        self._data = [nd_array(x, dtype=np.uint8) for x in data]
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root='~/.mxnet/datasets/fashion-mnist', train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from local binary batches (reference: vision.py:86)."""

    def __init__(self, root='~/.mxnet/datasets/cifar10', train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _read_batch(self, filename):
        if not os.path.exists(filename):
            raise MXNetError(
                f"CIFAR file {filename} not found (no network egress; "
                f"place it there manually)")
        with open(filename, 'rb') as fin:
            data = np.frombuffer(fin.read(), dtype=np.uint8) \
                .reshape(-1, 3072 + 1)
        return data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0].astype(np.int32)

    def _get_data(self):
        if self._train:
            files = [os.path.join(self._root, f'data_batch_{i}.bin')
                     for i in range(1, 6)]
        else:
            files = [os.path.join(self._root, 'test_batch.bin')]
        data, label = zip(*(self._read_batch(f) for f in files))
        data = np.concatenate(data)
        label = np.concatenate(label)
        self._data = [nd_array(x, dtype=np.uint8) for x in data]
        self._label = label


class ImageRecordDataset(RecordFileDataset):
    """Images packed in a RecordIO file (reference: vision.py:130)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ... import recordio, image
        record = super().__getitem__(idx)
        header, img = recordio.unpack(record)
        img = image.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
