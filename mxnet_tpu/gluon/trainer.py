"""Gluon Trainer (reference: python/mxnet/gluon/trainer.py:27).

The reference's step() pushes gradients to a kvstore (allreduce across
devices) and pulls updated weights (trainer.py:148).  Here a parameter is
ONE logical array (possibly mesh-sharded), so `step` = run the optimizer
update on each param's gradient; cross-chip gradient reduction already
happened inside the backward program (GSPMD psum).  The kvstore argument is
accepted for API parity and drives update_on_kvstore semantics.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt_mod
from .. import kvstore as kvs_mod
from .parameter import ParameterDict, Parameter


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore='device', compression_params=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}")
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(f"not a Parameter: {param!r}")
            param._trainer = self
            self._params.append(param)
        self._scale = 1.0
        optimizer_params = dict(optimizer_params or {})
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_type = kvstore
        self._kvstore = None
        self._kv_initialized = False

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
        else:
            self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer.param_dict = param_dict
        self._updaters = [opt_mod.get_updater(self._optimizer)]

    def _init_kvstore(self):
        """reference: trainer.py:102 — create the store lazily at first
        step; on TPU it is a facade over in-program collectives."""
        if self._kv_type:
            self._kvstore = kvs_mod.create(self._kv_type) \
                if isinstance(self._kv_type, str) else self._kv_type
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """reference: trainer.py:148."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        updater = self._updaters[0]
        for i, param in enumerate(self._params):
            if param.grad_req == 'null':
                continue
            if param._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        f"Parameter {param.name!r} was not initialized")
                continue
            updater(i, param.grad(), param.data())

    def allreduce_grads(self):
        """No-op on TPU: gradient reduction is fused into backward
        (GSPMD psum) — kept for API parity (reference: trainer.py
        allreduce_grads)."""

    def update(self, batch_size, ignore_stale_grad=False):
        self.step(batch_size, ignore_stale_grad)

    def save_states(self, fname):
        """reference: trainer.py save_states."""
        with open(fname, 'wb') as fout:
            fout.write(self._updaters[0].get_states())

    def load_states(self, fname):
        with open(fname, 'rb') as fin:
            self._updaters[0].set_states(fin.read())
