"""Gluon Trainer (reference: python/mxnet/gluon/trainer.py:27).

The reference's step() pushes gradients to a kvstore (allreduce across
devices) and pulls updated weights (trainer.py:148).  Here a parameter is
ONE logical array (possibly mesh-sharded), so `step` = run the optimizer
update on each param's gradient; cross-chip gradient reduction already
happened inside the backward program (GSPMD psum).  The kvstore argument is
accepted for API parity and drives update_on_kvstore semantics.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, env
from .. import optimizer as opt_mod
from .. import kvstore as kvs_mod
from .parameter import ParameterDict, Parameter


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore='device', compression_params=None,
                 mesh=None, zero_stage=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}")
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(f"not a Parameter: {param!r}")
            param._trainer = self
            self._params.append(param)
        self._scale = 1.0
        # ZeRO-1 over the dp mesh axis — same contract as
        # Module(zero_stage=1) (docs/design/kvstore.md): optimizer states
        # (+ fp32 masters) live dp-sharded; GSPMD schedules the
        # reduce-scatter/all-gather inside the fused update.
        from .. import parallel as _par
        if mesh is None:
            mesh = _par.current_mesh()
        self._mesh = mesh
        explicit_zero = zero_stage is not None
        if zero_stage is None:
            zero_stage = env("MXNET_ZERO_STAGE", 0)
        if zero_stage not in (0, 1):
            raise ValueError("zero_stage must be 0 or 1")
        if explicit_zero and zero_stage >= 1 and mesh is None:
            raise MXNetError(
                "zero_stage=1 needs a device mesh with dp>1 — pass "
                "mesh= (parallel.make_mesh) or enter a use_mesh scope")
        self._zero_stage = int(zero_stage)
        self._zero_dp = (_par.mesh_shape(mesh).get("dp", 1)
                         if mesh is not None else 1)
        if not explicit_zero and zero_stage >= 1 and self._zero_dp <= 1:
            # mirror Module's warning: env-enabled ZeRO without a dp>1
            # mesh silently leaves optimizer states replicated
            logging.warning(
                "MXNET_ZERO_STAGE=1 ignored: no device mesh with dp>1 "
                "on this Trainer — optimizer states will be fully "
                "replicated")
        optimizer_params = dict(optimizer_params or {})
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        # on-the-wire gradient compression (reference: Trainer
        # compression_params -> kvstore.set_gradient_compression).
        # Validated eagerly so a typo'd config fails at construction,
        # then shipped to the store at the lazy kvstore init.
        self._compression_params = None
        if compression_params:
            from ..compression import GradientCompression
            GradientCompression(compression_params)   # validate now
            self._compression_params = dict(compression_params)

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
        else:
            self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer.param_dict = param_dict
        self._updaters = [opt_mod.get_updater(self._optimizer)]

    def _init_kvstore(self):
        """reference: trainer.py:102 — create the store lazily at first
        step; on TPU it is a facade over in-program collectives, EXCEPT
        dist_async where the kvstore path IS the mechanism: the optimizer
        runs server-side and step() becomes push-grad/pull-weight
        (reference trainer.py:148 update-on-kvstore)."""
        if self._kv_type:
            self._kvstore = kvs_mod.create(self._kv_type) \
                if isinstance(self._kv_type, str) else self._kv_type
        if self._kvstore is not None and self._compression_params:
            # before any push: the first gradient must already ride the
            # compressed wire (dist_async; a no-wire store records the
            # setting and compresses nothing)
            self._kvstore.set_gradient_compression(
                self._compression_params)
        self._update_on_kvstore = (
            self._kvstore is not None
            and getattr(self._kvstore, "type", "") == "dist_async")
        if self._update_on_kvstore:
            # the optimizer is NOT shipped here: the server applies
            # updates with the optimizer AS PICKLED, so sending it from a
            # pre-first-step path (save_states/load_states resume flow)
            # would freeze the DEFAULT rescale_grad=1.0 into the servers
            # and every update would land ~batch_size× too large.
            # _ensure_kv_optimizer ships it from the first step(), after
            # rescale_grad is set (ADVICE r5: trainer.py resume path).
            self._kv_opt_sent = False
            self._kv_deferred_states = None
            self._kv_replay_states = None
            self._kv_param_inited = set()
            # ALL materialized params — including frozen (grad_req
            # 'null') ones — sync to the server-authoritative value, so
            # every worker trains against the same frozen weights
            # (reference: _initialize_kvstore registers every param)
            inited = [p for p in self._params if p._data is not None]
            for param in inited:
                self._kvstore.init(param.name, param.data())
                self._kv_param_inited.add(param.name)
            # pull the AUTHORITATIVE weights back: the server kept the
            # first-arriving worker's init, and every worker must start
            # from that same point (reference: model.py:96
            # _initialize_kvstore pulls after init)
            if inited:
                self._kvstore.pull([p.name for p in inited],
                                   out=[p.data() for p in inited])
        self._kv_initialized = True

    def _ensure_kv_optimizer(self):
        """Ship the optimizer to the dist_async servers once, from the
        first step() — AFTER rescale_grad is set — then replay any
        buffered load_states blob.  A pre-first-step save_states/
        load_states no longer bakes rescale_grad=1.0 into the servers'
        pickle-time snapshot."""
        if self._kv_opt_sent:
            return
        self._kvstore.set_optimizer(self._optimizer)
        self._kv_opt_snapshot = (self._optimizer.lr,
                                 self._optimizer.rescale_grad)
        self._kv_opt_sent = True
        # replay loaded states AFTER the ship: set_optimizer replaced the
        # server-side updater, which discarded any states a pre-first-
        # step load_states applied — without the replay, a resume
        # against live servers silently restarts the optimizer fresh
        blob = self._kv_deferred_states or self._kv_replay_states
        self._kv_deferred_states = self._kv_replay_states = None
        if blob is not None:
            self._kvstore.load_optimizer_states_blob(blob)

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """reference: trainer.py:148.

        Dense-gradient params with a pure-jax optimizer go through ONE
        jitted update over all of them (the gluon analog of Module's fused
        step — N per-param eager dispatches per step would each be a
        device round-trip on a remote-attached chip).  Sparse-gradient
        params and non-pure optimizers keep the per-param eager path.
        """
        # rescale BEFORE the lazy kvstore init: dist_async pickles the
        # optimizer to the servers at init and applies THERE
        self._optimizer.rescale_grad = self._scale / batch_size
        if not self._kv_initialized:
            self._init_kvstore()
        if getattr(self, "_update_on_kvstore", False):
            self._ensure_kv_optimizer()
            return self._step_on_kvstore(ignore_stale_grad)
        updater = self._updaters[0]
        from ..ndarray.sparse import RowSparseNDArray
        fuse = (env("MXNET_EXEC_BULK_EXEC_TRAIN", True)
                and getattr(self._optimizer, "pure_update", False))
        fused_batch = []
        for i, param in enumerate(self._params):
            if param.grad_req == 'null':
                continue
            if param._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        f"Parameter {param.name!r} was not initialized")
                continue
            grad = param.grad()
            if fuse and not isinstance(grad, RowSparseNDArray):
                fused_batch.append((i, param, grad))
            else:
                if self._zero_stage >= 1 and self._zero_dp > 1 \
                        and not getattr(self, "_zero_eager_warned", False):
                    self._zero_eager_warned = True
                    import warnings
                    warnings.warn(
                        "zero_stage=1 requested but parameter "
                        f"{param.name!r} updates on the eager path "
                        "(sparse grad, non-pure optimizer, or bulk-exec "
                        "disabled) — its optimizer state is NOT sharded",
                        stacklevel=2)
                updater(i, grad, param.data())
        if fused_batch:
            self._fused_update(fused_batch, updater)

    def _step_on_kvstore(self, ignore_stale_grad):
        """Async-PS step: ONE list-form push of every grad (small
        same-server keys coalesce into a single ``push_multi`` envelope
        under ``MXNET_KVSTORE_COALESCE_BYTES`` — per-param pushes used
        to bypass the coalescing path entirely and pay a frame+ack per
        tiny tensor), then ONE batched pull of the server's current
        weights back (reference: trainer.py:148 _update
        update-on-kvstore branch; pipelined pull = ~max-RTT, not N
        round trips).  Per-server FIFO guarantees each pull observes
        this worker's own pushes."""
        snap = (self._optimizer.lr, self._optimizer.rescale_grad)
        if snap != self._kv_opt_snapshot \
                and not getattr(self, "_kv_opt_drift_warned", False):
            self._kv_opt_drift_warned = True
            import warnings
            warnings.warn(
                "optimizer hyperparameters changed after the first "
                "dist_async step (lr/rescale_grad %s -> %s) — the SERVER "
                "keeps applying its pickle-time snapshot (re-sending the "
                "optimizer would reset server-side momentum state); "
                "restart training to change hyperparameters, as with the "
                "reference's server-side optimizer" %
                (self._kv_opt_snapshot, snap), stacklevel=3)
        live = []
        for param in self._params:
            if param.grad_req == 'null':
                continue
            if param._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        f"Parameter {param.name!r} was not initialized")
                continue
            if param.name not in self._kv_param_inited:
                # deferred-init param materialized after the first step:
                # register it before its first push (first-init-wins
                # makes a late init safe under concurrent workers)
                self._kvstore.init(param.name, param.data())
                self._kv_param_inited.add(param.name)
            live.append(param)
        if live:
            self._kvstore.push([p.name for p in live],
                               [p.grad() for p in live])
            self._kvstore.pull([p.name for p in live],
                               out=[p.data() for p in live])

    def _zero_pspec(self, arr):
        """Delegates to the shared rule in parallel.sharding (one source
        of truth with Module)."""
        from .. import parallel as _par
        return _par.zero_pspec(arr, self._zero_dp)

    def _zero_shard_state(self, state):
        import jax
        from jax.sharding import NamedSharding
        for s in self._optimizer._state_tuple(state):
            if s is None:
                continue
            s._set_data(jax.device_put(
                s._data, NamedSharding(self._mesh, self._zero_pspec(s))))

    def _zero_check_placed(self, batch, ws):
        """ZeRO-1 shards states onto the mesh, so the params must already
        live there (net.collect_params().place(mesh)) — otherwise jit
        fails with an opaque 'incompatible devices' error; fail clearly
        instead."""
        for (_i, p, _g), w in zip(batch, ws):
            if getattr(getattr(w, "sharding", None), "mesh", None) \
                    != self._mesh:
                raise MXNetError(
                    f"zero_stage=1: parameter {p.name!r} is not placed "
                    "on the trainer's mesh — call "
                    "net.collect_params().place(mesh) (and dp-shard the "
                    "input batch) before training")

    def _fused_update(self, batch, updater):
        """Apply the optimizer to every (dense) param in ONE jit call
        (the per-param dispatch lives in Optimizer.apply_fused, shared
        with Module's fused step).

        Shares per-param state with the eager Updater (same dict), so
        save_states/load_states and mixing eager/sparse updates stay
        coherent.  The jit cache is a dict keyed by (param set, mp
        layout, optimizer hyperparameter signature): changing e.g.
        momentum or rescale_grad mid-run retraces, and alternating keys
        (a smaller final batch) each compile once.
        """
        opt = self._optimizer
        zero1 = self._zero_stage >= 1 and self._zero_dp > 1
        for i, param, _g in batch:
            if i not in updater.states:
                updater.states[i] = \
                    opt.create_state_multi_precision(i, param.data())
                updater.states_synced[i] = True
                if zero1:
                    self._zero_shard_state(updater.states[i])
            opt._update_count(i)
        needs_t = getattr(opt, "needs_t", False)
        states = [opt._state_tuple(updater.states[i]) for i, _p, _g in batch]
        use_mp = tuple(opt.mp_states_active(p.data(), st)
                       for (_i, p, _g), st in zip(batch, states))
        ws = tuple(p._data._data for _i, p, _g in batch)
        gs = tuple(g._data for _i, _p, g in batch)
        sts = tuple(tuple(s._data for s in st) for st in states)
        if zero1:
            self._zero_check_placed(batch, ws)
            # params keep their CURRENT sharding (captured from the live
            # arrays — gluon has no rules engine; replicated unless the
            # user sharded them), states stay dp-sharded.  The specs join
            # the cache key so a placement change retraces the constraint.
            from jax.sharding import PartitionSpec as _P
            param_specs = tuple(
                getattr(w.sharding, "spec", _P()) for w in ws)
        else:
            param_specs = None
        key = (tuple(i for i, _p, _g in batch), use_mp, needs_t,
               opt.hyperparam_signature(), zero1, param_specs)
        cache = getattr(self, "_fused_cache", None)
        if cache is None:
            cache = self._fused_cache = {}
        fn = cache.get(key)
        if fn is None:
            def fused(ws, gs, sts, lrs, wds, ts):
                new_ws, new_sts = opt.apply_fused(
                    ws, gs, sts, lrs, wds, use_mp,
                    ts=ts if needs_t else None)
                if zero1:
                    from jax.sharding import NamedSharding
                    from .. import parallel as _par
                    mesh = self._mesh
                    new_ws = tuple(
                        jax.lax.with_sharding_constraint(
                            w, NamedSharding(mesh, ps))
                        for w, ps in zip(new_ws, param_specs))
                    new_sts = _par.constrain_zero_states(
                        new_sts, mesh, self._zero_dp)
                return new_ws, new_sts

            fn = cache[key] = jax.jit(fused)
        # cache lr/wd device scalars while unchanged (per-step host→device
        # scalar transfers would reintroduce the round-trips this path
        # removes — same discipline as Module._lrwd_cache)
        lrs = tuple(np.float32(opt._get_lr(i)) for i, _p, _g in batch)
        wds = tuple(np.float32(opt._get_wd(i)) for i, _p, _g in batch)
        lw_cache = getattr(self, "_lrwd_cache", None)
        if lw_cache is not None and lw_cache[0] == (lrs, wds):
            lrs, wds = lw_cache[1]
        else:
            key_ = (lrs, wds)
            lrs = tuple(jnp.asarray(v) for v in lrs)
            wds = tuple(jnp.asarray(v) for v in wds)
            self._lrwd_cache = (key_, (lrs, wds))
        if needs_t:
            # per-param bias-correction counts (a frozen/unfrozen param's
            # count differs — matching the eager path exactly)
            ts = tuple(jnp.asarray(opt._index_update_count[i], jnp.int32)
                       for i, _p, _g in batch)
        else:
            ts = getattr(self, "_t_zeros", None)
            if ts is None or len(ts) != len(batch):
                ts = self._t_zeros = tuple(
                    jnp.asarray(0, jnp.int32) for _ in batch)
        new_ws, new_sts = fn(ws, gs, sts, lrs, wds, ts)
        for (_i, p, _g), w, st_old, st_new in zip(batch, new_ws, states,
                                                  new_sts):
            p._data._set_data(w)
            for s, v in zip(st_old, st_new):
                s._set_data(v)

    def step_k(self, loss_fn, data, label=None, k=None, batch_size=None,
               eval_metric=None):
        """Run K training steps (forward + backward + update) as ONE
        scanned XLA program — the gluon analog of ``Module.run_steps``,
        built on the same ``executor.build_multi_step`` driver: a single
        host dispatch launches all K steps, amortizing the per-dispatch
        host cost to 1/K per step.

        ``loss_fn(data, label) -> loss NDArray`` is the user's forward
        (net + loss); it is traced ONCE into the scan body, with this
        trainer's parameters functionalized into the scan carry:
        trainable parameters update via the optimizer each step,
        non-trainable parameters the forward mutates (BatchNorm
        running stats) ride the carry too, so their K-step evolution
        matches K eager steps exactly.  ``data``/``label`` stack the K
        batches on a leading step axis (a single array or a tuple of
        arrays, mirrored into loss_fn per step).  Returns the per-step
        loss values stacked on a leading K axis (ONE host readback reads
        them all).

        ``eval_metric`` (the gluon leg of the sync-free training loop):
        each step's ``(labels, loss)`` pair folds into the metric.  A
        device-capable metric (metric.EvalMetric.device_update — e.g.
        ``Loss``, ``MAE``) rides the scan carry: K steps of metric
        accumulation cost ZERO extra dispatches and readbacks, and the
        host only syncs when the metric is read (get_name_value) — on
        the fused AND eager drivers alike.  Metrics without a device
        form fold host-side: on the fused path from ONE stacked
        readback of the K losses; on the eager fallback per step (the
        eager driver is per-step in every respect).

        Per-step lr/wd schedules and update counts are precomputed
        host-side, exactly as K ``step()`` calls would advance them.
        dist_async update-on-kvstore runs the CHUNKED variant of the
        same scan — one dispatch per ``MXNET_KVSTORE_FUSED_CHUNK``
        steps, a local worker-side replica of the server update keeping
        the in-chunk trajectory fresh, and the grad-push/weight-pull
        wire overlapped behind the next chunk's compute
        (``MXNET_KVSTORE_FUSED_STALENESS``; the Module.run_steps dist
        driver's gluon twin — see its docstring for the staleness
        contract).  Falls back to the eager loop (autograd
        record/backward + step) for K=1, non-pure optimizers,
        ``MXNET_KVSTORE_FUSED=0`` (dist), or
        ``MXNET_EXEC_BULK_EXEC_TRAIN=0``.  Caveat: ops drawing from the
        global RNG (Dropout) freeze their trace-time draw — use the
        eager path (or Module.run_steps, whose interpreter threads keys
        explicitly) for stochastic-regularization training.
        """
        import jax.numpy as jnp
        data_t = tuple(d._data if hasattr(d, "_data") else jnp.asarray(d)
                       for d in (data if isinstance(data, (list, tuple))
                                 else (data,)))
        label_t = None
        if label is not None:
            label_t = tuple(
                l._data if hasattr(l, "_data") else jnp.asarray(l)
                for l in (label if isinstance(label, (list, tuple))
                          else (label,)))
        ks = {int(a.shape[0]) for a in data_t + (label_t or ())}
        if len(ks) != 1:
            raise MXNetError(f"step_k: inconsistent leading (step) dims "
                             f"{sorted(ks)}")
        inferred = ks.pop()
        if inferred == 0:
            raise MXNetError("step_k: inputs stack ZERO steps (empty "
                             "leading axis)")
        if k is None:
            k = inferred
        elif k != inferred:
            raise MXNetError(f"step_k: k={k} but inputs stack {inferred} "
                             "steps (leading dim)")
        if batch_size is None:
            batch_size = int(data_t[0].shape[1]) if data_t[0].ndim > 1 \
                else 1
        # rescale BEFORE the lazy kvstore init (same contract as step)
        self._optimizer.rescale_grad = self._scale / batch_size
        if not self._kv_initialized:
            self._init_kvstore()
        fusable = (k > 1
                   and env("MXNET_EXEC_BULK_EXEC_TRAIN", True)
                   and getattr(self._optimizer, "pure_update", False))
        if getattr(self, "_update_on_kvstore", False):
            # dist_async no longer falls back to eager: the chunked
            # driver scans fwd+bwd+local-update and overlaps the
            # grad-push/weight-pull wire behind the next chunk's
            # compute (the Module.run_steps dist driver's gluon twin).
            # Elastic jobs ride it too — an in-flight pull_async
            # handle replans against the post-bump stripe layout from
            # inside wait() (docs/ROBUSTNESS.md replan contract).
            if fusable and env("MXNET_KVSTORE_FUSED", True):
                self._ensure_kv_optimizer()
                return self._step_k_fused(loss_fn, data_t, label_t, k,
                                          eval_metric, dist=True)
            return self._step_k_eager(loss_fn, data_t, label_t, k,
                                      batch_size, eval_metric)
        if not fusable:
            return self._step_k_eager(loss_fn, data_t, label_t, k,
                                      batch_size, eval_metric)
        return self._step_k_fused(loss_fn, data_t, label_t, k, eval_metric)

    def _step_k_eager(self, loss_fn, data_t, label_t, k, batch_size,
                      eval_metric=None):
        """K eager steps: record → backward → step, one dispatch each
        (the universal fallback; same math as the scanned path)."""
        from .. import autograd as _ag
        from ..ndarray import NDArray
        import jax.numpy as jnp
        def _wrap(vals):
            nds = tuple(NDArray(v) for v in vals)
            return nds[0] if len(nds) == 1 else nds

        losses = []
        for j in range(k):
            args = [_wrap([a[j] for a in data_t])]
            if label_t is not None:
                args.append(_wrap([a[j] for a in label_t]))
            with _ag.record():
                loss = loss_fn(*args)
            loss.backward()
            self.step(batch_size)
            if eval_metric is not None:
                # device-resident when the metric supports it (no sync)
                labs = [NDArray(a[j]) for a in label_t] \
                    if label_t is not None else []
                eval_metric.accumulate(labs, [loss])
            losses.append(loss._data)
        return NDArray(jnp.stack(losses))

    def _step_k_fused(self, loss_fn, data_t, label_t, k,
                      eval_metric=None, dist=False):
        """``dist=True`` is the update-on-kvstore variant: the SAME
        scanned body (the local update doubles as the worker-side
        replica of the server's updater — both run
        ``Optimizer._update_impl``) additionally scans out the raw
        per-step gradients, and the dispatch runs chunked through
        ``executor.drive_chunked_dist`` with the push/pull wire
        overlapped behind the next chunk's compute.  Staleness
        semantics and the exactness contract are documented on
        ``Module._run_steps_fused_dist``."""
        from .. import autograd as _ag
        from .. import profiler as _prof
        from ..ndarray import NDArray
        import jax
        import jax.numpy as jnp
        opt = self._optimizer
        updater = self._updaters[0]
        # ZeRO-1 state sharding composes with the LOCAL fused driver
        # only — under update-on-kvstore the authoritative states live
        # on the servers and the local replica states stay replicated
        zero1 = self._zero_stage >= 1 and self._zero_dp > 1 and not dist
        deferred = [p.name for p in self._params
                    if p._deferred_init is not None]
        if deferred:
            # a deferred-init param materializing INSIDE the jit trace
            # would silently train nothing (it never joins the carry)
            # and leak tracers into the live Parameter — fail clearly
            raise MXNetError(
                "step_k: parameters pending deferred init "
                f"({deferred[:3]}...) — run one eager forward (e.g. "
                "net(first_batch)) to materialize shapes before step_k")
        trainable, idxs = [], []
        aux_params = []
        for i, param in enumerate(self._params):
            if param._data is None:
                continue
            if param.grad_req == 'null':
                # non-trainable but possibly MUTATED by the forward
                # (BatchNorm running stats): carried through the scan
                aux_params.append(param)
            else:
                trainable.append(param)
                idxs.append(i)
        for i, param in zip(idxs, trainable):
            if i not in updater.states:
                updater.states[i] = \
                    opt.create_state_multi_precision(i, param.data())
                updater.states_synced[i] = True
                if zero1:
                    self._zero_shard_state(updater.states[i])
        needs_t = getattr(opt, "needs_t", False)
        states = [opt._state_tuple(updater.states[i]) for i in idxs]
        use_mp = tuple(opt.mp_states_active(p.data(), st)
                       for p, st in zip(trainable, states))
        ws = tuple(p._data._data for p in trainable)
        auxs = tuple(p._data._data for p in aux_params)
        sts = tuple(tuple(s._data for s in st) for st in states)
        if zero1:
            self._zero_check_placed(
                [(i, p, None) for i, p in zip(idxs, trainable)], ws)
            from jax.sharding import PartitionSpec as _P
            param_specs = tuple(
                getattr(w.sharding, "spec", _P()) for w in ws)
        else:
            param_specs = None
        donate = bool(env("MXNET_FUSED_DONATE", True))
        # device-capable metrics ride the scan carry (zero extra
        # dispatches/readbacks for K steps of metric accumulation);
        # others fold host-side from the stacked losses below
        use_dev_metric = (eval_metric is not None
                          and getattr(eval_metric, "device_enabled",
                                      lambda: False)())
        # cache key: loss_fn by CODE + bound instance + closure-cell
        # identities, not object identity — the natural per-iteration
        # lambda (`tr.step_k(lambda x, y: loss(net(x), y), ...)`) is a
        # fresh object every call but shares its code and closes over
        # the same net/loss objects, so it must HIT (identity keying
        # would retrace + recompile the whole K-step program per call
        # and pin every stale closure).  __self__ joins the key because
        # bound methods of two instances share __code__ with an empty
        # closure; callables without __code__ fall back to identity.
        pins = (getattr(loss_fn, "__self__", None),) + tuple(
            c.cell_contents
            for c in (getattr(loss_fn, "__closure__", None) or ()))
        fn_key = (getattr(loss_fn, "__code__", loss_fn),
                  tuple(id(p) for p in pins))
        key = (fn_key, tuple(idxs), len(aux_params), use_mp, needs_t,
               opt.hyperparam_signature(), zero1, param_specs,
               label_t is None, donate, dist,
               eval_metric._device_sig() if use_dev_metric else None)
        cache = getattr(self, "_step_k_cache", None)
        if cache is None:
            cache = self._step_k_cache = {}
        from ..executor import scan_cache_lookup, scan_cache_store
        entry = scan_cache_lookup(cache, key)
        # the entry PINS the id()'d objects: without the strong refs, a
        # GC'd closure object's address could be reused by a NEW object
        # and false-hit a program traced against the old one
        fn = entry[0] if entry is not None else None
        if fn is None:
            all_params = trainable + aux_params
            metric = eval_metric if use_dev_metric else None

            def f_loss(ws_, auxs_, data_j, label_j):
                """Functionalized forward: park traced values in the
                live Parameters, run the user's loss_fn, harvest the
                (possibly updated) aux payloads, restore."""
                old = [(p._data._payload, p._data._thunk)
                       for p in all_params]
                try:
                    for p, w in zip(trainable, ws_):
                        p._data._set_data(w)
                    for p, a in zip(aux_params, auxs_):
                        p._data._set_data(a)
                    args = [NDArray(data_j[0]) if len(data_j) == 1
                            else tuple(NDArray(d) for d in data_j)]
                    if label_j is not None:
                        args.append(NDArray(label_j[0])
                                    if len(label_j) == 1 else
                                    tuple(NDArray(l) for l in label_j))
                    with _ag.train_mode():
                        loss = loss_fn(*args)
                    new_auxs = tuple(p._data._data for p in aux_params)
                    return loss._data, new_auxs
                finally:
                    for p, (payload, thunk) in zip(all_params, old):
                        p._data._payload = payload
                        p._data._thunk = thunk

            def scan_body(carry, x, const):
                ws_, auxs_, sts_, mstate = carry
                data_j, label_j, lrs, wds, ts = x

                loss_val, vjp_fn, new_auxs = jax.vjp(
                    lambda w: f_loss(w, auxs_, data_j, label_j),
                    ws_, has_aux=True)
                grads = vjp_fn(jnp.ones_like(loss_val))[0]
                new_ws, new_sts = opt.apply_fused(
                    ws_, grads, sts_, lrs, wds, use_mp,
                    ts=ts if needs_t else None)
                if zero1:
                    from jax.sharding import NamedSharding
                    from .. import parallel as _par
                    mesh = self._mesh
                    new_ws = tuple(
                        jax.lax.with_sharding_constraint(
                            w, NamedSharding(mesh, ps))
                        for w, ps in zip(new_ws, param_specs))
                    new_sts = _par.constrain_zero_states(
                        new_sts, mesh, self._zero_dp)
                if metric is not None:
                    # (labels, loss) fold into the device metric state —
                    # accumulation stays in the one scanned program
                    mstate = metric.device_update(
                        mstate,
                        list(label_j) if label_j is not None else [],
                        [loss_val])
                ys = (loss_val, grads) if dist else loss_val
                return (new_ws, new_auxs, new_sts, mstate), ys

            from ..executor import build_multi_step
            fn = build_multi_step(scan_body, donate=donate)
            scan_cache_store(cache, key, (fn, pins))

        # per-step lr/wd/t advance exactly as K step() calls would
        # (shared helper with Module.run_steps); rollback keeps the host
        # schedule transactional with the dispatch — a failed compile
        # must not leave counts K steps ahead of the params.  The dist
        # driver keys schedules by param NAME — the wire key the
        # SERVER's updater advances counts under — so the local replica
        # samples the same lr sequence the server does
        from ..executor import precompute_step_schedules, schedule_rollback
        sched_keys = [p.name for p in trainable] if dist else idxs
        with schedule_rollback(opt):
            lrs, wds, ts = precompute_step_schedules(opt, sched_keys, k)
            # _take (not peek), and only now that every pre-dispatch
            # step that can fail (the schedule precompute above) is
            # done: the carry is donated, so a failed DISPATCH must
            # leave the metric empty rather than holding deleted
            # buffers — but a failed precompute rolls back and must
            # not cost the pending interval
            init_m = eval_metric._take_device_state() if use_dev_metric \
                else ()

            def _writeback(ws_, auxs_, sts_):
                for p, w in zip(trainable, ws_):
                    p._data._set_data(w)
                for p, a in zip(aux_params, auxs_):
                    p._data._set_data(a)
                for st_old, st_new in zip(states, sts_):
                    for s, v in zip(st_old, st_new):
                        s._set_data(v)

            if dist:
                new_ws, new_auxs, new_sts, new_m, losses = \
                    self._drive_step_k_dist(fn, trainable, use_mp, ws,
                                            auxs, sts, init_m, data_t,
                                            label_t, lrs, wds, ts, k,
                                            _writeback)
            else:
                _prof.record_dispatch("step_k.dispatch")
                with _prof.scope("step_k_scan", "symbolic"):
                    (new_ws, new_auxs, new_sts, new_m), losses = fn(
                        (ws, auxs, sts, init_m),
                        (data_t, label_t, lrs, wds, ts), ())
        _writeback(new_ws, new_auxs, new_sts)
        if use_dev_metric:
            eval_metric._absorb_device_state(new_m)
        elif eval_metric is not None:
            # host fallback: ONE stacked readback for all K losses (and
            # labels), folded per step.  NDArray-wrapped like the eager
            # path — the same user metric must work on both drivers
            eval_metric._warn_host_fallback()
            # ONE blocking device_get for losses AND labels together —
            # two sequential gets would pay the tunnel round trip twice
            # while the sync counter reported one
            host_losses, host_labels = jax.device_get(
                (losses, label_t if label_t is not None else ()))
            if label_t is None:
                host_labels = None
            _prof.record_host_sync("step_k.metric_fold")
            for j in range(k):
                eval_metric.update(
                    [NDArray(a[j]) for a in host_labels]
                    if host_labels is not None else [],
                    [NDArray(host_losses[j])])
        return NDArray(losses)

    def _drive_step_k_dist(self, fn, trainable, use_mp, ws, auxs, sts,
                           init_m, data_t, label_t, lrs, wds, ts, k,
                           on_failure):
        """Chunked dispatch of the dist step_k scan: one compiled-scan
        launch and one grad-push/weight-pull wire round per chunk, the
        round overlapped behind the NEXT chunk's compute
        (executor.drive_chunked_dist; profiler.wire_wait_ms /
        wire_overlap_pct count the exposed vs hidden wire).  Returns
        ``(new_ws, new_auxs, new_sts, new_m, stacked_losses)`` with
        ``new_ws`` the FINAL pull's server-authoritative weights."""
        import jax
        import jax.numpy as jnp
        from .. import profiler as _prof
        from ..executor import drive_chunked_dist, fused_dist_knobs
        kv = self._kvstore
        names = [p.name for p in trainable]
        shapes = [tuple(p._data.shape) for p in trainable]
        dtypes = [p._data._data.dtype for p in trainable]
        # a deferred-init param that materialized after _init_kvstore
        # must register before its first push — same first-init-wins
        # late registration the eager _step_on_kvstore performs
        for p in trainable:
            if p.name not in self._kv_param_inited:
                kv.init(p.name, p.data())
                self._kv_param_inited.add(p.name)
        chunk, staleness = fused_dist_knobs(k)
        carry = {"ws": ws, "auxs": auxs, "sts": sts, "m": init_m,
                 "losses": []}

        def adopt(adopted):
            # chunk-boundary re-sync: weights adopt the pulled server
            # values — for a multi-precision param the fp32 MASTER in
            # states[0] adopts too (the update runs on it and recasts
            # the weight); the rest of the replica optimizer state and
            # aux stay local (the async-SGD-grade part of the contract)
            new_ws, new_sts = [], list(carry["sts"])
            for i, (n, dt) in enumerate(zip(names, dtypes)):
                w = jnp.asarray(adopted[n])
                if use_mp[i]:
                    master = w.astype(jnp.float32)
                    new_sts[i] = (master,) + tuple(new_sts[i][1:])
                    w = master.astype(dt)
                else:
                    w = w.astype(dt)
                new_ws.append(w)
            carry["ws"] = tuple(new_ws)
            carry["sts"] = tuple(new_sts)

        def dispatch_chunk(j, lo, hi, adopted):
            if adopted is not None:
                adopt(adopted)
            xs = (tuple(a[lo:hi] for a in data_t),
                  tuple(a[lo:hi] for a in label_t)
                  if label_t is not None else None,
                  tuple(v[lo:hi] for v in lrs),
                  tuple(v[lo:hi] for v in wds),
                  tuple(v[lo:hi] for v in ts))
            _prof.record_dispatch("step_k.dist_chunk")
            with _prof.scope("step_k_dist_chunk", "symbolic"):
                (nws, nauxs, nsts, nm), (losses, grads) = fn(
                    (carry["ws"], carry["auxs"], carry["sts"],
                     carry["m"]), xs, ())
            carry.update(ws=nws, auxs=nauxs, sts=nsts, m=nm)
            carry["losses"].append(losses)
            # ONE stacked readback of the chunk's raw per-step grads —
            # blocks on the chunk's COMPUTE; the wire round itself is
            # what the driver overlaps behind the next chunk
            grads_np = jax.device_get(grads)
            _prof.record_host_sync("step_k.dist_grad_readback")
            return grads_np

        def ship_chunk(j, grads_np):
            return kv.ship_chunk_steps(names, grads_np, shapes)

        try:
            final = drive_chunked_dist(k, chunk, staleness,
                                       dispatch_chunk, ship_chunk)
        except BaseException:
            # a wire failure mid-drive lands AFTER earlier chunks
            # donated the original param/aux/state buffers — the carry
            # holds the latest chunk's OUTPUT arrays (alive): park them
            # so the trainer's params stay readable at the last
            # locally-completed step
            on_failure(carry["ws"], carry["auxs"], carry["sts"])
            raise
        # the final pull is the sync point: trainable weights adopt the
        # server-authoritative values, fp32 masters included (exactly
        # like step()'s pull)
        adopt(final)
        losses = (jnp.concatenate(carry["losses"])
                  if len(carry["losses"]) > 1 else carry["losses"][0])
        return (carry["ws"], carry["auxs"], carry["sts"], carry["m"],
                losses)

    def allreduce_grads(self):
        """No-op on TPU: gradient reduction is fused into backward
        (GSPMD psum) — kept for API parity (reference: trainer.py
        allreduce_grads)."""

    def update(self, batch_size, ignore_stale_grad=False):
        self.step(batch_size, ignore_stale_grad)

    def save_states(self, fname):
        """reference: trainer.py save_states.  Under dist_async the
        optimizer states LIVE on the servers — fetch them from there
        (worker-side updater states would be an empty dict).  The store
        is created here if needed so a pre-first-step call routes
        correctly (resume-from-checkpoint pattern)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            if not self._kv_opt_sent:
                # THIS worker never stepped, but another worker may have
                # shipped the optimizer and trained — gather from the
                # servers if they answer; never ship the optimizer from
                # here (that would freeze rescale_grad=1.0 server-side)
                if self._kv_deferred_states is not None:
                    with open(fname, 'wb') as fout:
                        fout.write(self._kv_deferred_states)
                    return
                try:
                    self._kvstore.save_optimizer_states(fname)
                except MXNetError:
                    # fresh cluster, no optimizer anywhere: no states
                    # exist yet — write an empty state dict
                    with open(fname, 'wb') as fout:
                        fout.write(self._updaters[0].get_states())
                return
            self._kvstore.save_optimizer_states(fname)
            return
        with open(fname, 'wb') as fout:
            fout.write(self._updaters[0].get_states())

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            if not self._kv_opt_sent:
                # if another worker already installed the server-side
                # optimizer, apply NOW (deferring would rewind their
                # later progress at this worker's first step); on a
                # fresh cluster buffer until the first step() ships the
                # optimizer with the REAL rescale_grad
                try:
                    self._kvstore.load_optimizer_states(fname)
                    # rank 0's first step RE-SHIPS the optimizer, which
                    # replaces the server updater and wipes the states
                    # just applied — keep the blob so the ship replays
                    # it (tracked separately from _kv_deferred_states:
                    # a pre-step save_states must keep returning the
                    # LIVE server states, which other workers may have
                    # advanced past this blob)
                    with open(fname, 'rb') as fin:
                        self._kv_replay_states = fin.read()
                except MXNetError:
                    with open(fname, 'rb') as fin:
                        self._kv_deferred_states = fin.read()
                return
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, 'rb') as fin:
            self._updaters[0].set_states(fin.read())
        if self._zero_stage >= 1 and self._zero_dp > 1:
            # restored buffers land unsharded — re-apply ZeRO placement
            # now, not at the first step, to avoid the O(P) peak
            for st in self._updaters[0].states.values():
                self._zero_shard_state(st)
