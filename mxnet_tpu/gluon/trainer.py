"""Gluon Trainer (reference: python/mxnet/gluon/trainer.py:27).

The reference's step() pushes gradients to a kvstore (allreduce across
devices) and pulls updated weights (trainer.py:148).  Here a parameter is
ONE logical array (possibly mesh-sharded), so `step` = run the optimizer
update on each param's gradient; cross-chip gradient reduction already
happened inside the backward program (GSPMD psum).  The kvstore argument is
accepted for API parity and drives update_on_kvstore semantics.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, env
from .. import optimizer as opt_mod
from .. import kvstore as kvs_mod
from .parameter import ParameterDict, Parameter


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore='device', compression_params=None,
                 mesh=None, zero_stage=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}")
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(f"not a Parameter: {param!r}")
            param._trainer = self
            self._params.append(param)
        self._scale = 1.0
        # ZeRO-1 over the dp mesh axis — same contract as
        # Module(zero_stage=1) (docs/design/kvstore.md): optimizer states
        # (+ fp32 masters) live dp-sharded; GSPMD schedules the
        # reduce-scatter/all-gather inside the fused update.
        from .. import parallel as _par
        if mesh is None:
            mesh = _par.current_mesh()
        self._mesh = mesh
        explicit_zero = zero_stage is not None
        if zero_stage is None:
            zero_stage = env("MXNET_ZERO_STAGE", 0)
        if zero_stage not in (0, 1):
            raise ValueError("zero_stage must be 0 or 1")
        if explicit_zero and zero_stage >= 1 and mesh is None:
            raise MXNetError(
                "zero_stage=1 needs a device mesh with dp>1 — pass "
                "mesh= (parallel.make_mesh) or enter a use_mesh scope")
        self._zero_stage = int(zero_stage)
        self._zero_dp = (_par.mesh_shape(mesh).get("dp", 1)
                         if mesh is not None else 1)
        if not explicit_zero and zero_stage >= 1 and self._zero_dp <= 1:
            # mirror Module's warning: env-enabled ZeRO without a dp>1
            # mesh silently leaves optimizer states replicated
            logging.warning(
                "MXNET_ZERO_STAGE=1 ignored: no device mesh with dp>1 "
                "on this Trainer — optimizer states will be fully "
                "replicated")
        optimizer_params = dict(optimizer_params or {})
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_type = kvstore
        self._kvstore = None
        self._kv_initialized = False

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
        else:
            self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer.param_dict = param_dict
        self._updaters = [opt_mod.get_updater(self._optimizer)]

    def _init_kvstore(self):
        """reference: trainer.py:102 — create the store lazily at first
        step; on TPU it is a facade over in-program collectives, EXCEPT
        dist_async where the kvstore path IS the mechanism: the optimizer
        runs server-side and step() becomes push-grad/pull-weight
        (reference trainer.py:148 update-on-kvstore)."""
        if self._kv_type:
            self._kvstore = kvs_mod.create(self._kv_type) \
                if isinstance(self._kv_type, str) else self._kv_type
        self._update_on_kvstore = (
            self._kvstore is not None
            and getattr(self._kvstore, "type", "") == "dist_async")
        if self._update_on_kvstore:
            # the server applies updates with the optimizer AS PICKLED
            # here — step() sets rescale_grad before first use so it
            # rides along (the reference's server shares this pickle-time
            # snapshot semantics, kvstore.py:353)
            self._kvstore.set_optimizer(self._optimizer)
            self._kv_opt_snapshot = (self._optimizer.lr,
                                     self._optimizer.rescale_grad)
            self._kv_param_inited = set()
            # ALL materialized params — including frozen (grad_req
            # 'null') ones — sync to the server-authoritative value, so
            # every worker trains against the same frozen weights
            # (reference: _initialize_kvstore registers every param)
            inited = [p for p in self._params if p._data is not None]
            for param in inited:
                self._kvstore.init(param.name, param.data())
                self._kv_param_inited.add(param.name)
            # pull the AUTHORITATIVE weights back: the server kept the
            # first-arriving worker's init, and every worker must start
            # from that same point (reference: model.py:96
            # _initialize_kvstore pulls after init)
            if inited:
                self._kvstore.pull([p.name for p in inited],
                                   out=[p.data() for p in inited])
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """reference: trainer.py:148.

        Dense-gradient params with a pure-jax optimizer go through ONE
        jitted update over all of them (the gluon analog of Module's fused
        step — N per-param eager dispatches per step would each be a
        device round-trip on a remote-attached chip).  Sparse-gradient
        params and non-pure optimizers keep the per-param eager path.
        """
        # rescale BEFORE the lazy kvstore init: dist_async pickles the
        # optimizer to the servers at init and applies THERE
        self._optimizer.rescale_grad = self._scale / batch_size
        if not self._kv_initialized:
            self._init_kvstore()
        if getattr(self, "_update_on_kvstore", False):
            return self._step_on_kvstore(ignore_stale_grad)
        updater = self._updaters[0]
        from ..ndarray.sparse import RowSparseNDArray
        fuse = (env("MXNET_EXEC_BULK_EXEC_TRAIN", True)
                and getattr(self._optimizer, "pure_update", False))
        fused_batch = []
        for i, param in enumerate(self._params):
            if param.grad_req == 'null':
                continue
            if param._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        f"Parameter {param.name!r} was not initialized")
                continue
            grad = param.grad()
            if fuse and not isinstance(grad, RowSparseNDArray):
                fused_batch.append((i, param, grad))
            else:
                if self._zero_stage >= 1 and self._zero_dp > 1 \
                        and not getattr(self, "_zero_eager_warned", False):
                    self._zero_eager_warned = True
                    import warnings
                    warnings.warn(
                        "zero_stage=1 requested but parameter "
                        f"{param.name!r} updates on the eager path "
                        "(sparse grad, non-pure optimizer, or bulk-exec "
                        "disabled) — its optimizer state is NOT sharded",
                        stacklevel=2)
                updater(i, grad, param.data())
        if fused_batch:
            self._fused_update(fused_batch, updater)

    def _step_on_kvstore(self, ignore_stale_grad):
        """Async-PS step: push every grad (fire-and-forget, overlapping),
        then ONE batched pull of the server's current weights back
        (reference: trainer.py:148 _update update-on-kvstore branch;
        pipelined pull = ~max-RTT, not N round trips).  Per-server FIFO
        guarantees each pull observes this worker's own pushes."""
        snap = (self._optimizer.lr, self._optimizer.rescale_grad)
        if snap != self._kv_opt_snapshot \
                and not getattr(self, "_kv_opt_drift_warned", False):
            self._kv_opt_drift_warned = True
            import warnings
            warnings.warn(
                "optimizer hyperparameters changed after the first "
                "dist_async step (lr/rescale_grad %s -> %s) — the SERVER "
                "keeps applying its pickle-time snapshot (re-sending the "
                "optimizer would reset server-side momentum state); "
                "restart training to change hyperparameters, as with the "
                "reference's server-side optimizer" %
                (self._kv_opt_snapshot, snap), stacklevel=3)
        live = []
        for param in self._params:
            if param.grad_req == 'null':
                continue
            if param._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        f"Parameter {param.name!r} was not initialized")
                continue
            if param.name not in self._kv_param_inited:
                # deferred-init param materialized after the first step:
                # register it before its first push (first-init-wins
                # makes a late init safe under concurrent workers)
                self._kvstore.init(param.name, param.data())
                self._kv_param_inited.add(param.name)
            self._kvstore.push(param.name, param.grad())
            live.append(param)
        if live:
            self._kvstore.pull([p.name for p in live],
                               out=[p.data() for p in live])

    def _zero_pspec(self, arr):
        """Delegates to the shared rule in parallel.sharding (one source
        of truth with Module)."""
        from .. import parallel as _par
        return _par.zero_pspec(arr, self._zero_dp)

    def _zero_shard_state(self, state):
        import jax
        from jax.sharding import NamedSharding
        for s in self._optimizer._state_tuple(state):
            if s is None:
                continue
            s._set_data(jax.device_put(
                s._data, NamedSharding(self._mesh, self._zero_pspec(s))))

    def _zero_check_placed(self, batch, ws):
        """ZeRO-1 shards states onto the mesh, so the params must already
        live there (net.collect_params().place(mesh)) — otherwise jit
        fails with an opaque 'incompatible devices' error; fail clearly
        instead."""
        for (_i, p, _g), w in zip(batch, ws):
            if getattr(getattr(w, "sharding", None), "mesh", None) \
                    != self._mesh:
                raise MXNetError(
                    f"zero_stage=1: parameter {p.name!r} is not placed "
                    "on the trainer's mesh — call "
                    "net.collect_params().place(mesh) (and dp-shard the "
                    "input batch) before training")

    def _fused_update(self, batch, updater):
        """Apply the optimizer to every (dense) param in ONE jit call
        (the per-param dispatch lives in Optimizer.apply_fused, shared
        with Module's fused step).

        Shares per-param state with the eager Updater (same dict), so
        save_states/load_states and mixing eager/sparse updates stay
        coherent.  The jit cache is a dict keyed by (param set, mp
        layout, optimizer hyperparameter signature): changing e.g.
        momentum or rescale_grad mid-run retraces, and alternating keys
        (a smaller final batch) each compile once.
        """
        opt = self._optimizer
        zero1 = self._zero_stage >= 1 and self._zero_dp > 1
        for i, param, _g in batch:
            if i not in updater.states:
                updater.states[i] = \
                    opt.create_state_multi_precision(i, param.data())
                updater.states_synced[i] = True
                if zero1:
                    self._zero_shard_state(updater.states[i])
            opt._update_count(i)
        needs_t = getattr(opt, "needs_t", False)
        states = [opt._state_tuple(updater.states[i]) for i, _p, _g in batch]
        use_mp = tuple(opt.mp_states_active(p.data(), st)
                       for (_i, p, _g), st in zip(batch, states))
        ws = tuple(p._data._data for _i, p, _g in batch)
        gs = tuple(g._data for _i, _p, g in batch)
        sts = tuple(tuple(s._data for s in st) for st in states)
        if zero1:
            self._zero_check_placed(batch, ws)
            # params keep their CURRENT sharding (captured from the live
            # arrays — gluon has no rules engine; replicated unless the
            # user sharded them), states stay dp-sharded.  The specs join
            # the cache key so a placement change retraces the constraint.
            from jax.sharding import PartitionSpec as _P
            param_specs = tuple(
                getattr(w.sharding, "spec", _P()) for w in ws)
        else:
            param_specs = None
        key = (tuple(i for i, _p, _g in batch), use_mp, needs_t,
               opt.hyperparam_signature(), zero1, param_specs)
        cache = getattr(self, "_fused_cache", None)
        if cache is None:
            cache = self._fused_cache = {}
        fn = cache.get(key)
        if fn is None:
            def fused(ws, gs, sts, lrs, wds, ts):
                new_ws, new_sts = opt.apply_fused(
                    ws, gs, sts, lrs, wds, use_mp,
                    ts=ts if needs_t else None)
                if zero1:
                    from jax.sharding import NamedSharding
                    from .. import parallel as _par
                    mesh = self._mesh
                    new_ws = tuple(
                        jax.lax.with_sharding_constraint(
                            w, NamedSharding(mesh, ps))
                        for w, ps in zip(new_ws, param_specs))
                    new_sts = _par.constrain_zero_states(
                        new_sts, mesh, self._zero_dp)
                return new_ws, new_sts

            fn = cache[key] = jax.jit(fused)
        # cache lr/wd device scalars while unchanged (per-step host→device
        # scalar transfers would reintroduce the round-trips this path
        # removes — same discipline as Module._lrwd_cache)
        lrs = tuple(np.float32(opt._get_lr(i)) for i, _p, _g in batch)
        wds = tuple(np.float32(opt._get_wd(i)) for i, _p, _g in batch)
        lw_cache = getattr(self, "_lrwd_cache", None)
        if lw_cache is not None and lw_cache[0] == (lrs, wds):
            lrs, wds = lw_cache[1]
        else:
            key_ = (lrs, wds)
            lrs = tuple(jnp.asarray(v) for v in lrs)
            wds = tuple(jnp.asarray(v) for v in wds)
            self._lrwd_cache = (key_, (lrs, wds))
        if needs_t:
            # per-param bias-correction counts (a frozen/unfrozen param's
            # count differs — matching the eager path exactly)
            ts = tuple(jnp.asarray(opt._index_update_count[i], jnp.int32)
                       for i, _p, _g in batch)
        else:
            ts = getattr(self, "_t_zeros", None)
            if ts is None or len(ts) != len(batch):
                ts = self._t_zeros = tuple(
                    jnp.asarray(0, jnp.int32) for _ in batch)
        new_ws, new_sts = fn(ws, gs, sts, lrs, wds, ts)
        for (_i, p, _g), w, st_old, st_new in zip(batch, new_ws, states,
                                                  new_sts):
            p._data._set_data(w)
            for s, v in zip(st_old, st_new):
                s._set_data(v)

    def allreduce_grads(self):
        """No-op on TPU: gradient reduction is fused into backward
        (GSPMD psum) — kept for API parity (reference: trainer.py
        allreduce_grads)."""

    def update(self, batch_size, ignore_stale_grad=False):
        self.step(batch_size, ignore_stale_grad)

    def save_states(self, fname):
        """reference: trainer.py save_states.  Under dist_async the
        optimizer states LIVE on the servers — fetch them from there
        (worker-side updater states would be an empty dict).  The store
        is created here if needed so a pre-first-step call routes
        correctly (resume-from-checkpoint pattern)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
            return
        with open(fname, 'wb') as fout:
            fout.write(self._updaters[0].get_states())

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, 'rb') as fin:
            self._updaters[0].set_states(fin.read())
        if self._zero_stage >= 1 and self._zero_dp > 1:
            # restored buffers land unsharded — re-apply ZeRO placement
            # now, not at the first step, to avoid the O(P) peak
            for st in self._updaters[0].states.values():
                self._zero_shard_state(st)
