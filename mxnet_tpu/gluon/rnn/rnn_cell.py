"""Gluon recurrent cells (reference: python/mxnet/gluon/rnn/rnn_cell.py).

Cells are HybridBlocks: one graph node set per step, composed by
``unroll``; under ``hybridize()`` the unrolled loop compiles to one XLA
program whose per-step matmuls XLA schedules back-to-back on the MXU.

Compatibility contract, deliberately preserved from the reference API:
parameter names (``i2h_weight`` …), gate order ([i, f, c, o] for LSTM,
[r, z, o] for GRU), state_info layouts, and cell aliases — these make
reference checkpoints load into gluon models unchanged.  Within that
contract the cell bodies share ``_fc_pair`` (both per-step projections,
all gates batched into one matmul) and the ``_lstm_step``/``_gru_step``
recurrences.
"""
from __future__ import annotations

from ..block import Block, HybridBlock
from ...base import MXNetError


def _fc_pair(F, inputs, prev_h, n_units, i2h_weight, h2h_weight,
             i2h_bias, h2h_bias):
    """Both per-step projections with every gate batched into one matmul
    each — the shape all cells share."""
    i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                           num_hidden=n_units)
    h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                           num_hidden=n_units)
    return i2h, h2h


def _lstm_step(F, gates, prev_c):
    """LSTM recurrence over summed pre-activation gates; order
    [i, f, c, o] is the checkpoint/fused-op contract."""
    sl = list(F.SliceChannel(gates, num_outputs=4))
    in_gate = F.Activation(sl[0], act_type='sigmoid')
    forget_gate = F.Activation(sl[1], act_type='sigmoid')
    in_transform = F.Activation(sl[2], act_type='tanh')
    out_gate = F.Activation(sl[3], act_type='sigmoid')
    next_c = forget_gate * prev_c + in_gate * in_transform
    next_h = out_gate * F.Activation(next_c, act_type='tanh')
    return next_h, next_c


def _gru_step(F, i2h, h2h, prev_h):
    """GRU recurrence over the two projection outputs; order [r, z, o],
    candidate mixes the reset-gated recurrent slice."""
    i2h_r, i2h_z, i2h_o = list(F.SliceChannel(i2h, num_outputs=3))
    h2h_r, h2h_z, h2h_o = list(F.SliceChannel(h2h, num_outputs=3))
    reset_gate = F.Activation(i2h_r + h2h_r, act_type='sigmoid')
    update_gate = F.Activation(i2h_z + h2h_z, act_type='sigmoid')
    next_h_tmp = F.Activation(i2h_o + reset_gate * h2h_o,
                              act_type='tanh')
    return update_gate * prev_h + (1. - update_gate) * next_h_tmp


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _get_begin_state(cell, F, begin_state, inputs, batch_size):
    if begin_state is None:
        from ... import ndarray as nd_mod
        if F is nd_mod:
            ctx = inputs.context if hasattr(inputs, 'context') else None
            begin_state = cell.begin_state(batch_size=batch_size,
                                           func=nd_mod.zeros)
        else:
            begin_state = cell.begin_state(func=F.zeros)
    return begin_state


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    """reference: gluon/rnn/rnn_cell.py:38."""
    from ... import ndarray as nd_mod
    from ... import symbol as sym_mod
    from ...ndarray import NDArray
    assert inputs is not None
    axis = layout.find('T')
    batch_axis = layout.find('N')
    batch_size = 0
    in_axis = in_layout.find('T') if in_layout is not None else axis
    if isinstance(inputs, sym_mod.Symbol):
        F = sym_mod
        if merge is False:
            if len(inputs.list_outputs()) != 1:
                raise MXNetError(
                    "unroll doesn't allow grouped symbol as input.")
            inputs = list(sym_mod.SliceChannel(
                inputs, axis=in_axis, num_outputs=length, squeeze_axis=1))
    elif isinstance(inputs, NDArray):
        F = nd_mod
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            inputs = [inputs[(slice(None),) * in_axis + (i,)]
                      for i in range(inputs.shape[in_axis])]
    else:
        assert length is None or len(inputs) == length
        if isinstance(inputs[0], sym_mod.Symbol):
            F = sym_mod
        else:
            F = nd_mod
            batch_size = inputs[0].shape[batch_axis - 1 if batch_axis > axis
                                         else batch_axis]
        if merge is True:
            inputs = [F.expand_dims(i, axis=axis) for i in inputs]
            inputs = F.Concat(*inputs, dim=axis)
            in_axis = axis
    if hasattr(inputs, 'list_outputs') or hasattr(inputs, 'shape'):
        if axis != in_axis:
            inputs = F.SwapAxis(inputs, dim1=axis, dim2=in_axis)
    return inputs, axis, F, batch_size


class RecurrentCell(Block):
    """Base recurrent cell (reference: gluon/rnn/rnn_cell.py:81)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children:
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """reference: gluon/rnn/rnn_cell.py:118."""
        assert not self._modified
        if func is None:
            from ... import ndarray as nd_mod
            func = nd_mod.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info = dict(info, **kwargs)
            else:
                info = kwargs
            info.pop('__layout__', None)
            shape = info.pop('shape')
            shape = tuple(1 if s == 0 else s for s in shape)
            state = func(shape=shape,
                         name=f'{self._prefix}begin_state_'
                              f'{self._init_counter}', **info)
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        """reference: gluon/rnn/rnn_cell.py:158."""
        self.reset()
        inputs, axis, F, batch_size = _format_sequence(
            length, inputs, layout, False)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _, _, _ = _format_sequence(length, outputs, layout,
                                            merge_outputs)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """reference: gluon/rnn/rnn_cell.py:219."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        from ...ndarray import NDArray
        if isinstance(inputs, NDArray):
            from ... import ndarray as nd_mod
            pdata = {}
            for n, p in self._reg_params.items():
                if p._deferred_init is not None:
                    p._finish_deferred_init(
                        self._infer_param_shape(n, inputs))
                pdata[n] = p.data()
            return self.hybrid_forward(nd_mod, inputs, states, **pdata)
        from ... import symbol as sym_mod
        pvars = {n: p.var() for n, p in self._reg_params.items()}
        return self.hybrid_forward(sym_mod, inputs, states, **pvars)

    def _infer_param_shape(self, name, inputs):
        ng = self._gates if hasattr(self, '_gates') else 1
        nh = self._hidden_size
        if 'i2h_weight' in name:
            return (ng * nh, inputs.shape[1])
        if 'h2h_weight' in name:
            return (ng * nh, nh)
        return (ng * nh,)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """reference: gluon/rnn/rnn_cell.py:232."""

    def __init__(self, hidden_size, activation='tanh',
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self._gates = 1
        self.i2h_weight = self.params.get(
            'i2h_weight', shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            'h2h_weight', shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            'i2h_bias', shape=(hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            'h2h_bias', shape=(hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size),
                 '__layout__': 'NC'}]

    def _alias(self):
        return 'rnn'

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = _fc_pair(F, inputs, states[0], self._hidden_size,
                            i2h_weight, h2h_weight, i2h_bias, h2h_bias)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """reference: gluon/rnn/rnn_cell.py:310."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._gates = 4
        self.i2h_weight = self.params.get(
            'i2h_weight', shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            'h2h_weight', shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            'i2h_bias', shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            'h2h_bias', shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size),
                 '__layout__': 'NC'},
                {'shape': (batch_size, self._hidden_size),
                 '__layout__': 'NC'}]

    def _alias(self):
        return 'lstm'

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = _fc_pair(F, inputs, states[0], 4 * self._hidden_size,
                            i2h_weight, h2h_weight, i2h_bias, h2h_bias)
        next_h, next_c = _lstm_step(F, i2h + h2h, states[1])
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """reference: gluon/rnn/rnn_cell.py:426."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._gates = 3
        self.i2h_weight = self.params.get(
            'i2h_weight', shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            'h2h_weight', shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            'i2h_bias', shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            'h2h_bias', shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size),
                 '__layout__': 'NC'}]

    def _alias(self):
        return 'gru'

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = _fc_pair(F, inputs, states[0], 3 * self._hidden_size,
                            i2h_weight, h2h_weight, i2h_bias, h2h_bias)
        next_h = _gru_step(F, i2h, h2h, states[0])
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """reference: gluon/rnn/rnn_cell.py:540."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)
        super(Block, self).__setattr__(
            f'_cell{len(self._children)-1}', cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def __call__(self, inputs, states):
        return self.forward(inputs, states)

    def forward(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children:
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        num_cells = len(self._children)
        _, _, F, batch_size = _format_sequence(length, inputs, layout,
                                               None)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        p = 0
        next_states = []
        for i, cell in enumerate(self._children):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return self._children[i]

    def __len__(self):
        return len(self._children)


class DropoutCell(HybridRecurrentCell):
    """reference: gluon/rnn/rnn_cell.py:610."""

    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix, params)
        assert isinstance(rate, float)
        self.rate = rate

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return 'dropout'

    def hybrid_forward(self, F, inputs, states):
        if self.rate > 0:
            inputs = F.Dropout(inputs, p=self.rate)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        inputs, _, F, _ = _format_sequence(length, inputs, layout,
                                           merge_outputs)
        if hasattr(inputs, 'shape') or hasattr(inputs, 'list_outputs'):
            return self.hybrid_forward(F, inputs, [])
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs)


class ModifierCell(HybridRecurrentCell):
    """reference: gluon/rnn/rnn_cell.py:659."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified." % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """reference: gluon/rnn/rnn_cell.py:711."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. " \
            "Please add ZoneoutCell to the cells underneath instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def _alias(self):
        return 'zoneout'

    def reset(self):
        super().reset()
        self.prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return F.Dropout(F.ones_like(like), p=p)
        prev_output = self.prev_output if self.prev_output is not None \
            else F.zeros_like(next_output)
        output = F.where(mask(p_outputs, next_output), next_output,
                         prev_output) if p_outputs != 0. else next_output
        new_states = [F.where(mask(p_states, new_s), new_s, old_s)
                      for new_s, old_s in zip(next_states, states)] \
            if p_states != 0. else next_states
        self.prev_output = output
        return output, new_states

    def forward(self, inputs, states):
        self._counter += 1
        from ...ndarray import NDArray
        if isinstance(inputs, NDArray):
            from ... import ndarray as nd_mod
            return self.hybrid_forward(nd_mod, inputs, states)
        from ... import symbol as sym_mod
        return self.hybrid_forward(sym_mod, inputs, states)


class ResidualCell(ModifierCell):
    """reference: gluon/rnn/rnn_cell.py:770."""

    def _alias(self):
        return 'residual'

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def forward(self, inputs, states):
        self._counter += 1
        from ...ndarray import NDArray
        if isinstance(inputs, NDArray):
            from ... import ndarray as nd_mod
            return self.hybrid_forward(nd_mod, inputs, states)
        from ... import symbol as sym_mod
        return self.hybrid_forward(sym_mod, inputs, states)

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        from ... import symbol as sym_mod
        merge_outputs = isinstance(outputs, sym_mod.Symbol) or \
            hasattr(outputs, 'shape') if merge_outputs is None \
            else merge_outputs
        inputs, _, F, _ = _format_sequence(length, inputs, layout,
                                           merge_outputs)
        if merge_outputs:
            outputs = outputs + inputs
        else:
            outputs = [out + inp for out, inp in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """reference: gluon/rnn/rnn_cell.py:830."""

    def __init__(self, l_cell, r_cell, output_prefix='bi_'):
        super().__init__(prefix='', params=None)
        self.register_child(l_cell)
        self.register_child(r_cell)
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise MXNetError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        inputs, axis, F, batch_size = _format_sequence(
            length, inputs, layout, False)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        states = begin_state
        l_cell, r_cell = self._children
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False)
        outputs = [F.Concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, reversed(r_outputs))]
        if merge_outputs:
            outputs, _, _, _ = _format_sequence(length, outputs, layout,
                                                merge_outputs)
        states = l_states + r_states
        return outputs, states
