"""Gluon fused RNN layers (reference: python/mxnet/gluon/rnn/rnn_layer.py).

RNN/LSTM/GRU HybridBlocks emitting the single fused `RNN` op
(ops/rnn.py — lax.scan over MXU-mapped gate matmuls, the cuDNN-kernel
replacement).  Weights are kept per-layer/direction/gate as separate
Parameters (the reference's i2h/h2h naming) and packed into the op's flat
vector at call time.
"""
from __future__ import annotations

import numpy as np

from ..block import HybridBlock
from ...base import MXNetError


class _RNNLayer(HybridBlock):
    """reference: rnn_layer.py:33."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ('TNC', 'NTC'), \
            f"Invalid layout {layout}; must be one of ['TNC' or 'NTC']"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer

        self._gates = {'rnn_relu': 1, 'rnn_tanh': 1, 'lstm': 4,
                       'gru': 3}[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in (['l', 'r'] if self._dir == 2 else ['l']):
                self._register_param(
                    f'{j}{i}_i2h_weight', shape=(ng * nh, ni),
                    init=i2h_weight_initializer)
                self._register_param(
                    f'{j}{i}_h2h_weight', shape=(ng * nh, nh),
                    init=h2h_weight_initializer)
                self._register_param(
                    f'{j}{i}_i2h_bias', shape=(ng * nh,),
                    init=i2h_bias_initializer)
                self._register_param(
                    f'{j}{i}_h2h_bias', shape=(ng * nh,),
                    init=h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)

    def __repr__(self):
        s = '{name}({mapping}, {_layout}'
        if self._num_layers != 1:
            s += ', num_layers={_num_layers}'
        if self._dropout != 0:
            s += ', dropout={_dropout}'
        if self._dir == 2:
            s += ', bidirectional'
        s += ')'
        mapping = f'{self._input_size or None} -> {self._hidden_size}'
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent state (reference: rnn_layer.py:147)."""
        from ... import ndarray as nd_mod
        if func is None:
            func = nd_mod.zeros
        states = []
        for info in self.state_info(batch_size):
            if info is not None:
                info = dict(info, **kwargs)
            else:
                info = kwargs
            info.pop('__layout__', None)
            states.append(func(shape=info.pop('shape'), **info))
        return states

    def forward(self, inputs, states=None):
        """Finish deferred weight init from the eager input's feature dim
        (the packing Concat defeats graph back-fill — reference
        rnn_layer.py similarly resolves input_size in forward), then
        dispatch; states are flattened into positional args for the
        HybridBlock cache."""
        from ...ndarray import NDArray
        if isinstance(inputs, NDArray):
            self._finish_deferred(inputs.shape)
        if states is None:
            return super().forward(inputs)
        if not isinstance(states, (list, tuple)):
            states = [states]
        out = super().forward(inputs, *states)
        # (output, h[, c]) comes back flattened from the graph
        if isinstance(out, (list, tuple)):
            return out[0], list(out[1:])
        return out

    def _finish_deferred(self, in_shape):
        ni = in_shape[2]  # feature dim is last in both TNC and NTC
        ng, nh = self._gates, self._hidden_size
        dirs = ['l', 'r'] if self._dir == 2 else ['l']
        for i in range(self._num_layers):
            for j in dirs:
                for suffix, shape in (
                        ('i2h_weight', (ng * nh, ni)),
                        ('h2h_weight', (ng * nh, nh)),
                        ('i2h_bias', (ng * nh,)),
                        ('h2h_bias', (ng * nh,))):
                    p = getattr(self, f'{j}{i}_{suffix}')
                    if p._deferred_init is not None:
                        p._finish_deferred_init(shape)
                    elif p.shape and any(s == 0 for s in p.shape):
                        p.shape = shape
            ni = nh * self._dir

    def hybrid_forward(self, F, inputs, *states, **params):
        """Emit the fused RNN op; returns output [or output + states]."""
        states = [s for s in states if s is not None]
        skip_states = not states

        # pack per-gate params into the flat vector the op consumes
        parameters = self._pack(F, params)

        if self._layout == 'NTC':
            inputs = F.SwapAxis(inputs, dim1=0, dim2=1)
        if skip_states:
            b = self._num_layers * self._dir
            H = self._hidden_size
            state_args = {'state': F.zeros((b, 1, H))}
            if self._mode == 'lstm':
                state_args['state_cell'] = F.zeros((b, 1, H))
        else:
            state_args = {'state': states[0]}
            if self._mode == 'lstm':
                state_args['state_cell'] = states[1]
        rnn = F.RNN(inputs, parameters, state_size=self._hidden_size,
                    num_layers=self._num_layers,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=not skip_states, mode=self._mode,
                    **state_args)
        if skip_states:
            outputs = rnn if not isinstance(rnn, (list, tuple)) else rnn[0]
            out_states = []
        else:
            outs = list(rnn)
            outputs = outs[0]
            out_states = outs[1:]
        if self._layout == 'NTC':
            outputs = F.SwapAxis(outputs, dim1=0, dim2=1)
        if skip_states:
            return outputs
        return tuple([outputs] + list(out_states))

    def _pack(self, F, params):
        """Concatenate i2h/h2h weights+biases into the cuDNN-layout flat
        vector (ops/rnn.py header)."""
        dirs = ['l', 'r'] if self._dir == 2 else ['l']
        chunks = []
        for i in range(self._num_layers):
            for j in dirs:
                chunks.append(F.Reshape(
                    params[f'{j}{i}_i2h_weight'], shape=(-1,)))
                chunks.append(F.Reshape(
                    params[f'{j}{i}_h2h_weight'], shape=(-1,)))
        for i in range(self._num_layers):
            for j in dirs:
                chunks.append(params[f'{j}{i}_i2h_bias'])
                chunks.append(params[f'{j}{i}_h2h_bias'])
        return F.Concat(*chunks, dim=0)


class RNN(_RNNLayer):
    """Vanilla multi-layer RNN (reference: rnn_layer.py:190)."""

    def __init__(self, hidden_size, num_layers=1, activation='relu',
                 layout='TNC', dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         'rnn_' + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference: rnn_layer.py:284)."""

    def __init__(self, hidden_size, num_layers=1, layout='TNC', dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         'lstm', **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'},
                {'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]


class GRU(_RNNLayer):
    """Multi-layer GRU (reference: rnn_layer.py:388)."""

    def __init__(self, hidden_size, num_layers=1, layout='TNC', dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         'gru', **kwargs)

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size), '__layout__': 'LNC'}]
